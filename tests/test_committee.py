"""Tests for committee thresholds, election, aggregation — mirrors
committee.rs:530-553 plus wider coverage of the fast-path certification engine."""
import pytest

from mysticeti_tpu.committee import (
    Committee,
    QUORUM,
    StakeAggregator,
    TransactionAggregator,
    VALIDITY,
    VoteRangeBuilder,
    shared_ranges,
)
from mysticeti_tpu.types import (
    Share,
    StatementBlock,
    TransactionLocator,
    TransactionLocatorRange,
    Vote,
    VoteRange,
)


class TestThresholds:
    def test_quorum_validity(self):
        c = Committee.new_test([1, 1, 1, 1])
        assert c.total_stake == 4
        assert c.quorum_threshold() == 3  # > 2/3 of 4
        assert c.validity_threshold() == 2  # > 1/3 of 4
        assert not c.is_quorum(2)
        assert c.is_quorum(3)
        assert not c.is_valid(1)
        assert c.is_valid(2)

    def test_uneven_stake(self):
        c = Committee.new_test([100, 200, 300, 400])
        assert c.total_stake == 1000
        assert c.is_quorum(667)
        assert not c.is_quorum(666)
        assert c.is_valid(334)
        assert not c.is_valid(333)

    def test_empty_rejected(self):
        with pytest.raises(ValueError):
            Committee.new_test([])

    def test_zero_stake_is_registered_but_inactive(self):
        # Stable-index membership (reconfig.py): stake 0 marks a registered
        # authority that is currently INACTIVE — it keeps its index and key
        # but contributes nothing to thresholds and is unelectable.
        c = Committee.new_test([1, 0, 1])
        assert c.total_stake == 2
        assert c.known_authority(1)
        assert not c.is_active(1)
        # Negative stakes and an all-inactive committee stay rejected.
        with pytest.raises(ValueError):
            Committee.new_test([1, -1, 1])
        with pytest.raises(ValueError):
            Committee.new_test([0, 0, 0])


class TestLeaderElection:
    def test_round_robin(self):
        c = Committee.new_test([1, 1, 1, 1])
        assert [c.elect_leader(r) for r in range(5)] == [0, 1, 2, 3, 0]
        assert c.elect_leader(1, offset=2) == 3

    def test_stake_based_distinct_per_offset(self):
        """committee.rs:530-546 stake_aware_leader_election."""
        c = Committee.new_test([100, 200, 300, 400, 500])
        leaders = {c.elect_leader_stake_based(10, off) for off in range(5)}
        assert len(leaders) == 5  # all distinct

    def test_stake_based_deterministic(self):
        c = Committee.new_test([100, 200, 300, 400, 500])
        for r in range(1, 20):
            assert c.elect_leader_stake_based(r, 0) == c.elect_leader_stake_based(r, 0)

    def test_stake_based_weighting(self):
        """An authority with overwhelming stake should win most rounds."""
        c = Committee.new_test([1, 1, 1, 10000])
        wins = sum(1 for r in range(1, 101) if c.elect_leader_stake_based(r, 0) == 3)
        assert wins > 90

    def test_genesis_round_leader_zero(self):
        c = Committee.new_test([5, 1, 1, 1])
        assert c.elect_leader_stake_based(0, 0) == 0


class TestStakeAggregator:
    def test_quorum(self):
        c = Committee.new_test([1, 1, 1, 1])
        agg = StakeAggregator(QUORUM)
        assert not agg.add(0, c)
        assert not agg.add(0, c)  # duplicate vote doesn't double-count
        assert not agg.add(1, c)
        assert agg.add(2, c)

    def test_validity(self):
        c = Committee.new_test([1, 1, 1, 1])
        agg = StakeAggregator(VALIDITY)
        assert not agg.add(0, c)
        assert agg.add(1, c)

    def test_encode_decode(self):
        from mysticeti_tpu.serde import Reader, Writer

        c = Committee.new_test([1, 1, 1, 1])
        agg = StakeAggregator(QUORUM)
        agg.add(1, c)
        agg.add(3, c)
        w = Writer()
        agg.encode(w)
        back = StakeAggregator.decode(Reader(w.finish()))
        assert back.kind == QUORUM
        assert back.stake == agg.stake
        assert sorted(back.voters()) == [1, 3]


def _block_with_shares(authority, n_tx, signers=None):
    genesis = [StatementBlock.new_genesis(i) for i in range(4)]
    return StatementBlock.build(
        authority, 1, [g.reference for g in genesis],
        [Share(bytes([i])) for i in range(n_tx)],
    )



def _offsets(ranges):
    """Expand certified TransactionLocatorRange outputs to offset lists."""
    out = []
    for r in ranges:
        out.extend(range(r.offset_start_inclusive, r.offset_end_exclusive))
    return out


class TestTransactionAggregator:
    def test_fast_path_certification(self):
        """Author's share is an implicit vote; 2 more votes certify (4-committee)."""
        c = Committee.new_test([1, 1, 1, 1])
        agg = TransactionAggregator(QUORUM)
        block = _block_with_shares(0, 5)
        processed = agg.process_block(block, None, c)
        assert processed == []  # shares only register
        assert len(agg) == 1

        rng = TransactionLocatorRange(block.reference, 0, 5)
        out = []
        agg.vote(rng, 1, c, out)
        assert out == []
        agg.vote(rng, 2, c, out)  # third distinct authority → quorum
        assert _offsets(out) == [0, 1, 2, 3, 4]
        assert agg.is_empty()
        assert agg.is_processed(TransactionLocator(block.reference, 3))

    def test_author_self_vote_not_double_counted(self):
        c = Committee.new_test([1, 1, 1, 1])
        agg = TransactionAggregator(QUORUM)
        block = _block_with_shares(0, 1)
        agg.process_block(block, None, c)
        out = []
        agg.vote(TransactionLocatorRange(block.reference, 0, 1), 0, c, out)
        assert out == []  # author voting again adds no stake

    def test_partial_range_votes(self):
        """Votes over sub-ranges split the aggregation correctly (RangeMap)."""
        c = Committee.new_test([1, 1, 1, 1])
        agg = TransactionAggregator(QUORUM)
        block = _block_with_shares(0, 10)
        agg.process_block(block, None, c)
        out = []
        agg.vote(TransactionLocatorRange(block.reference, 0, 6), 1, c, out)
        agg.vote(TransactionLocatorRange(block.reference, 3, 10), 2, c, out)
        # only [3,6) has author + 1 + 2 = quorum
        assert sorted(_offsets(out)) == [3, 4, 5]
        assert not agg.is_empty()
        out2 = []
        agg.vote(TransactionLocatorRange(block.reference, 0, 3), 2, c, out2)
        assert sorted(_offsets(out2)) == [0, 1, 2]

    def test_vote_for_unknown_transaction_raises(self):
        c = Committee.new_test([1, 1, 1, 1])
        agg = TransactionAggregator(QUORUM)
        ref = StatementBlock.new_genesis(0).reference
        with pytest.raises(RuntimeError, match="unknown"):
            agg.vote(TransactionLocatorRange(ref, 0, 1), 1, c, [])

    def test_process_block_emits_vote_ranges(self):
        c = Committee.new_test([1, 1, 1, 1])
        agg = TransactionAggregator(QUORUM)
        block = _block_with_shares(0, 3)
        response = []
        agg.process_block(block, response, c)
        assert len(response) == 1
        assert isinstance(response[0], VoteRange)
        assert response[0].range == TransactionLocatorRange(block.reference, 0, 3)

    def test_process_block_tallies_vote_statements(self):
        c = Committee.new_test([1, 1, 1, 1])
        agg = TransactionAggregator(QUORUM)
        share_block = _block_with_shares(0, 2)
        agg.process_block(share_block, None, c)
        genesis = [StatementBlock.new_genesis(i) for i in range(4)]
        vb1 = StatementBlock.build(
            1, 1, [g.reference for g in genesis],
            [VoteRange(TransactionLocatorRange(share_block.reference, 0, 2))],
        )
        vb2 = StatementBlock.build(
            2, 1, [g.reference for g in genesis],
            [Vote(TransactionLocator(share_block.reference, 0)),
             Vote(TransactionLocator(share_block.reference, 1))],
        )
        assert agg.process_block(vb1, None, c) == []
        processed = agg.process_block(vb2, None, c)
        assert sorted(_offsets(processed)) == [0, 1]

    def test_state_roundtrip(self):
        c = Committee.new_test([1, 1, 1, 1])
        agg = TransactionAggregator(QUORUM)
        block = _block_with_shares(0, 8)
        agg.process_block(block, None, c)
        agg.vote(TransactionLocatorRange(block.reference, 0, 4), 1, c, [])
        snapshot = agg.state()

        restored = TransactionAggregator(QUORUM)
        restored.with_state(snapshot)
        restored.processed = set(agg.processed)
        # one more vote certifies [0,4) in the restored copy too
        out = []
        restored.vote(TransactionLocatorRange(block.reference, 0, 4), 2, c, out)
        assert sorted(_offsets(out)) == [0, 1, 2, 3]


class TestSharedRanges:
    def test_contiguous_runs(self):
        genesis = [StatementBlock.new_genesis(i) for i in range(4)]
        ref = genesis[0].reference
        block = StatementBlock.build(
            0, 1, [g.reference for g in genesis],
            [Share(b"a"), Share(b"b"),
             Vote(TransactionLocator(ref, 0)),
             Share(b"c")],
        )
        ranges = shared_ranges(block)
        assert [(r.offset_start_inclusive, r.offset_end_exclusive) for r in ranges] == [
            (0, 2), (3, 4),
        ]


class TestVoteRangeBuilder:
    def test_reference_sequence(self):
        """committee.rs:530-541 vote_range_builder_test."""
        b = VoteRangeBuilder()
        assert b.add(1) is None
        assert b.add(2) is None
        assert b.add(4) == (1, 3)
        assert b.add(6) == (4, 5)
        assert b.finish() == (6, 7)

    def test_empty(self):
        assert VoteRangeBuilder().finish() is None


class TestNativeAggregatorParity:
    """The C++ VoteAggregator core must be behaviorally identical to the
    pure-Python RangeMap/StakeAggregator path — same certifications, same
    violations, byte-identical state snapshots."""

    @staticmethod
    def _pair():
        from mysticeti_tpu.native import native

        if native is None or not hasattr(native, "va_new"):
            import pytest

            pytest.skip("native extension unavailable")
        nat = TransactionAggregator(QUORUM)
        assert nat._nat is not None
        py = TransactionAggregator(QUORUM)
        py._nat = None  # pin the fallback path
        return nat, py

    def test_randomized_differential(self):
        import random

        c = Committee.new_test([1, 2, 1, 1, 2])
        nat, py = self._pair()
        rng = random.Random(42)
        blocks = [_block_with_shares(a % 4, 12) for a in range(3)]
        for blk in blocks:
            for agg in (nat, py):
                agg.process_block(blk, None, c)
        assert len(nat) == len(py)
        for _ in range(200):
            blk = rng.choice(blocks)
            s = rng.randrange(0, 12)
            e = rng.randrange(s + 1, 13)
            voter = rng.randrange(5)
            locator_range = TransactionLocatorRange(blk.reference, s, e)
            out_n, out_p = [], []
            err_n = err_p = None
            try:
                nat.vote(locator_range, voter, c, out_n)
            except RuntimeError as exc:
                err_n = str(exc)
            try:
                py.vote(locator_range, voter, c, out_p)
            except RuntimeError as exc:
                err_p = str(exc)
            assert out_n == out_p
            assert (err_n is None) == (err_p is None), (err_n, err_p)
            if err_n is not None:
                assert err_n == err_p
            assert len(nat) == len(py)
            assert nat.state() == py.state()
        # spot-check processed queries agree
        for blk in blocks:
            for off in range(12):
                k = TransactionLocator(blk.reference, off)
                assert nat.is_processed(k) == py.is_processed(k)

    def test_duplicate_share_differential(self):
        c = Committee.new_test([1, 1, 1, 1])
        nat, py = self._pair()
        blk = _block_with_shares(0, 4)
        for agg in (nat, py):
            agg.process_block(blk, None, c)
            try:
                agg.process_block(blk, None, c)
                raised = False
            except RuntimeError:
                raised = True
            assert raised, type(agg)

    def test_state_roundtrip_cross_implementation(self):
        """A native snapshot restores into the python path and vice versa."""
        c = Committee.new_test([1, 1, 1, 1])
        nat, py = self._pair()
        blk = _block_with_shares(0, 8)
        for agg in (nat, py):
            agg.process_block(blk, None, c)
            agg.vote(TransactionLocatorRange(blk.reference, 0, 5), 1, c, [])
        snap_nat, snap_py = nat.state(), py.state()
        assert snap_nat == snap_py

        nat2, py2 = self._pair()
        nat2.with_state(snap_py)  # python snapshot -> native core
        py2.with_state(snap_nat)  # native snapshot -> python core
        out_n, out_p = [], []
        nat2.vote(TransactionLocatorRange(blk.reference, 0, 8), 2, c, out_n)
        py2.vote(TransactionLocatorRange(blk.reference, 0, 8), 2, c, out_p)
        assert out_n == out_p
        assert sorted(_offsets(out_n)) == [0, 1, 2, 3, 4]
        assert nat2.state() == py2.state()

    def test_hook_call_count_parity(self):
        """Non-raising handler hooks must observe every violating offset,
        native and pure alike (the ProcessedTransactionHandler seam)."""

        class Recording(TransactionAggregator):
            def __init__(self):
                super().__init__(QUORUM)
                self.dups = []
                self.unknowns = []

            def duplicate_transaction(self, k, from_):
                self.dups.append(k.offset)

            def unknown_transaction(self, k, from_):
                self.unknowns.append(k.offset)

        c = Committee.new_test([1, 1, 1, 1])
        blk = _block_with_shares(0, 6)
        ref = blk.reference
        results = []
        for force_py in (False, True):
            agg = Recording()
            if force_py:
                agg._nat = None
            elif agg._nat is None:
                import pytest

                pytest.skip("native extension unavailable")
            agg.process_block(blk, None, c)
            # duplicate share over [2, 5) -> 3 duplicate hook calls
            agg.register(TransactionLocatorRange(ref, 2, 5), 1, c)
            # vote over a block never shared -> unknown per offset
            ghost = _block_with_shares(1, 1).reference
            agg.vote(TransactionLocatorRange(ghost, 0, 4), 2, c, [])
            results.append((agg.dups, agg.unknowns))
        assert results[0] == results[1] == ([2, 3, 4], [0, 1, 2, 3])

    def test_untracked_blocks_retire(self):
        """With track_processed off (certified-log mode) a fully-certified
        block must release all native state — flat memory at load."""
        from mysticeti_tpu.native import native

        if native is None or not hasattr(native, "va_new"):
            import pytest

            pytest.skip("native extension unavailable")
        c = Committee.new_test([1, 1, 1, 1])
        agg = TransactionAggregator(QUORUM, track_processed=False)
        assert agg._nat is not None
        blk = _block_with_shares(0, 4)
        agg.process_block(blk, None, c)
        assert len(agg._refs) == 1
        out = []
        rng = TransactionLocatorRange(blk.reference, 0, 4)
        agg.vote(rng, 1, c, out)
        agg.vote(rng, 2, c, out)
        assert len(_offsets(out)) == 4 and agg.is_empty()
        assert agg._refs == {}  # record retired, no growth

    def test_recovered_aggregator_tolerates_pre_snapshot_votes(self):
        """After with_state recovery the processed set is gone (it is not in
        the snapshot, committee.rs:352-362), so votes/shares for pre-snapshot
        transactions must NOT trip the Byzantine oracles; a fresh aggregator
        still raises (regression: crash-recovery fleets logged
        unknown-transaction tracebacks on every reboot)."""
        c = Committee.new_test([1, 1, 1, 1])
        nat, py = self._pair()
        blk = _block_with_shares(0, 4)
        ghost = TransactionLocatorRange(blk.reference, 0, 4)
        for agg in (nat, py):
            with pytest.raises(RuntimeError):
                agg.vote(ghost, 1, c, [])
            restored = TransactionAggregator(QUORUM)
            if agg is py:
                restored._nat = None
            restored.with_state(agg.state())
            restored.vote(ghost, 1, c, [])  # no raise
            restored.register(ghost, 0, c)  # duplicate-share path, no raise

    def test_native_state_serializer_matches_python_encoder(self):
        """va_state (all-C++ snapshot) is byte-identical to the reference
        Python encoder (_nat_state) across many blocks — covers the sort
        order (authority, round, digest) and the full range layout."""
        import pytest as _pytest

        from mysticeti_tpu.native import native as _native

        if _native is None or not hasattr(_native, "va_state"):
            _pytest.skip("native extension unavailable")
        c = Committee.new_test([1, 1, 1, 1])
        nat, _ = self._pair()
        genesis = [StatementBlock.new_genesis(a) for a in range(4)]
        prev = [g.reference for g in genesis]
        for r in range(1, 9):
            layer = []
            for a in range(4):
                blk = StatementBlock.build(
                    a, r, prev, [Share(bytes([r, a, i])) for i in range(6)]
                )
                layer.append(blk)
                nat.process_block(blk, None, c)
                if r % 2 == 0:
                    nat.vote(
                        TransactionLocatorRange(blk.reference, 0, 3),
                        (a + 1) % 4, c, [],
                    )
            prev = [b.reference for b in layer]
        assert len(nat) > 4
        assert _native.va_state(nat._nat) == nat._nat_state()

    def test_recovery_watermark_scopes_leniency(self):
        """with_state(watermark_round=R) scopes the post-recovery leniency:
        locators at rounds <= R (possibly pre-snapshot) bypass the Byzantine
        oracles, locators first shared ABOVE R stay strictly checked for the
        aggregator's whole remaining life (regression: recovered=True used to
        disable the duplicate/unknown oracles permanently)."""
        c = Committee.new_test([1, 1, 1, 1])
        nat, py = self._pair()
        old = _block_with_shares(0, 4)  # round 1
        ghost_old = TransactionLocatorRange(old.reference, 0, 4)
        genesis = [StatementBlock.new_genesis(i) for i in range(4)]
        new = StatementBlock.build(
            0, 9, [g.reference for g in genesis],
            [Share(bytes([i])) for i in range(4)],
        )
        ghost_new = TransactionLocatorRange(new.reference, 0, 4)
        for agg in (nat, py):
            restored = TransactionAggregator(QUORUM)
            if agg is py:
                restored._nat = None
            restored.with_state(agg.state(), watermark_round=1)
            restored.vote(ghost_old, 1, c, [])  # at watermark: tolerated
            with pytest.raises(RuntimeError):
                restored.vote(ghost_new, 1, c, [])  # above watermark: strict
