"""bench.py recovery-ladder logic (no accelerator, no jax import): the
guaranteed CPU fallback rung makes a parsed measurement unconditional
(VERDICT r5: two consecutive parsed=null rounds), and total failure still
emits a parsed zero record with the per-rung evidence."""
import importlib.util
import json
import os
import sys

import pytest


@pytest.fixture()
def bench(monkeypatch):
    # Import bench.py as a module without running main(); top level is
    # stdlib-only (jax imports live in the workers).
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
    spec = importlib.util.spec_from_file_location("bench_under_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setenv("BENCH_ACCOUNTING", "0")
    # Ladder tests must not append their synthetic measurements to the
    # repo's live perf-trend index (tools/bench_trend.py).
    monkeypatch.setenv("BENCH_TREND", "0")
    monkeypatch.delenv("BENCH_WORKER", raising=False)
    return mod


def _parse_record(capsys):
    out = capsys.readouterr().out.strip().splitlines()
    assert out, "bench printed no record"
    return json.loads(out[-1])


def test_cpu_fallback_rung_produces_labeled_measurement(bench, monkeypatch, capsys):
    """Every default rung wedges (the accelerator story); the CPU rung —
    which pins JAX_PLATFORMS=cpu in the worker env — still measures, and the
    record is labeled with the backend that produced it."""
    calls = []

    def fake_multi(batch, iters, trials, procs, ready_timeout_s,
                   stall_timeout_s, extra_env=None):
        calls.append(extra_env)
        if not extra_env:
            raise RuntimeError("accelerator unreachable: wedged tunnel")
        assert extra_env["JAX_PLATFORMS"] == "cpu"
        return 12345.6

    monkeypatch.setattr(bench, "_multi_process", fake_multi)
    bench.main()
    record = _parse_record(capsys)
    assert record["value"] == 12345.6
    assert record["unit"] == "sig/s"
    assert record["backend"] == "cpu"
    # Partial per-rung results ride along: the failures are evidence, not
    # silence.
    assert [r["ok"] for r in record["rungs"]] == [False, False, False, True]
    # The default rungs all ran without env overrides; only the last pinned
    # the CPU platform.
    assert calls[:-1] == [None] * (len(calls) - 1)


def test_total_failure_still_emits_parsed_zero_record(bench, monkeypatch, capsys):
    def always_fails(*args, **kwargs):
        raise RuntimeError("nothing works")

    monkeypatch.setattr(bench, "_multi_process", always_fails)
    with pytest.raises(RuntimeError, match="nothing works"):
        bench.main()
    record = _parse_record(capsys)
    assert record["value"] == 0.0
    assert record["backend"] == "none"
    assert all(r["ok"] is False for r in record["rungs"])


def test_budget_skipped_rungs_are_recorded(bench, monkeypatch, capsys):
    """An exhausted ladder budget skips intermediate rungs (never the CPU
    fallback), and each skip leaves per-rung evidence in the artifact
    instead of silently vanishing from the rungs list."""
    monkeypatch.setenv("BENCH_LADDER_BUDGET_S", "0")

    def fake_multi(batch, iters, trials, procs, ready_timeout_s,
                   stall_timeout_s, extra_env=None):
        if not extra_env:
            raise RuntimeError("accelerator unreachable: wedged tunnel")
        return 777.0

    monkeypatch.setattr(bench, "_multi_process", fake_multi)
    bench.main()
    record = _parse_record(capsys)
    assert record["backend"] == "cpu"
    assert record["value"] == 777.0
    assert [r.get("skipped", False) for r in record["rungs"]] == [
        False, True, True, False]
    assert all(r["error"] == "ladder budget exhausted"
               for r in record["rungs"] if r.get("skipped"))


def test_first_rung_success_keeps_the_healthy_record_shape(bench, monkeypatch, capsys):
    monkeypatch.setattr(
        bench, "_multi_process",
        lambda *a, **k: 600000.0,
    )
    bench.main()
    record = _parse_record(capsys)
    assert record["value"] == 600000.0
    assert record["backend"] == "default"
    assert record["vs_baseline"] == 1.2
    assert "rungs" not in record  # healthy runs keep the compact artifact
