"""Native (C++) runtime helpers with transparent build + pure-Python fallback.

``native`` resolves to the compiled ``_native`` module, or ``None`` when no
toolchain is available — callers must keep a Python fallback path (the
extension is an acceleration, matching the reference's Rust storage hot paths,
never a hard dependency).

The extension is built on first import with ``g++ -O2 -shared -fPIC ... -lz``
into this directory; set ``MYSTICETI_NO_NATIVE=1`` to disable both the build
and the import (useful to pin tests to the fallback path).
"""
from __future__ import annotations

import importlib
import logging
import os
import shutil
import subprocess
import sysconfig
import tempfile

log = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "mysticeti_native.cpp")
_SO = os.path.join(_DIR, "_native.so")


def _build() -> bool:
    gxx = shutil.which("g++") or shutil.which("c++")
    if gxx is None:
        return False
    include = sysconfig.get_path("include")
    # Build to a temp file then atomically rename: concurrent processes
    # (e.g. a validator fleet booting) race benignly.
    try:
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
        os.close(fd)
    except OSError:  # read-only install dir: fall back to pure Python
        return False
    cmd = [
        gxx, "-O2", "-std=c++17", "-shared", "-fPIC",
        f"-I{include}", _SRC, "-o", tmp, "-lz",
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=120)
        if proc.returncode != 0:
            log.warning("native build failed: %s", proc.stderr.decode()[-500:])
            os.unlink(tmp)
            return False
        os.replace(tmp, _SO)
        return True
    except Exception as exc:  # toolchain quirks must never break the node
        log.warning("native build error: %r", exc)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _import():
    try:
        return importlib.import_module("mysticeti_tpu.native._native")
    except ImportError as exc:
        log.warning("native import failed: %r", exc)
        return None


def _load():
    if os.environ.get("MYSTICETI_NO_NATIVE"):
        return None
    if not os.path.exists(_SRC):
        # Source-less deploy: a prebuilt .so may still match this interpreter.
        return _import() if os.path.exists(_SO) else None
    stale = not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC)
    if stale and not _build():
        return None
    mod = _import()
    if mod is None and not stale and _build():
        # A fresh-looking .so can still target another ABI/arch (e.g. the
        # checkout moved between interpreters); one rebuild fixes that.
        mod = _import()
    return mod


native = _load()
