"""Native (C++) runtime helpers with transparent build + pure-Python fallback.

``native`` resolves to the compiled ``_native`` module, or ``None`` when no
toolchain is available — callers must keep a Python fallback path (the
extension is an acceleration, matching the reference's Rust storage hot paths,
never a hard dependency).  The ``native-fallback`` lint rule
(mysticeti_tpu/analysis) enforces that every call site sits under a
``native is None``-aware gate.

The extension is built on first import with ``g++ -O2 -shared -fPIC ... -lz``
into this directory; set ``MYSTICETI_NO_NATIVE=1`` to disable both the build
and the import (useful to pin tests to the fallback path).

A failed build is remembered: a marker file keyed by the source sha256 is
written next to ``_native.so`` so a fleet of processes doesn't re-run the
doomed ``g++`` invocation (and re-log the warning) on every boot.  Editing
the source invalidates the marker.
"""
from __future__ import annotations

import hashlib
import importlib
import logging
import os
import shutil
import subprocess
import sysconfig
import tempfile

log = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "mysticeti_native.cpp")
_SO = os.path.join(_DIR, "_native.so")
_FAIL_MARKER = os.path.join(_DIR, "_native.buildfail")


def _src_fingerprint() -> str:
    with open(_SRC, "rb") as fh:
        return hashlib.sha256(fh.read()).hexdigest()


def _read_marker() -> str:
    try:
        with open(_FAIL_MARKER, "r", encoding="ascii") as fh:
            return fh.read().strip()
    except OSError:
        return ""


def _write_marker(fingerprint: str) -> None:
    try:
        with open(_FAIL_MARKER, "w", encoding="ascii") as fh:
            fh.write(fingerprint)
    except OSError:  # read-only dir: the retry cost returns, nothing breaks
        pass


def _clear_marker() -> None:
    try:
        os.unlink(_FAIL_MARKER)
    except OSError:
        pass


def _build(fingerprint: str = "") -> bool:
    gxx = shutil.which("g++") or shutil.which("c++")
    if gxx is None:
        if fingerprint:
            _write_marker(fingerprint)
        return False
    include = sysconfig.get_path("include")
    # Build to a temp file then atomically rename: concurrent processes
    # (e.g. a validator fleet booting) race benignly.
    try:
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
        os.close(fd)
    except OSError:  # read-only install dir: fall back to pure Python
        return False
    cmd = [
        gxx, "-O2", "-std=c++17", "-shared", "-fPIC",
        f"-I{include}", _SRC, "-o", tmp, "-lz",
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=120)
        if proc.returncode != 0:
            log.warning("native build failed: %s", proc.stderr.decode()[-500:])
            os.unlink(tmp)
            if fingerprint:
                _write_marker(fingerprint)
            return False
        os.replace(tmp, _SO)
        _clear_marker()
        return True
    except Exception as exc:  # toolchain quirks must never break the node
        log.warning("native build error: %r", exc)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        if fingerprint:
            _write_marker(fingerprint)
        return False


def _import():
    try:
        return importlib.import_module("mysticeti_tpu.native._native")
    except ImportError as exc:
        log.warning("native import failed: %r", exc)
        return None


def _load():
    if os.environ.get("MYSTICETI_NO_NATIVE"):
        return None
    if not os.path.exists(_SRC):
        # Source-less deploy: a prebuilt .so may still match this interpreter.
        return _import() if os.path.exists(_SO) else None
    stale = not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC)
    if stale:
        fingerprint = _src_fingerprint()
        if _read_marker() == fingerprint:
            # This exact source already failed to build on this box; the
            # warning was logged when the marker was written.
            log.debug("native build previously failed for this source; "
                      "skipping retry (remove %s to force)", _FAIL_MARKER)
            return None
        if not _build(fingerprint):
            return None
    mod = _import()
    if mod is None and not stale and _build(_src_fingerprint()):
        # A fresh-looking .so can still target another ABI/arch (e.g. the
        # checkout moved between interpreters); one rebuild fixes that.
        mod = _import()
    return mod


native = _load()


def active_functions() -> tuple:
    """Sorted names of the native functions resolved in this process.

    Empty when the extension is absent (no toolchain, build failure, or
    ``MYSTICETI_NO_NATIVE=1``) — the source of truth for the
    ``mysticeti_native_active`` info series and the ``/health`` host block,
    so A/B artifacts can record which path a run actually measured.
    """
    if native is None:
        return ()
    return tuple(sorted(
        name for name in dir(native)
        if not name.startswith("_") and callable(getattr(native, name))
    ))
