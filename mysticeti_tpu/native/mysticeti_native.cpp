// Native runtime helpers for mysticeti-tpu (CPython C API, no pybind11).
//
// The reference implements its storage/wire hot paths in Rust
// (mysticeti-core/src/wal.rs, network.rs); this extension is the C++
// equivalent for the paths where pure Python measurably costs: the WAL
// recovery scan (header walk + crc over every entry at node restart) and
// scatter-gather entry framing.  Little-endian hosts only (x86-64 / aarch64
// — same assumption the <IIII struct framing in wal.py already makes).
//
// Build: see mysticeti_tpu/native/__init__.py (g++ -O2 -shared -fPIC -lz).
// Python fallbacks exist for every function; the extension is an
// acceleration, not a requirement.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <zlib.h>

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr uint32_t kWalMagic = 0x314C4157;  // b"WAL1"
constexpr Py_ssize_t kHeaderSize = 16;      // magic, crc32, len, tag (u32 LE)

// wal_scan(buffer, end) -> list[(pos, tag, payload_off, payload_len)]
//
// Walks entry headers from offset 0, validating magic and payload crc32.
// Stops cleanly at the first invalid/torn entry — the crash-recovery
// contract of WalReader.iter_until (wal.rs:270-293 semantics).  Offsets are
// returned instead of payload copies so the caller can slice the mmap
// zero-copy.
PyObject* wal_scan(PyObject*, PyObject* args) {
  Py_buffer buf;
  unsigned long long end_arg;
  if (!PyArg_ParseTuple(args, "y*K", &buf, &end_arg)) return nullptr;

  const uint8_t* data = static_cast<const uint8_t*>(buf.buf);
  Py_ssize_t limit = static_cast<Py_ssize_t>(end_arg);
  if (limit > buf.len) limit = buf.len;

  PyObject* out = PyList_New(0);
  if (out == nullptr) {
    PyBuffer_Release(&buf);
    return nullptr;
  }

  Py_ssize_t pos = 0;
  while (pos + kHeaderSize <= limit) {
    uint32_t magic, crc, length, tag;
    std::memcpy(&magic, data + pos, 4);
    std::memcpy(&crc, data + pos + 4, 4);
    std::memcpy(&length, data + pos + 8, 4);
    std::memcpy(&tag, data + pos + 12, 4);
    if (magic != kWalMagic) break;
    Py_ssize_t payload_off = pos + kHeaderSize;
    if (payload_off + static_cast<Py_ssize_t>(length) > limit) break;

    uint32_t actual;
    Py_BEGIN_ALLOW_THREADS
    actual = static_cast<uint32_t>(
        crc32(0L, data + payload_off, static_cast<uInt>(length)));
    Py_END_ALLOW_THREADS
    if (actual != crc) break;

    PyObject* item =
        Py_BuildValue("(KIKI)", static_cast<unsigned long long>(pos), tag,
                      static_cast<unsigned long long>(payload_off), length);
    if (item == nullptr || PyList_Append(out, item) < 0) {
      Py_XDECREF(item);
      Py_DECREF(out);
      PyBuffer_Release(&buf);
      return nullptr;
    }
    Py_DECREF(item);
    pos = payload_off + static_cast<Py_ssize_t>(length);
  }

  PyBuffer_Release(&buf);
  return out;
}

// frame_entry(tag, parts) -> bytes
//
// Assemble one WAL entry (16-byte header + concatenated parts) with the
// crc computed in a single pass — replaces the per-part Python crc loop +
// struct.pack + join in WalWriter.writev.
PyObject* frame_entry(PyObject*, PyObject* args) {
  unsigned int tag;
  PyObject* parts;
  if (!PyArg_ParseTuple(args, "IO", &tag, &parts)) return nullptr;
  PyObject* seq = PySequence_Fast(parts, "parts must be a sequence");
  if (seq == nullptr) return nullptr;

  // Acquire every part's buffer up front: total is computed from the SAME
  // views the copy uses (PyObject_Length counts items, not bytes — sizing
  // from it would overflow the output for itemsize > 1 buffers), and holding
  // the views pins the lengths against concurrent mutation.
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  std::vector<Py_buffer> views(static_cast<size_t>(n));
  Py_ssize_t total = 0;
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* part = PySequence_Fast_GET_ITEM(seq, i);
    if (PyObject_GetBuffer(part, &views[i], PyBUF_SIMPLE) < 0) {
      for (Py_ssize_t j = 0; j < i; ++j) PyBuffer_Release(&views[j]);
      Py_DECREF(seq);
      return nullptr;
    }
    total += views[i].len;
  }

  PyObject* out = PyBytes_FromStringAndSize(nullptr, kHeaderSize + total);
  if (out == nullptr) {
    for (Py_ssize_t i = 0; i < n; ++i) PyBuffer_Release(&views[i]);
    Py_DECREF(seq);
    return nullptr;
  }
  uint8_t* dst = reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(out));
  uint8_t* payload = dst + kHeaderSize;
  for (Py_ssize_t i = 0; i < n; ++i) {
    std::memcpy(payload, views[i].buf, views[i].len);
    payload += views[i].len;
    PyBuffer_Release(&views[i]);
  }

  uint32_t crc;
  Py_BEGIN_ALLOW_THREADS
  crc = static_cast<uint32_t>(
      crc32(0L, dst + kHeaderSize, static_cast<uInt>(total)));
  Py_END_ALLOW_THREADS

  uint32_t magic = kWalMagic;
  uint32_t length = static_cast<uint32_t>(total);
  std::memcpy(dst, &magic, 4);
  std::memcpy(dst + 4, &crc, 4);
  std::memcpy(dst + 8, &length, 4);
  std::memcpy(dst + 12, &tag, 4);

  Py_DECREF(seq);
  return out;
}

PyMethodDef kMethods[] = {
    {"wal_scan", wal_scan, METH_VARARGS,
     "Scan crc-framed WAL entries; returns (pos, tag, off, len) tuples."},
    {"frame_entry", frame_entry, METH_VARARGS,
     "Assemble one framed WAL entry (header + parts) with single-pass crc."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {
    PyModuleDef_HEAD_INIT, "_native",
    "Native runtime helpers (WAL framing/scan).", -1, kMethods,
};

}  // namespace

PyMODINIT_FUNC PyInit__native(void) { return PyModule_Create(&kModule); }
