// Native runtime helpers for mysticeti-tpu (CPython C API, no pybind11).
//
// The reference implements its storage/wire hot paths in Rust
// (mysticeti-core/src/wal.rs, network.rs); this extension is the C++
// equivalent for the paths where pure Python measurably costs: the WAL
// recovery scan (header walk + crc over every entry at node restart) and
// scatter-gather entry framing.  Little-endian hosts only (x86-64 / aarch64
// — same assumption the <IIII struct framing in wal.py already makes).
//
// Build: see mysticeti_tpu/native/__init__.py (g++ -O2 -shared -fPIC -lz).
// Python fallbacks exist for every function; the extension is an
// acceleration, not a requirement.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <zlib.h>

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <map>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint32_t kWalMagic = 0x314C4157;  // b"WAL1"
constexpr Py_ssize_t kHeaderSize = 16;      // magic, crc32, len, tag (u32 LE)

// wal_scan(buffer, end) -> list[(pos, tag, payload_off, payload_len)]
//
// Walks entry headers from offset 0, validating magic and payload crc32.
// Stops cleanly at the first invalid/torn entry — the crash-recovery
// contract of WalReader.iter_until (wal.rs:270-293 semantics).  Offsets are
// returned instead of payload copies so the caller can slice the mmap
// zero-copy.
PyObject* wal_scan(PyObject*, PyObject* args) {
  Py_buffer buf;
  unsigned long long end_arg;
  if (!PyArg_ParseTuple(args, "y*K", &buf, &end_arg)) return nullptr;

  const uint8_t* data = static_cast<const uint8_t*>(buf.buf);
  Py_ssize_t limit = static_cast<Py_ssize_t>(end_arg);
  if (limit > buf.len) limit = buf.len;

  PyObject* out = PyList_New(0);
  if (out == nullptr) {
    PyBuffer_Release(&buf);
    return nullptr;
  }

  Py_ssize_t pos = 0;
  while (pos + kHeaderSize <= limit) {
    uint32_t magic, crc, length, tag;
    std::memcpy(&magic, data + pos, 4);
    std::memcpy(&crc, data + pos + 4, 4);
    std::memcpy(&length, data + pos + 8, 4);
    std::memcpy(&tag, data + pos + 12, 4);
    if (magic != kWalMagic) break;
    Py_ssize_t payload_off = pos + kHeaderSize;
    if (payload_off + static_cast<Py_ssize_t>(length) > limit) break;

    uint32_t actual;
    Py_BEGIN_ALLOW_THREADS
    actual = static_cast<uint32_t>(
        crc32(0L, data + payload_off, static_cast<uInt>(length)));
    Py_END_ALLOW_THREADS
    if (actual != crc) break;

    PyObject* item =
        Py_BuildValue("(KIKI)", static_cast<unsigned long long>(pos), tag,
                      static_cast<unsigned long long>(payload_off), length);
    if (item == nullptr || PyList_Append(out, item) < 0) {
      Py_XDECREF(item);
      Py_DECREF(out);
      PyBuffer_Release(&buf);
      return nullptr;
    }
    Py_DECREF(item);
    pos = payload_off + static_cast<Py_ssize_t>(length);
  }

  PyBuffer_Release(&buf);
  return out;
}

// frame_entry(tag, parts) -> bytes
//
// Assemble one WAL entry (16-byte header + concatenated parts) with the
// crc computed in a single pass — replaces the per-part Python crc loop +
// struct.pack + join in WalWriter.writev.
PyObject* frame_entry(PyObject*, PyObject* args) {
  unsigned int tag;
  PyObject* parts;
  if (!PyArg_ParseTuple(args, "IO", &tag, &parts)) return nullptr;
  PyObject* seq = PySequence_Fast(parts, "parts must be a sequence");
  if (seq == nullptr) return nullptr;

  // Acquire every part's buffer up front: total is computed from the SAME
  // views the copy uses (PyObject_Length counts items, not bytes — sizing
  // from it would overflow the output for itemsize > 1 buffers), and holding
  // the views pins the lengths against concurrent mutation.
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  std::vector<Py_buffer> views(static_cast<size_t>(n));
  Py_ssize_t total = 0;
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* part = PySequence_Fast_GET_ITEM(seq, i);
    if (PyObject_GetBuffer(part, &views[i], PyBUF_SIMPLE) < 0) {
      for (Py_ssize_t j = 0; j < i; ++j) PyBuffer_Release(&views[j]);
      Py_DECREF(seq);
      return nullptr;
    }
    total += views[i].len;
  }

  PyObject* out = PyBytes_FromStringAndSize(nullptr, kHeaderSize + total);
  if (out == nullptr) {
    for (Py_ssize_t i = 0; i < n; ++i) PyBuffer_Release(&views[i]);
    Py_DECREF(seq);
    return nullptr;
  }
  uint8_t* dst = reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(out));
  uint8_t* payload = dst + kHeaderSize;
  for (Py_ssize_t i = 0; i < n; ++i) {
    std::memcpy(payload, views[i].buf, views[i].len);
    payload += views[i].len;
    PyBuffer_Release(&views[i]);
  }

  uint32_t crc;
  Py_BEGIN_ALLOW_THREADS
  crc = static_cast<uint32_t>(
      crc32(0L, dst + kHeaderSize, static_cast<uInt>(total)));
  Py_END_ALLOW_THREADS

  uint32_t magic = kWalMagic;
  uint32_t length = static_cast<uint32_t>(total);
  std::memcpy(dst, &magic, 4);
  std::memcpy(dst + 4, &crc, 4);
  std::memcpy(dst + 8, &length, 4);
  std::memcpy(dst + 12, &tag, 4);

  Py_DECREF(seq);
  return out;
}

// ---------------------------------------------------------------------------
// VoteAggregator — the TransactionAggregator hot core (committee.rs:364-482
// analog).  Replaces the per-offset Python objects (TransactionLocator
// namedtuples, StakeAggregator instances, set hashing) that dominate the
// engine profile at load.  Semantics mirror mysticeti_tpu/committee.py
// exactly, including RangeMap's split-on-overlap behavior (range_map.py:38),
// so state() snapshots are byte-identical to the pure-Python path.
// ---------------------------------------------------------------------------

constexpr int kMaskWords = 8;  // 512-bit authority mask (AuthoritySet cap)

struct VaEntry {
  uint64_t start, end;  // half-open offset range
  uint64_t stake;
  uint8_t kind;  // 0 quorum / 1 validity (round-trips the state encoding)
  uint64_t mask[kMaskWords];
};

struct VaBlock {
  std::vector<VaEntry> ranges;               // sorted, disjoint, non-empty
  std::map<uint64_t, uint64_t> processed;    // merged [start, end) intervals
};

struct VoteAgg {
  bool track_processed = true;
  bool bound = false;
  uint8_t kind = 0;
  std::vector<uint64_t> stakes;
  uint64_t threshold = 0;
  std::unordered_map<std::string, VaBlock> blocks;
  size_t pending_count = 0;  // blocks with non-empty ranges
};

void va_destroy(PyObject* cap) {
  delete static_cast<VoteAgg*>(PyCapsule_GetPointer(cap, "mysticeti.va"));
}

VoteAgg* va_from(PyObject* cap) {
  return static_cast<VoteAgg*>(PyCapsule_GetPointer(cap, "mysticeti.va"));
}

// Merged-interval helpers over VaBlock::processed.
void processed_mark(VaBlock& b, uint64_t s, uint64_t e) {
  auto it = b.processed.upper_bound(s);
  if (it != b.processed.begin()) {
    --it;
    if (it->second >= s) {
      s = it->first;
      e = std::max(e, it->second);
      it = b.processed.erase(it);
    } else {
      ++it;
    }
  }
  while (it != b.processed.end() && it->first <= e) {
    e = std::max(e, it->second);
    it = b.processed.erase(it);
  }
  b.processed.emplace(s, e);
}

bool processed_contains(const VaBlock& b, uint64_t off) {
  auto it = b.processed.upper_bound(off);
  if (it == b.processed.begin()) return false;
  --it;
  return it->first <= off && off < it->second;
}

// Append the sub-intervals of [s, e) NOT in the processed set.  These are
// the violation ranges the Python wrapper feeds through the overridable
// handler hooks offset-by-offset — exact parity with the pure path, which
// calls the hook for every violating offset.
void unprocessed_intervals(const VaBlock& b, uint64_t s, uint64_t e,
                           std::vector<std::pair<uint64_t, uint64_t>>& out) {
  uint64_t cur = s;
  while (cur < e) {
    auto it = b.processed.upper_bound(cur);
    if (it != b.processed.begin()) {
      auto prev = std::prev(it);
      if (prev->first <= cur && cur < prev->second) {
        cur = prev->second;
        continue;
      }
    }
    uint64_t gap_end = e;
    if (it != b.processed.end()) gap_end = std::min(gap_end, it->first);
    if (cur < gap_end) out.emplace_back(cur, gap_end);
    cur = gap_end;
  }
}

PyObject* intervals_to_list(
    const std::vector<std::pair<uint64_t, uint64_t>>& ivs) {
  PyObject* out = PyList_New(0);
  if (out == nullptr) return nullptr;
  for (auto& iv : ivs) {
    PyObject* item =
        Py_BuildValue("(KK)", static_cast<unsigned long long>(iv.first),
                      static_cast<unsigned long long>(iv.second));
    if (item == nullptr || PyList_Append(out, item) < 0) {
      Py_XDECREF(item);
      Py_DECREF(out);
      return nullptr;
    }
    Py_DECREF(item);
  }
  return out;
}

// va_new(track_processed, kind) -> capsule
PyObject* va_new(PyObject*, PyObject* args) {
  int track, kind;
  if (!PyArg_ParseTuple(args, "pi", &track, &kind)) return nullptr;
  auto* agg = new VoteAgg();
  agg->track_processed = track != 0;
  agg->kind = static_cast<uint8_t>(kind);
  return PyCapsule_New(agg, "mysticeti.va", va_destroy);
}

// va_bind(cap, stakes_list, threshold)
PyObject* va_bind(PyObject*, PyObject* args) {
  PyObject* cap;
  PyObject* stakes;
  unsigned long long threshold;
  if (!PyArg_ParseTuple(args, "OOK", &cap, &stakes, &threshold)) return nullptr;
  VoteAgg* agg = va_from(cap);
  if (agg == nullptr) return nullptr;
  PyObject* seq = PySequence_Fast(stakes, "stakes must be a sequence");
  if (seq == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  if (n > kMaskWords * 64) {
    Py_DECREF(seq);
    PyErr_SetString(PyExc_ValueError, "committee exceeds 512 authorities");
    return nullptr;
  }
  agg->stakes.resize(static_cast<size_t>(n));
  for (Py_ssize_t i = 0; i < n; ++i) {
    agg->stakes[static_cast<size_t>(i)] = PyLong_AsUnsignedLongLong(
        PySequence_Fast_GET_ITEM(seq, i));
    if (PyErr_Occurred()) {
      Py_DECREF(seq);
      return nullptr;
    }
  }
  Py_DECREF(seq);
  agg->threshold = threshold;
  agg->bound = true;
  Py_RETURN_NONE;
}

// The shared sweep structure of RangeMap.mutate_range (range_map.py:38-80):
// fragments of existing entries overlapping [start, end) and the gaps
// between them, visited in offset order.  `OnFrag` returns true to keep the
// (possibly modified) fragment, false to drop it; `OnGap` returns true to
// materialize a fresh entry for the gap (initialized by it).
template <typename OnFrag, typename OnGap>
void sweep(VaBlock& b, uint64_t start, uint64_t end, OnFrag on_frag,
           OnGap on_gap) {
  std::vector<VaEntry> out;
  out.reserve(b.ranges.size() + 4);
  uint64_t cursor = start;
  for (VaEntry& entry : b.ranges) {
    if (entry.end <= start || entry.start >= end) {
      out.push_back(entry);
      continue;
    }
    if (entry.start < start) {
      VaEntry head = entry;
      head.end = start;
      out.push_back(head);
    }
    uint64_t ov_s = std::max(entry.start, start);
    uint64_t ov_e = std::min(entry.end, end);
    if (cursor < ov_s) {
      VaEntry fresh;
      if (on_gap(cursor, ov_s, fresh)) {
        fresh.start = cursor;
        fresh.end = ov_s;
        out.push_back(fresh);
      }
    }
    VaEntry frag = entry;  // POD clone — RangeMap clones on split
    frag.start = ov_s;
    frag.end = ov_e;
    if (on_frag(frag)) out.push_back(frag);
    cursor = ov_e;
    if (entry.end > end) {
      VaEntry tail = entry;
      tail.start = end;
      out.push_back(tail);
    }
  }
  if (cursor < end) {
    VaEntry fresh;
    if (on_gap(cursor, end, fresh)) {
      fresh.start = cursor;
      fresh.end = end;
      out.push_back(fresh);
    }
  }
  std::sort(out.begin(), out.end(),
            [](const VaEntry& a, const VaEntry& c) { return a.start < c.start; });
  b.ranges = std::move(out);
}

bool va_check_author(VoteAgg* agg, unsigned long long author) {
  if (!agg->bound) {
    PyErr_SetString(PyExc_RuntimeError, "VoteAggregator not bound to a committee");
    return false;
  }
  if (author >= agg->stakes.size()) {
    PyErr_SetString(PyExc_ValueError, "authority index out of range");
    return false;
  }
  return true;
}

// va_register(cap, key, start, end, author) -> [(s, e) violation ranges]
//
// committee.py register(): gaps get a fresh aggregator seeded with the
// author's vote; existing fragments are duplicate-share violations unless
// every offset is already processed.
PyObject* va_register(PyObject*, PyObject* args) {
  PyObject* cap;
  const char* key;
  Py_ssize_t keylen;
  unsigned long long start, end, author;
  if (!PyArg_ParseTuple(args, "Oy#KKK", &cap, &key, &keylen, &start, &end,
                        &author))
    return nullptr;
  VoteAgg* agg = va_from(cap);
  if (agg == nullptr || !va_check_author(agg, author)) return nullptr;
  std::vector<std::pair<uint64_t, uint64_t>> violations;
  if (start < end) {
    VaBlock& b = agg->blocks[std::string(key, static_cast<size_t>(keylen))];
    bool was_empty = b.ranges.empty();
    sweep(
        b, start, end,
        [&](VaEntry& frag) {
          if (agg->track_processed) {
            unprocessed_intervals(b, frag.start, frag.end, violations);
          }
          return true;  // keep the existing aggregation untouched
        },
        [&](uint64_t, uint64_t, VaEntry& fresh) {
          std::memset(fresh.mask, 0, sizeof(fresh.mask));
          fresh.mask[author / 64] = 1ULL << (author % 64);
          fresh.stake = agg->stakes[author];
          fresh.kind = agg->kind;
          return true;
        });
    if (was_empty && !b.ranges.empty()) agg->pending_count++;
  }
  return intervals_to_list(violations);
}

// va_vote(cap, key, start, end, author)
//   -> ([(s, e) certified...], [(s, e) violations...], block_retired)
//
// committee.py vote(): gaps are unknown-transaction violations unless
// processed; fragments accumulate the vote and certify at the threshold
// (certified fragments are dropped and marked processed).  `block_retired`
// tells the wrapper the block record was dropped entirely (only possible
// when track_processed is off — with tracking on, the processed intervals
// must outlive the pending ranges, exactly like the pure path's `processed`
// set).
PyObject* va_vote(PyObject*, PyObject* args) {
  PyObject* cap;
  const char* key;
  Py_ssize_t keylen;
  unsigned long long start, end, author;
  if (!PyArg_ParseTuple(args, "Oy#KKK", &cap, &key, &keylen, &start, &end,
                        &author))
    return nullptr;
  VoteAgg* agg = va_from(cap);
  if (agg == nullptr || !va_check_author(agg, author)) return nullptr;
  std::vector<std::pair<uint64_t, uint64_t>> done;
  std::vector<std::pair<uint64_t, uint64_t>> violations;
  bool retired = false;
  if (start < end) {
    auto found = agg->blocks.find(std::string(key, static_cast<size_t>(keylen)));
    if (found == agg->blocks.end()) {
      // No record for this block at all: nothing pending and nothing ever
      // processed (committee.py vote():380-384).
      if (agg->track_processed) violations.emplace_back(start, end);
    } else {
      VaBlock& b = found->second;
      bool was_nonempty = !b.ranges.empty();
      sweep(
          b, start, end,
          [&](VaEntry& frag) {
            uint64_t bit = 1ULL << (author % 64);
            if (!(frag.mask[author / 64] & bit)) {
              frag.mask[author / 64] |= bit;
              frag.stake += agg->stakes[author];
            }
            if (frag.stake >= agg->threshold) {
              done.emplace_back(frag.start, frag.end);
              return false;  // certified: drop from pending
            }
            return true;
          },
          [&](uint64_t gs, uint64_t ge, VaEntry&) {
            if (agg->track_processed) unprocessed_intervals(b, gs, ge, violations);
            return false;  // gaps stay gaps
          });
      if (agg->track_processed) {
        for (auto& range : done) processed_mark(b, range.first, range.second);
      }
      if (was_nonempty && b.ranges.empty()) {
        agg->pending_count--;
        if (!agg->track_processed) {
          // Nothing left to remember for this block: drop the record so a
          // long-running certified-log node (track_processed off) stays
          // flat on memory, like the pure path deleting its RangeMap.
          agg->blocks.erase(found);
          retired = true;
        }
      }
    }
  }
  PyObject* certified = intervals_to_list(done);
  if (certified == nullptr) return nullptr;
  PyObject* viol = intervals_to_list(violations);
  if (viol == nullptr) {
    Py_DECREF(certified);
    return nullptr;
  }
  PyObject* out = Py_BuildValue("(NNO)", certified, viol,
                                retired ? Py_True : Py_False);
  if (out == nullptr) {
    Py_DECREF(certified);
    Py_DECREF(viol);
  }
  return out;
}

// va_is_processed(cap, key, offset) -> bool
PyObject* va_is_processed(PyObject*, PyObject* args) {
  PyObject* cap;
  const char* key;
  Py_ssize_t keylen;
  unsigned long long off;
  if (!PyArg_ParseTuple(args, "Oy#K", &cap, &key, &keylen, &off)) return nullptr;
  VoteAgg* agg = va_from(cap);
  if (agg == nullptr) return nullptr;
  auto found = agg->blocks.find(std::string(key, static_cast<size_t>(keylen)));
  if (found == agg->blocks.end()) Py_RETURN_FALSE;
  if (processed_contains(found->second, off)) Py_RETURN_TRUE;
  Py_RETURN_FALSE;
}

// va_pending_len(cap) -> number of blocks with live aggregations
PyObject* va_pending_len(PyObject*, PyObject* args) {
  PyObject* cap;
  if (!PyArg_ParseTuple(args, "O", &cap)) return nullptr;
  VoteAgg* agg = va_from(cap);
  if (agg == nullptr) return nullptr;
  return PyLong_FromSize_t(agg->pending_count);
}

// va_items(cap) -> [(key, [(start, end, stake, kind, mask_bytes)...])...]
// for blocks with live ranges (state snapshot source; caller sorts by ref).
PyObject* va_items(PyObject*, PyObject* args) {
  PyObject* cap;
  if (!PyArg_ParseTuple(args, "O", &cap)) return nullptr;
  VoteAgg* agg = va_from(cap);
  if (agg == nullptr) return nullptr;
  PyObject* out = PyList_New(0);
  if (out == nullptr) return nullptr;
  for (auto& kv : agg->blocks) {
    if (kv.second.ranges.empty()) continue;
    PyObject* ranges = PyList_New(0);
    if (ranges == nullptr) goto fail;
    for (const VaEntry& e : kv.second.ranges) {
      PyObject* item = Py_BuildValue(
          "(KKKiy#)", static_cast<unsigned long long>(e.start),
          static_cast<unsigned long long>(e.end),
          static_cast<unsigned long long>(e.stake), static_cast<int>(e.kind),
          reinterpret_cast<const char*>(e.mask),
          static_cast<Py_ssize_t>(sizeof(e.mask)));
      if (item == nullptr || PyList_Append(ranges, item) < 0) {
        Py_XDECREF(item);
        Py_DECREF(ranges);
        goto fail;
      }
      Py_DECREF(item);
    }
    {
      PyObject* pair = Py_BuildValue(
          "(y#N)", kv.first.data(), static_cast<Py_ssize_t>(kv.first.size()),
          ranges);
      if (pair == nullptr) {
        Py_DECREF(ranges);
        goto fail;
      }
      if (PyList_Append(out, pair) < 0) {
        Py_DECREF(pair);
        goto fail;
      }
      Py_DECREF(pair);
    }
  }
  return out;
fail:
  Py_DECREF(out);
  return nullptr;
}

// va_load(cap, key, start, end, stake, kind, mask_bytes) — state restore.
PyObject* va_load(PyObject*, PyObject* args) {
  PyObject* cap;
  const char* key;
  Py_ssize_t keylen;
  unsigned long long start, end, stake;
  int kind;
  const char* mask;
  Py_ssize_t masklen;
  if (!PyArg_ParseTuple(args, "Oy#KKKiy#", &cap, &key, &keylen, &start, &end,
                        &stake, &kind, &mask, &masklen))
    return nullptr;
  VoteAgg* agg = va_from(cap);
  if (agg == nullptr) return nullptr;
  if (masklen > static_cast<Py_ssize_t>(sizeof(uint64_t) * kMaskWords)) {
    PyErr_SetString(PyExc_ValueError, "vote mask too wide");
    return nullptr;
  }
  VaBlock& b = agg->blocks[std::string(key, static_cast<size_t>(keylen))];
  bool was_empty = b.ranges.empty();
  VaEntry e;
  e.start = start;
  e.end = end;
  e.stake = stake;
  e.kind = static_cast<uint8_t>(kind);
  std::memset(e.mask, 0, sizeof(e.mask));
  std::memcpy(e.mask, mask, static_cast<size_t>(masklen));
  auto pos = std::upper_bound(
      b.ranges.begin(), b.ranges.end(), e,
      [](const VaEntry& a, const VaEntry& c) { return a.start < c.start; });
  b.ranges.insert(pos, e);
  if (was_empty) agg->pending_count++;
  Py_RETURN_NONE;
}

// va_state(cap) -> bytes — the canonical aggregator snapshot, byte-identical
// to committee.py TransactionAggregator._nat_state(): u32 block count; per
// block, sorted by (authority, round, digest): the 48-byte reference
// encoding — which IS the map key verbatim (LE u64 authority + LE u64 round
// + 32-byte digest, exactly BlockReference.encode's layout); u32 range
// count; per range: u64 start, u64 end, u8 kind, u64 stake, u32 mask length
// + mask bytes.  Serializing here instead of round-tripping va_items through
// Python removes the dominant cost of the per-commit state snapshot (tens
// of ms at deep pending backlogs -> tens of µs).
PyObject* va_state(PyObject*, PyObject* args) {
  PyObject* cap;
  if (!PyArg_ParseTuple(args, "O", &cap)) return nullptr;
  VoteAgg* agg = va_from(cap);
  if (agg == nullptr) return nullptr;
  std::vector<std::pair<const std::string*, const VaBlock*>> items;
  items.reserve(agg->blocks.size());
  for (const auto& kv : agg->blocks) {
    if (kv.second.ranges.empty()) continue;
    if (kv.first.size() != 48) {
      PyErr_SetString(PyExc_ValueError, "aggregator key is not a block ref");
      return nullptr;
    }
    items.emplace_back(&kv.first, &kv.second);
  }
  // Sort order must match Python's BlockReference dataclass ordering:
  // numeric (authority, round) then lexicographic digest.  LE host assumed
  // (module-wide assumption), so the packed u64s decode with memcpy.
  std::sort(items.begin(), items.end(),
            [](const std::pair<const std::string*, const VaBlock*>& x,
               const std::pair<const std::string*, const VaBlock*>& y) {
              uint64_t xa, xr, ya, yr;
              std::memcpy(&xa, x.first->data(), 8);
              std::memcpy(&xr, x.first->data() + 8, 8);
              std::memcpy(&ya, y.first->data(), 8);
              std::memcpy(&yr, y.first->data() + 8, 8);
              if (xa != ya) return xa < ya;
              if (xr != yr) return xr < yr;
              return std::memcmp(x.first->data() + 16, y.first->data() + 16,
                                 32) < 0;
            });
  std::string out;
  auto put_u32 = [&out](uint32_t v) {
    out.append(reinterpret_cast<const char*>(&v), 4);
  };
  auto put_u64 = [&out](uint64_t v) {
    out.append(reinterpret_cast<const char*>(&v), 8);
  };
  put_u32(static_cast<uint32_t>(items.size()));
  for (const auto& item : items) {
    out.append(*item.first);  // 48-byte ref encoding == the key bytes
    put_u32(static_cast<uint32_t>(item.second->ranges.size()));
    for (const VaEntry& e : item.second->ranges) {
      put_u64(e.start);
      put_u64(e.end);
      out.push_back(static_cast<char>(e.kind));
      put_u64(e.stake);
      put_u32(static_cast<uint32_t>(sizeof(e.mask)));
      out.append(reinterpret_cast<const char*>(e.mask), sizeof(e.mask));
    }
  }
  return PyBytes_FromStringAndSize(out.data(),
                                   static_cast<Py_ssize_t>(out.size()));
}

// ---------------------------------------------------------------------------
// Block decoding (types.py:StatementBlock.from_bytes hot path).
//
// At saturated load a node decodes ~20+ MB/s of peer blocks; the Python
// inline decoder costs ~77 ms per 5 MB block (tens of thousands of
// interpreter-loop slice+construct steps).  This walks the same wire format
// in C and builds the same frozen-dataclass statement objects, which the
// caller assembles into a StatementBlock.  Registered classes are module
// state (decode_register, called by types.py at import).

PyObject* g_cls_block_ref = nullptr;
PyObject* g_cls_share = nullptr;
PyObject* g_cls_vote = nullptr;
PyObject* g_cls_vote_range = nullptr;
PyObject* g_cls_locator = nullptr;
PyObject* g_cls_locator_range = nullptr;

// Interned attribute keys for the fast construction path.
PyObject* g_empty_tuple = nullptr;
PyObject* k_authority = nullptr;
PyObject* k_round = nullptr;
PyObject* k_digest = nullptr;
PyObject* k_transaction = nullptr;
PyObject* k_locator = nullptr;
PyObject* k_accept = nullptr;
PyObject* k_conflict = nullptr;
PyObject* k_range = nullptr;
PyObject* k_block = nullptr;
PyObject* k_offset = nullptr;
PyObject* k_start = nullptr;
PyObject* k_end = nullptr;
// Fast construction verified safe for the registered classes?
bool g_fast = false;

// Build an instance of a plain (non-__slots__) frozen dataclass WITHOUT
// running its __init__: tp_new + direct instance-dict population.  The
// frozen __init__ costs ~1 µs/instance in object.__setattr__ calls — at
// ~10k statements per block that IS the decode cost.  decode_register
// self-verifies this path against a normal constructor call and falls back
// to PyObject_CallFunction when the classes change shape.  Steals vals
// references (also on failure).
PyObject* fast_instance(PyObject* cls, PyObject* const keys[],
                        PyObject* vals[], int n) {
  PyTypeObject* tp = reinterpret_cast<PyTypeObject*>(cls);
  PyObject* inst = tp->tp_new(tp, g_empty_tuple, nullptr);
  PyObject* dict =
      inst != nullptr ? PyObject_GenericGetDict(inst, nullptr) : nullptr;
  if (dict == nullptr) {
    Py_XDECREF(inst);
    for (int i = 0; i < n; i++) Py_XDECREF(vals[i]);
    return nullptr;
  }
  for (int i = 0; i < n; i++) {
    if (vals[i] == nullptr || PyDict_SetItem(dict, keys[i], vals[i]) < 0) {
      for (int j = i; j < n; j++) Py_XDECREF(vals[j]);
      Py_DECREF(dict);
      Py_DECREF(inst);
      return nullptr;
    }
    Py_DECREF(vals[i]);
  }
  Py_DECREF(dict);
  return inst;
}

constexpr Py_ssize_t kDigestSize = 32;
constexpr Py_ssize_t kSignatureSize = 64;
constexpr uint64_t kLocatorRangeMaxLen = 1ull << 20;
constexpr uint8_t kVoteAccept = 0;
constexpr uint8_t kVoteReject = 1;
constexpr uint8_t kStShare = 0;
constexpr uint8_t kStVote = 1;
constexpr uint8_t kStVoteRange = 2;

PyObject* make_block_ref(const uint8_t* p);  // fwd

PyObject* decode_register(PyObject*, PyObject* args) {
  PyObject *block_ref, *share, *vote, *vote_range, *locator, *locator_range;
  if (!PyArg_ParseTuple(args, "OOOOOO", &block_ref, &share, &vote,
                        &vote_range, &locator, &locator_range))
    return nullptr;
  Py_INCREF(block_ref);
  Py_INCREF(share);
  Py_INCREF(vote);
  Py_INCREF(vote_range);
  Py_INCREF(locator);
  Py_INCREF(locator_range);
  g_cls_block_ref = block_ref;
  g_cls_share = share;
  g_cls_vote = vote;
  g_cls_vote_range = vote_range;
  g_cls_locator = locator;
  g_cls_locator_range = locator_range;
  if (g_empty_tuple == nullptr) {
    g_empty_tuple = PyTuple_New(0);
    k_authority = PyUnicode_InternFromString("authority");
    k_round = PyUnicode_InternFromString("round");
    k_digest = PyUnicode_InternFromString("digest");
    k_transaction = PyUnicode_InternFromString("transaction");
    k_locator = PyUnicode_InternFromString("locator");
    k_accept = PyUnicode_InternFromString("accept");
    k_conflict = PyUnicode_InternFromString("conflict");
    k_range = PyUnicode_InternFromString("range");
    k_block = PyUnicode_InternFromString("block");
    k_offset = PyUnicode_InternFromString("offset");
    k_start = PyUnicode_InternFromString("offset_start_inclusive");
    k_end = PyUnicode_InternFromString("offset_end_exclusive");
  }
  // Self-verify the fast construction path: build one BlockReference both
  // ways and compare.  Any class-shape change (e.g. __slots__) flips the
  // decoder to plain constructor calls instead of miscreating objects.
  g_fast = true;
  uint8_t probe[48];
  std::memset(probe, 0, sizeof probe);
  probe[0] = 3;
  probe[8] = 7;
  PyObject* fast = make_block_ref(probe);
  PyObject* digest = fast != nullptr
      ? PyBytes_FromStringAndSize(reinterpret_cast<const char*>(probe + 16),
                                  kDigestSize)
      : nullptr;
  PyObject* slow = digest != nullptr
      ? PyObject_CallFunction(g_cls_block_ref, "iiN", 3, 7, digest)
      : nullptr;
  int eq = (fast != nullptr && slow != nullptr)
               ? PyObject_RichCompareBool(fast, slow, Py_EQ)
               : -1;
  Py_XDECREF(fast);
  Py_XDECREF(slow);
  if (eq != 1) {
    PyErr_Clear();
    g_fast = false;
  }
  Py_RETURN_NONE;
}

inline uint64_t read_u64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

inline uint32_t read_u32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

PyObject* truncated(const char* what) {
  PyErr_Format(PyExc_ValueError, "truncated input: %s", what);
  return nullptr;
}

// Builds BlockReference(authority, round, digest) from 48 bytes.
PyObject* make_block_ref(const uint8_t* p) {
  PyObject* digest =
      PyBytes_FromStringAndSize(reinterpret_cast<const char*>(p + 16),
                                kDigestSize);
  if (digest == nullptr) return nullptr;
  if (g_fast) {
    PyObject* const keys[] = {k_authority, k_round, k_digest};
    PyObject* vals[] = {PyLong_FromUnsignedLongLong(read_u64(p)),
                        PyLong_FromUnsignedLongLong(read_u64(p + 8)), digest};
    return fast_instance(g_cls_block_ref, keys, vals, 3);
  }
  return PyObject_CallFunction(
      g_cls_block_ref, "KKN", static_cast<unsigned long long>(read_u64(p)),
      static_cast<unsigned long long>(read_u64(p + 8)), digest);
}

// TransactionLocator(block=ref, offset) — steals ref.
PyObject* make_locator(PyObject* ref, uint64_t offset) {
  if (ref == nullptr) return nullptr;
  if (g_fast) {
    PyObject* const keys[] = {k_block, k_offset};
    PyObject* vals[] = {ref, PyLong_FromUnsignedLongLong(offset)};
    return fast_instance(g_cls_locator, keys, vals, 2);
  }
  return PyObject_CallFunction(g_cls_locator, "NK", ref,
                               static_cast<unsigned long long>(offset));
}

// decode_block(data)
//   -> (authority, round, includes, statements, meta_ns, epoch_marker,
//       epoch, signature, share_runs, stamps)
// share_runs: tuple of (start, end) half-open spans of contiguous Share
// statements (committee.shared_ranges precompute).
// stamps: bytes, 8 per Share statement — the payload's first 8 bytes, or
// zeros for sub-8-byte payloads (commit-observer latency input).
// Raises ValueError on any malformed input (same cases as the Python
// decoder; types.py maps it to SerdeError).
PyObject* decode_block(PyObject*, PyObject* args) {
  Py_buffer buf;
  if (!PyArg_ParseTuple(args, "y*", &buf)) return nullptr;
  if (g_cls_block_ref == nullptr) {
    PyBuffer_Release(&buf);
    PyErr_SetString(PyExc_RuntimeError, "decode_register was never called");
    return nullptr;
  }
  const uint8_t* d = static_cast<const uint8_t*>(buf.buf);
  const Py_ssize_t n = buf.len;
  Py_ssize_t pos = 0;
  PyObject* includes = nullptr;
  PyObject* statements = nullptr;
  PyObject* result = nullptr;

  auto fail = [&](const char* what) -> PyObject* {
    Py_XDECREF(includes);
    Py_XDECREF(statements);
    PyBuffer_Release(&buf);
    if (!PyErr_Occurred())
      PyErr_Format(PyExc_ValueError, "truncated input: %s", what);
    return nullptr;
  };

  if (n < 20) return fail("header");
  const uint64_t authority = read_u64(d);
  const uint64_t round = read_u64(d + 8);
  pos = 16;
  uint32_t cnt = read_u32(d + pos);
  pos += 4;
  // Counts are attacker-controlled: bound them by the bytes that could
  // possibly back them BEFORE allocating (a 24-byte frame claiming 2^32
  // includes must not preallocate a 34 GB list).
  if (static_cast<uint64_t>(cnt) * 48 > static_cast<uint64_t>(n - pos))
    return fail("include digest");
  includes = PyList_New(cnt);
  if (includes == nullptr) return fail("includes alloc");
  for (uint32_t i = 0; i < cnt; i++) {
    if (pos + 48 > n) return fail("include digest");
    PyObject* ref = make_block_ref(d + pos);
    if (ref == nullptr) return fail("include ref");
    PyList_SET_ITEM(includes, i, ref);
    pos += 48;
  }
  if (pos + 4 > n) return fail("statement count");
  cnt = read_u32(d + pos);
  pos += 4;
  // Every statement costs at least 1 byte (its tag).
  if (static_cast<uint64_t>(cnt) > static_cast<uint64_t>(n - pos))
    return fail("statement tag");
  statements = PyList_New(cnt);
  if (statements == nullptr) return fail("statements alloc");
  // Share run-length spans (committee.shared_ranges precompute): collected
  // for free while walking statements.
  std::vector<std::pair<uint32_t, uint32_t>> share_runs;
  // Benchmark submission stamps: first 8 bytes of every Share payload
  // (zero for sub-8-byte payloads) — the commit observer's latency input,
  // collected for free during the parse.
  std::string stamps;
  for (uint32_t i = 0; i < cnt; i++) {
    if (pos + 1 > n) return fail("statement tag");
    const uint8_t tag = d[pos];
    pos += 1;
    PyObject* st = nullptr;
    if (tag == kStShare) {
      if (!share_runs.empty() && share_runs.back().second == i) {
        share_runs.back().second = i + 1;
      } else {
        share_runs.emplace_back(i, i + 1);
      }
      if (pos + 4 > n) return fail("share length");
      const uint32_t ln = read_u32(d + pos);
      pos += 4;
      if (pos + static_cast<Py_ssize_t>(ln) > n) return fail("share payload");
      if (ln >= 8) {
        stamps.append(reinterpret_cast<const char*>(d + pos), 8);
      } else {
        stamps.append(8, '\0');
      }
      PyObject* payload = PyBytes_FromStringAndSize(
          reinterpret_cast<const char*>(d + pos), ln);
      if (payload == nullptr) return fail("share alloc");
      if (g_fast) {
        PyObject* const keys[] = {k_transaction};
        PyObject* vals[] = {payload};
        st = fast_instance(g_cls_share, keys, vals, 1);
      } else {
        st = PyObject_CallFunction(g_cls_share, "N", payload);
      }
      pos += ln;
    } else if (tag == kStVote) {
      if (pos + 57 > n) return fail("vote locator");
      PyObject* locator =
          make_locator(make_block_ref(d + pos), read_u64(d + pos + 48));
      pos += 56;
      if (locator == nullptr) return fail("vote locator obj");
      const uint8_t vote_byte = d[pos];
      pos += 1;
      if (vote_byte != kVoteAccept && vote_byte != kVoteReject) {
        Py_DECREF(locator);
        PyErr_Format(PyExc_ValueError, "invalid vote byte %d", vote_byte);
        return fail("vote byte");
      }
      PyObject* conflict = Py_None;
      Py_INCREF(conflict);
      if (vote_byte == kVoteReject) {
        if (pos + 1 > n) {
          Py_DECREF(locator);
          Py_DECREF(conflict);
          return fail("conflict presence");
        }
        const uint8_t presence = d[pos];
        pos += 1;
        if (presence != 0 && presence != 1) {
          Py_DECREF(locator);
          Py_DECREF(conflict);
          PyErr_Format(PyExc_ValueError,
                       "invalid conflict-presence byte %d", presence);
          return fail("conflict presence byte");
        }
        if (presence == 1) {
          if (pos + 56 > n) {
            Py_DECREF(locator);
            Py_DECREF(conflict);
            return fail("conflict");
          }
          Py_DECREF(conflict);
          conflict =
              make_locator(make_block_ref(d + pos), read_u64(d + pos + 48));
          pos += 56;
          if (conflict == nullptr) {
            Py_DECREF(locator);
            return fail("conflict obj");
          }
        }
      }
      if (g_fast) {
        PyObject* accept = vote_byte == kVoteAccept ? Py_True : Py_False;
        Py_INCREF(accept);
        PyObject* const keys[] = {k_locator, k_accept, k_conflict};
        PyObject* vals[] = {locator, accept, conflict};
        st = fast_instance(g_cls_vote, keys, vals, 3);
      } else {
        st = PyObject_CallFunction(
            g_cls_vote, "NON", locator,
            vote_byte == kVoteAccept ? Py_True : Py_False, conflict);
      }
    } else if (tag == kStVoteRange) {
      if (pos + 64 > n) return fail("range digest");
      const uint64_t start = read_u64(d + pos + 48);
      const uint64_t end = read_u64(d + pos + 56);
      if (end < start) {
        PyErr_Format(PyExc_ValueError,
                     "invalid locator range: end %llu < start %llu",
                     static_cast<unsigned long long>(end),
                     static_cast<unsigned long long>(start));
        return fail("range order");
      }
      if (end - start > kLocatorRangeMaxLen || end > kLocatorRangeMaxLen) {
        PyErr_Format(PyExc_ValueError, "locator range too long/large: %llu",
                     static_cast<unsigned long long>(end));
        return fail("range bound");
      }
      PyObject* ref = make_block_ref(d + pos);
      if (ref == nullptr) return fail("range ref");
      PyObject* rng;
      if (g_fast) {
        PyObject* const rkeys[] = {k_block, k_start, k_end};
        PyObject* rvals[] = {ref, PyLong_FromUnsignedLongLong(start),
                             PyLong_FromUnsignedLongLong(end)};
        rng = fast_instance(g_cls_locator_range, rkeys, rvals, 3);
      } else {
        rng = PyObject_CallFunction(
            g_cls_locator_range, "NKK", ref,
            static_cast<unsigned long long>(start),
            static_cast<unsigned long long>(end));
      }
      pos += 64;
      if (rng == nullptr) return fail("range obj");
      if (g_fast) {
        PyObject* const keys[] = {k_range};
        PyObject* vals[] = {rng};
        st = fast_instance(g_cls_vote_range, keys, vals, 1);
      } else {
        st = PyObject_CallFunction(g_cls_vote_range, "N", rng);
      }
    } else {
      PyErr_Format(PyExc_ValueError, "unknown statement tag %d", tag);
      return fail("tag");
    }
    if (st == nullptr) return fail("statement obj");
    PyList_SET_ITEM(statements, i, st);
  }
  if (pos + 8 + 1 + 8 + kSignatureSize > n) return fail("trailer");
  const uint64_t meta_ns = read_u64(d + pos);
  pos += 8;
  const uint8_t epoch_marker = d[pos];
  pos += 1;
  const uint64_t epoch = read_u64(d + pos);
  pos += 8;
  PyObject* signature = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(d + pos), kSignatureSize);
  pos += kSignatureSize;
  if (signature == nullptr) return fail("signature alloc");
  if (pos != n) {
    Py_DECREF(signature);
    PyErr_Format(PyExc_ValueError, "trailing garbage: %zd bytes", n - pos);
    return fail("trailer garbage");
  }
  PyObject* runs = PyTuple_New(static_cast<Py_ssize_t>(share_runs.size()));
  if (runs == nullptr) {
    Py_DECREF(signature);
    return fail("runs alloc");
  }
  for (size_t i = 0; i < share_runs.size(); i++) {
    PyObject* pair = Py_BuildValue("(II)", share_runs[i].first,
                                   share_runs[i].second);
    if (pair == nullptr) {
      Py_DECREF(runs);
      Py_DECREF(signature);
      return fail("runs pair");
    }
    PyTuple_SET_ITEM(runs, static_cast<Py_ssize_t>(i), pair);
  }
  PyObject* stamp_bytes = PyBytes_FromStringAndSize(
      stamps.data(), static_cast<Py_ssize_t>(stamps.size()));
  if (stamp_bytes == nullptr) {
    Py_DECREF(runs);
    Py_DECREF(signature);
    return fail("stamps alloc");
  }
  result = Py_BuildValue(
      "(KKNNKBKNNN)", static_cast<unsigned long long>(authority),
      static_cast<unsigned long long>(round), includes, statements,
      static_cast<unsigned long long>(meta_ns), epoch_marker,
      static_cast<unsigned long long>(epoch), signature, runs, stamp_bytes);
  if (result == nullptr) {
    // includes/statements ownership consumed on success only.
    PyBuffer_Release(&buf);
    return nullptr;
  }
  PyBuffer_Release(&buf);
  return result;
}

// ---------------------------------------------------------------------------
// BLAKE2b-256 (RFC 7693) — embedded so the batched digest path links against
// nothing beyond zlib (the build contract of native/__init__.py).  Unkeyed,
// no salt/personal, 32-byte output: exactly
// ``hashlib.blake2b(data, digest_size=32)``, pinned byte-for-byte by the
// parity corpus test against crypto.blake2b_256.
// ---------------------------------------------------------------------------

namespace blake2b {

constexpr uint64_t kIV[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
    0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};

constexpr uint8_t kSigma[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3}};

inline uint64_t rotr64(uint64_t x, int n) { return (x >> n) | (x << (64 - n)); }

struct Ctx {
  uint64_t h[8];
  uint64_t t0, t1;
  uint8_t buf[128];
  size_t buflen;
};

inline void compress(Ctx& c, const uint8_t* block, bool last) {
  uint64_t m[16], v[16];
  for (int i = 0; i < 16; i++) std::memcpy(&m[i], block + 8 * i, 8);
  for (int i = 0; i < 8; i++) v[i] = c.h[i];
  for (int i = 0; i < 8; i++) v[i + 8] = kIV[i];
  v[12] ^= c.t0;
  v[13] ^= c.t1;
  if (last) v[14] = ~v[14];
#define B2B_G(a, b, cc, d, x, y)              \
  v[a] = v[a] + v[b] + (x);                   \
  v[d] = rotr64(v[d] ^ v[a], 32);             \
  v[cc] = v[cc] + v[d];                       \
  v[b] = rotr64(v[b] ^ v[cc], 24);            \
  v[a] = v[a] + v[b] + (y);                   \
  v[d] = rotr64(v[d] ^ v[a], 16);             \
  v[cc] = v[cc] + v[d];                       \
  v[b] = rotr64(v[b] ^ v[cc], 63);
// Rounds unrolled with literal indices: kSigma is constexpr, so every
// m[kSigma[r][i]] folds to a direct load — the loop-carried indirect
// indexing was the compress bottleneck under -O2/-O3.
#define B2B_ROUND(r)                                      \
  B2B_G(0, 4, 8, 12, m[kSigma[r][0]], m[kSigma[r][1]]);   \
  B2B_G(1, 5, 9, 13, m[kSigma[r][2]], m[kSigma[r][3]]);   \
  B2B_G(2, 6, 10, 14, m[kSigma[r][4]], m[kSigma[r][5]]);  \
  B2B_G(3, 7, 11, 15, m[kSigma[r][6]], m[kSigma[r][7]]);  \
  B2B_G(0, 5, 10, 15, m[kSigma[r][8]], m[kSigma[r][9]]);  \
  B2B_G(1, 6, 11, 12, m[kSigma[r][10]], m[kSigma[r][11]]); \
  B2B_G(2, 7, 8, 13, m[kSigma[r][12]], m[kSigma[r][13]]);  \
  B2B_G(3, 4, 9, 14, m[kSigma[r][14]], m[kSigma[r][15]]);
  B2B_ROUND(0); B2B_ROUND(1); B2B_ROUND(2); B2B_ROUND(3);
  B2B_ROUND(4); B2B_ROUND(5); B2B_ROUND(6); B2B_ROUND(7);
  B2B_ROUND(8); B2B_ROUND(9); B2B_ROUND(10); B2B_ROUND(11);
#undef B2B_ROUND
#undef B2B_G
  for (int i = 0; i < 8; i++) c.h[i] ^= v[i] ^ v[i + 8];
}

inline void init256(Ctx& c) {
  for (int i = 0; i < 8; i++) c.h[i] = kIV[i];
  c.h[0] ^= 0x01010000ULL ^ 32ULL;  // digest_size=32, no key, fanout/depth 1
  c.t0 = c.t1 = 0;
  c.buflen = 0;
}

inline void update(Ctx& c, const uint8_t* in, size_t len) {
  while (len > 0) {
    if (c.buflen == 128) {
      // The buffer only compresses once MORE input is known to follow —
      // the final block must flow through the last-block flag instead.
      c.t0 += 128;
      if (c.t0 < 128) c.t1++;
      compress(c, c.buf, false);
      c.buflen = 0;
    }
    size_t take = std::min(len, 128 - c.buflen);
    std::memcpy(c.buf + c.buflen, in, take);
    c.buflen += take;
    in += take;
    len -= take;
  }
}

inline void final256(Ctx& c, uint8_t out[32]) {
  c.t0 += c.buflen;
  if (c.t0 < c.buflen) c.t1++;
  std::memset(c.buf + c.buflen, 0, 128 - c.buflen);
  compress(c, c.buf, true);
  for (int i = 0; i < 32; i++)
    out[i] = static_cast<uint8_t>(c.h[i / 8] >> (8 * (i % 8)));
}

inline void hash256(const uint8_t* in, size_t len, uint8_t out[32]) {
  Ctx c;
  init256(c);
  update(c, in, len);
  final256(c, out);
}

// Both StatementBlock digests in ~one pass: the block digest covers the
// full bytes, the signature pre-hash covers the bytes minus the 64-byte
// trailer — the two streams are IDENTICAL up to the pre-hash message's
// final partial block, so hash the shared prefix once and fork the state.
// Cuts the hashing work per block from len + (len-64) to ~len + 128.
inline void hash256_pair(const uint8_t* in, size_t len, uint8_t full_out[32],
                         uint8_t signed_out[32]) {
  const size_t sig = static_cast<size_t>(kSignatureSize);
  if (len < sig) {
    hash256(in, len, full_out);
    hash256(in, 0, signed_out);  // Python's data[:-64] on short input: b""
    return;
  }
  const size_t msg_len = len - sig;
  // All full 128-byte blocks strictly before the pre-hash's final block;
  // `update` keeps a full buffered block uncompressed until more input
  // arrives, so the forked copies continue bit-identically to streaming.
  const size_t prefix = msg_len == 0 ? 0 : ((msg_len - 1) / 128) * 128;
  Ctx c;
  init256(c);
  update(c, in, prefix);
  Ctx cs = c;
  update(cs, in + prefix, msg_len - prefix);
  final256(cs, signed_out);
  update(c, in + prefix, len - prefix);
  final256(c, full_out);
}

}  // namespace blake2b

// block_digests(parts) -> [(digest32, signed_digest32)...]
//
// Batched StatementBlock digest path (types.py): for each serialized block,
// the canonical blake2b-256 over the full bytes (the reference digest) AND
// over the bytes minus the 64-byte signature trailer (the message Ed25519
// signs — crypto.rs:77-84 layering).  One GIL round-trip hashes the whole
// frame batch; the hashing itself runs with the GIL released, so the event
// loop keeps scheduling while the offload thread grinds.  Sub-64-byte parts
// hash an EMPTY trimmed message, matching Python's ``data[:-64]`` slice
// semantics (such parts fail decode anyway; the slice parity keeps this
// function order-independent from the decode step).
PyObject* block_digests(PyObject*, PyObject* args) {
  PyObject* parts;
  if (!PyArg_ParseTuple(args, "O", &parts)) return nullptr;
  PyObject* seq = PySequence_Fast(parts, "parts must be a sequence");
  if (seq == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  std::vector<Py_buffer> views(static_cast<size_t>(n));
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* part = PySequence_Fast_GET_ITEM(seq, i);
    if (PyObject_GetBuffer(part, &views[i], PyBUF_SIMPLE) < 0) {
      for (Py_ssize_t j = 0; j < i; ++j) PyBuffer_Release(&views[j]);
      Py_DECREF(seq);
      return nullptr;
    }
  }
  std::vector<uint8_t> digests(static_cast<size_t>(n) * 64);
  Py_BEGIN_ALLOW_THREADS
  for (Py_ssize_t i = 0; i < n; ++i) {
    const uint8_t* data = static_cast<const uint8_t*>(views[i].buf);
    const size_t len = static_cast<size_t>(views[i].len);
    uint8_t* out = digests.data() + static_cast<size_t>(i) * 64;
    blake2b::hash256_pair(data, len, out, out + 32);
  }
  Py_END_ALLOW_THREADS
  for (Py_ssize_t i = 0; i < n; ++i) PyBuffer_Release(&views[i]);
  Py_DECREF(seq);
  PyObject* out = PyList_New(n);
  if (out == nullptr) return nullptr;
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* d = reinterpret_cast<const char*>(digests.data() +
                                                  static_cast<size_t>(i) * 64);
    PyObject* pair = Py_BuildValue("(y#y#)", d, (Py_ssize_t)32, d + 32,
                                   (Py_ssize_t)32);
    if (pair == nullptr) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, i, pair);
  }
  return out;
}

// encode_blocks_frame(tag, stamped, mono_ns, wall_ns, parts) -> bytes
//
// Whole-frame payload for the Blocks-shaped wire messages (tags 2/4/12):
// tag u8 [+ u64 sender-monotonic + u64 sender-wall when stamped] + u32
// count + per block u32 length + raw bytes — byte-identical to
// network.encode_message's Writer path (golden corpus pins it).  One call
// replaces the per-block Writer append loop the FrameCache paid per
// encode-once build; the copy runs with the GIL released.
PyObject* encode_blocks_frame(PyObject*, PyObject* args) {
  unsigned int tag;
  int stamped;
  unsigned long long mono_ns, wall_ns;
  PyObject* parts;
  if (!PyArg_ParseTuple(args, "IpKKO", &tag, &stamped, &mono_ns, &wall_ns,
                        &parts))
    return nullptr;
  PyObject* seq = PySequence_Fast(parts, "blocks must be a sequence");
  if (seq == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  std::vector<Py_buffer> views(static_cast<size_t>(n));
  Py_ssize_t total = 1 + (stamped ? 16 : 0) + 4;
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* part = PySequence_Fast_GET_ITEM(seq, i);
    if (PyObject_GetBuffer(part, &views[i], PyBUF_SIMPLE) < 0) {
      for (Py_ssize_t j = 0; j < i; ++j) PyBuffer_Release(&views[j]);
      Py_DECREF(seq);
      return nullptr;
    }
    total += 4 + views[i].len;
  }
  PyObject* out = PyBytes_FromStringAndSize(nullptr, total);
  if (out == nullptr) {
    for (Py_ssize_t i = 0; i < n; ++i) PyBuffer_Release(&views[i]);
    Py_DECREF(seq);
    return nullptr;
  }
  uint8_t* dst = reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(out));
  Py_BEGIN_ALLOW_THREADS
  uint8_t* p = dst;
  *p++ = static_cast<uint8_t>(tag);
  if (stamped) {
    std::memcpy(p, &mono_ns, 8);
    std::memcpy(p + 8, &wall_ns, 8);
    p += 16;
  }
  uint32_t count = static_cast<uint32_t>(n);
  std::memcpy(p, &count, 4);
  p += 4;
  for (Py_ssize_t i = 0; i < n; ++i) {
    uint32_t len = static_cast<uint32_t>(views[i].len);
    std::memcpy(p, &len, 4);
    std::memcpy(p + 4, views[i].buf, views[i].len);
    p += 4 + views[i].len;
  }
  Py_END_ALLOW_THREADS
  for (Py_ssize_t i = 0; i < n; ++i) PyBuffer_Release(&views[i]);
  Py_DECREF(seq);
  return out;
}

// split_frames(buffer, start, have, max_frame)
//   -> ([(payload_off, payload_len)...], new_start, oversized_len)
//
// The _FrameReceiver assembly-buffer walk (network.py:_parse): split
// [start, have) into complete 4-byte-length-prefixed frames.  Returns the
// payload spans (the caller wraps them as memoryviews — the last step that
// must touch Python objects), the new parse cursor, and the offending
// length when a prefix exceeds ``max_frame`` (0 = none; the caller severs
// exactly as the pure path does).
PyObject* split_frames(PyObject*, PyObject* args) {
  Py_buffer buf;
  unsigned long long start_arg, have_arg, max_frame;
  if (!PyArg_ParseTuple(args, "y*KKK", &buf, &start_arg, &have_arg,
                        &max_frame))
    return nullptr;
  const uint8_t* data = static_cast<const uint8_t*>(buf.buf);
  Py_ssize_t start = static_cast<Py_ssize_t>(start_arg);
  Py_ssize_t have = static_cast<Py_ssize_t>(have_arg);
  if (have > buf.len) have = buf.len;
  std::vector<std::pair<Py_ssize_t, Py_ssize_t>> spans;
  unsigned long long oversized = 0;
  Py_BEGIN_ALLOW_THREADS
  while (have - start >= 4) {
    uint32_t length = read_u32(data + start);
    if (static_cast<unsigned long long>(length) > max_frame) {
      oversized = length;
      break;
    }
    Py_ssize_t end = start + 4 + static_cast<Py_ssize_t>(length);
    if (end > have) break;
    spans.emplace_back(start + 4, static_cast<Py_ssize_t>(length));
    start = end;
  }
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&buf);
  PyObject* out = PyList_New(static_cast<Py_ssize_t>(spans.size()));
  if (out == nullptr) return nullptr;
  for (size_t i = 0; i < spans.size(); ++i) {
    PyObject* pair =
        Py_BuildValue("(nn)", spans[i].first, spans[i].second);
    if (pair == nullptr) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, static_cast<Py_ssize_t>(i), pair);
  }
  return Py_BuildValue("(NnK)", out, start, oversized);
}

// parse_blocks_spans(payload) -> (tag, mono_ns, wall_ns, [(off, len)...])
//
// Native sibling of decode_message's Blocks-shaped branches (tags 2/4/12):
// validates the whole payload body and returns per-block (offset, length)
// spans — the caller builds zero-copy sub-views, deferring Python object
// creation to the last step.  Rejection cases and MESSAGES are
// byte-identical to serde.Reader's ("truncated input: need N bytes at P,
// have H", "trailing garbage: N bytes"), so torn-frame error shapes stay
// indistinguishable across the native/fallback paths (parity corpus).
PyObject* parse_blocks_spans(PyObject*, PyObject* args) {
  Py_buffer buf;
  if (!PyArg_ParseTuple(args, "y*", &buf)) return nullptr;
  const uint8_t* d = static_cast<const uint8_t*>(buf.buf);
  const Py_ssize_t n = buf.len;
  Py_ssize_t pos = 0;
  auto fail_need = [&](Py_ssize_t need) -> PyObject* {
    PyErr_Format(PyExc_ValueError,
                 "truncated input: need %zd bytes at %zd, have %zd", need,
                 pos, n);
    PyBuffer_Release(&buf);
    return nullptr;
  };
  if (n < 1) return fail_need(1);
  const uint8_t tag = d[0];
  pos = 1;
  unsigned long long mono = 0, wall = 0;
  if (tag == 12) {  // _MSG_BLOCKS_TIMESTAMPED: two u64 sender stamps first
    if (pos + 8 > n) return fail_need(8);
    mono = read_u64(d + pos);
    pos += 8;
    if (pos + 8 > n) return fail_need(8);
    wall = read_u64(d + pos);
    pos += 8;
  } else if (tag != 2 && tag != 4) {  // _MSG_BLOCKS / _MSG_RESPONSE
    PyErr_Format(PyExc_ValueError, "not a blocks-shaped frame: tag %d", tag);
    PyBuffer_Release(&buf);
    return nullptr;
  }
  if (pos + 4 > n) return fail_need(4);
  const uint32_t count = read_u32(d + pos);
  pos += 4;
  std::vector<std::pair<Py_ssize_t, Py_ssize_t>> spans;
  for (uint32_t i = 0; i < count; ++i) {
    if (pos + 4 > n) return fail_need(4);
    const uint32_t len = read_u32(d + pos);
    pos += 4;
    if (pos + static_cast<Py_ssize_t>(len) > n)
      return fail_need(static_cast<Py_ssize_t>(len));
    spans.emplace_back(pos, static_cast<Py_ssize_t>(len));
    pos += static_cast<Py_ssize_t>(len);
  }
  if (pos != n) {
    PyErr_Format(PyExc_ValueError, "trailing garbage: %zd bytes", n - pos);
    PyBuffer_Release(&buf);
    return nullptr;
  }
  PyBuffer_Release(&buf);
  PyObject* out = PyList_New(static_cast<Py_ssize_t>(spans.size()));
  if (out == nullptr) return nullptr;
  for (size_t i = 0; i < spans.size(); ++i) {
    PyObject* pair = Py_BuildValue("(nn)", spans[i].first, spans[i].second);
    if (pair == nullptr) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, static_cast<Py_ssize_t>(i), pair);
  }
  return Py_BuildValue("(BKKN)", tag, mono, wall, out);
}

PyMethodDef kMethods[] = {
    {"decode_register", decode_register, METH_VARARGS,
     "Register the Python statement/reference classes for decode_block."},
    {"decode_block", decode_block, METH_VARARGS,
     "Decode a StatementBlock wire frame into its component tuple."},
    {"wal_scan", wal_scan, METH_VARARGS,
     "Scan crc-framed WAL entries; returns (pos, tag, off, len) tuples."},
    {"frame_entry", frame_entry, METH_VARARGS,
     "Assemble one framed WAL entry (header + parts) with single-pass crc."},
    {"va_new", va_new, METH_VARARGS, "New vote-aggregator core."},
    {"va_bind", va_bind, METH_VARARGS, "Bind committee stakes + threshold."},
    {"va_register", va_register, METH_VARARGS,
     "Register a shared range with the author's self-vote."},
    {"va_vote", va_vote, METH_VARARGS,
     "Tally a vote range; returns (certified ranges, violation offset)."},
    {"va_is_processed", va_is_processed, METH_VARARGS,
     "Was this (block, offset) certified?"},
    {"va_pending_len", va_pending_len, METH_VARARGS,
     "Number of blocks with pending aggregations."},
    {"va_items", va_items, METH_VARARGS, "Snapshot pending ranges."},
    {"va_state", va_state, METH_VARARGS,
     "Canonical state snapshot bytes (committee.py state() layout)."},
    {"va_load", va_load, METH_VARARGS, "Restore one pending range."},
    {"block_digests", block_digests, METH_VARARGS,
     "Batched blake2b-256 (digest, signed-prehash) pairs over N blocks."},
    {"encode_blocks_frame", encode_blocks_frame, METH_VARARGS,
     "Serialize a whole Blocks-shaped frame payload in one call."},
    {"split_frames", split_frames, METH_VARARGS,
     "Split a length-prefixed assembly buffer into payload spans."},
    {"parse_blocks_spans", parse_blocks_spans, METH_VARARGS,
     "Validate a Blocks-shaped payload; returns per-block (off, len) spans."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {
    PyModuleDef_HEAD_INIT, "_native",
    "Native runtime helpers (WAL framing/scan, decode, data plane).", -1,
    kMethods,
};

}  // namespace

PyMODINIT_FUNC PyInit__native(void) { return PyModule_Create(&kModule); }
