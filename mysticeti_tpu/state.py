"""Crash-recovery state folding: WAL replay -> core + commit-observer state.

Capability parity with ``mysticeti-core/src/state.rs``:

* ``CoreRecoveredState``  (state.rs:13-20) — block store, last own block, pending
  proposal queue, handler state snapshot, blocks to re-run through the handler,
  last committed leader.
* ``CommitObserverRecoveredState`` (commit_observer.rs) — committed sub-dags +
  committed-transaction aggregator state.
* ``RecoveredStateBuilder`` (state.rs:23-95) — folds the five WAL entry kinds:
  block/payload entries accumulate into the pending queue; an own-block entry
  drops every pending entry before its ``next_entry`` cursor (those were consumed
  by that proposal, state.rs:49-54); a state snapshot clears the unprocessed-block
  replay list (state.rs:56-59); commit entries track commit history + state.

``MetaStatement`` (core.rs:61-65) lives here so both ``core`` and this module can
use it without a cycle: Include(reference) | Payload(list-of-statements).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple, Union

from collections import deque

from .block_store import CommitData, OwnBlockData
from .serde import Reader, Writer
from .types import (
    BaseStatement,
    BlockReference,
    StatementBlock,
    decode_statement,
    encode_statements,
)
from .wal import WalPosition


@dataclass(frozen=True)
class Include:
    """Pending reference to another authority's block (core.rs:63)."""

    reference: BlockReference


@dataclass(frozen=True)
class Payload:
    """Pending own statements produced by the block handler (core.rs:64)."""

    statements: Tuple[BaseStatement, ...]


MetaStatement = Union[Include, Payload]


def encode_payload(statements) -> bytes:
    w = Writer()
    w.u32(len(statements))
    encode_statements(w, statements)
    return w.finish()


def decode_payload(data: bytes) -> Tuple[BaseStatement, ...]:
    r = Reader(data)
    statements = tuple(decode_statement(r) for _ in range(r.u32()))
    r.expect_done()
    return statements


@dataclass
class CoreRecoveredState:
    """Everything ``Core.open`` needs to resume exactly where the crash left off."""

    block_store: object  # BlockStore (untyped to avoid cycle)
    last_own_block: Optional[OwnBlockData]
    pending: Deque[Tuple[WalPosition, MetaStatement]]
    state: Optional[bytes]
    unprocessed_blocks: List[StatementBlock]
    last_committed_leader: Optional[BlockReference]


@dataclass
class CommitObserverRecoveredState:
    sub_dags: List[CommitData] = field(default_factory=list)
    state: Optional[bytes] = None


class RecoveredStateBuilder:
    """Folds WAL replay entries in log order (state.rs:23-95)."""

    def __init__(self) -> None:
        # position -> raw meta statement; kept sorted by insertion (wal order).
        self._pending: Dict[WalPosition, MetaStatement] = {}
        self._last_own_block: Optional[OwnBlockData] = None
        self._state: Optional[bytes] = None
        self._unprocessed_blocks: List[StatementBlock] = []
        self._last_committed_leader: Optional[BlockReference] = None
        self._committed_sub_dags: List[CommitData] = []
        self._committed_state: Optional[bytes] = None

    def block(self, pos: WalPosition, block: StatementBlock) -> None:
        self._pending[pos] = Include(block.reference)
        self._unprocessed_blocks.append(block)

    def payload(self, pos: WalPosition, payload: bytes) -> None:
        self._pending[pos] = Payload(decode_payload(payload))

    def own_block(self, own: OwnBlockData) -> None:
        # Drop pending entries the proposal already consumed (state.rs:49-54);
        # next_entry == POSITION_MAX drops everything.
        self._pending = {
            pos: st for pos, st in self._pending.items() if pos >= own.next_entry
        }
        self._unprocessed_blocks.append(own.block)
        self._last_own_block = own

    def state(self, state: bytes) -> None:
        self._state = state
        self._unprocessed_blocks.clear()

    def commit_data(self, commits: List[CommitData], committed_state: bytes) -> None:
        for commit in commits:
            self._last_committed_leader = commit.leader
            if self._committed_sub_dags:
                assert commit.height > self._committed_sub_dags[-1].height
            self._committed_sub_dags.append(commit)
        self._committed_state = committed_state

    def build(
        self, block_store
    ) -> Tuple[CoreRecoveredState, CommitObserverRecoveredState]:
        pending: Deque[Tuple[WalPosition, MetaStatement]] = deque(
            sorted(self._pending.items())
        )
        core = CoreRecoveredState(
            block_store=block_store,
            last_own_block=self._last_own_block,
            pending=pending,
            state=self._state,
            unprocessed_blocks=self._unprocessed_blocks,
            last_committed_leader=self._last_committed_leader,
        )
        observer = CommitObserverRecoveredState(
            sub_dags=self._committed_sub_dags,
            state=self._committed_state,
        )
        return core, observer
