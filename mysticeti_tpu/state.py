"""Crash-recovery state folding: WAL replay -> core + commit-observer state.

Capability parity with ``mysticeti-core/src/state.rs``:

* ``CoreRecoveredState``  (state.rs:13-20) — block store, last own block, pending
  proposal queue, handler state snapshot, blocks to re-run through the handler,
  last committed leader.
* ``CommitObserverRecoveredState`` (commit_observer.rs) — committed sub-dags +
  committed-transaction aggregator state.
* ``RecoveredStateBuilder`` (state.rs:23-95) — folds the five WAL entry kinds:
  block/payload entries accumulate into the pending queue; an own-block entry
  drops every pending entry before its ``next_entry`` cursor (those were consumed
  by that proposal, state.rs:49-54); a state snapshot clears the unprocessed-block
  replay list (state.rs:56-59); commit entries track commit history + state.

``MetaStatement`` (core.rs:61-65) lives here so both ``core`` and this module can
use it without a cycle: Include(reference) | Payload(list-of-statements).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple, Union

from collections import deque

from .block_store import CommitData, OwnBlockData
from .serde import Reader, Writer
from .types import (
    BaseStatement,
    BlockReference,
    StatementBlock,
    decode_statement,
    encode_statements,
)
from .wal import WalPosition


@dataclass(frozen=True)
class Include:
    """Pending reference to another authority's block (core.rs:63)."""

    reference: BlockReference


@dataclass(frozen=True)
class Payload:
    """Pending own statements produced by the block handler (core.rs:64)."""

    statements: Tuple[BaseStatement, ...]


MetaStatement = Union[Include, Payload]


def encode_payload(statements) -> bytes:
    w = Writer()
    w.u32(len(statements))
    encode_statements(w, statements)
    return w.finish()


def decode_payload(data: bytes) -> Tuple[BaseStatement, ...]:
    r = Reader(data)
    statements = tuple(decode_statement(r) for _ in range(r.u32()))
    r.expect_done()
    return statements


@dataclass
class CoreRecoveredState:
    """Everything ``Core.open`` needs to resume exactly where the crash left off."""

    block_store: object  # BlockStore (untyped to avoid cycle)
    last_own_block: Optional[OwnBlockData]
    pending: Deque[Tuple[WalPosition, MetaStatement]]
    state: Optional[bytes]
    unprocessed_blocks: List[StatementBlock]
    last_committed_leader: Optional[BlockReference]
    # Storage-lifecycle baseline (storage.py): the commit chain as of the end
    # of replay, and how much replay actually cost — checkpointed boots
    # assert replayed_bytes << lifetime WAL bytes.
    commit_height: int = 0
    chain_digest: bytes = b""
    gc_round: int = 0
    replay_start: WalPosition = 0
    replayed_bytes: int = 0
    checkpoint_height: int = 0
    # Reconfiguration (reconfig.py): the serialized epoch chain from the
    # recovering checkpoint/snapshot, plus the commits replayed AFTER that
    # baseline — Core re-scans them so a crash between a boundary commit and
    # the next checkpoint still reboots into the right epoch.
    epoch_chain: bytes = b""
    recovered_commits: List[CommitData] = field(default_factory=list)
    # Execution plane (execution.py): the serialized account state from the
    # recovering checkpoint/snapshot; Core re-folds the post-baseline
    # ``recovered_commits`` on top so the node reboots onto the exact root
    # it crashed out of.
    exec_state: bytes = b""


@dataclass
class CommitObserverRecoveredState:
    sub_dags: List[CommitData] = field(default_factory=list)
    state: Optional[bytes] = None
    # Checkpoint/snapshot baseline: the linearizer resumes at ``base_height``
    # with ``base_committed`` already sequenced and everything below
    # ``gc_round`` settled (storage.py).  ``sub_dags`` then carries only the
    # commits replayed AFTER the baseline.
    base_height: int = 0
    base_committed: List[BlockReference] = field(default_factory=list)
    gc_round: int = 0


class RecoveredStateBuilder:
    """Folds WAL replay entries in log order (state.rs:23-95)."""

    def __init__(self) -> None:
        # position -> raw meta statement; kept sorted by insertion (wal order).
        self._pending: Dict[WalPosition, MetaStatement] = {}
        self._last_own_block: Optional[OwnBlockData] = None
        self._state: Optional[bytes] = None
        self._unprocessed_blocks: List[StatementBlock] = []
        self._last_committed_leader: Optional[BlockReference] = None
        self._committed_sub_dags: List[CommitData] = []
        self._committed_state: Optional[bytes] = None
        # Storage-lifecycle chain state (storage.py): folded from the
        # checkpoint/snapshot baseline plus every replayed commit entry.
        self._commit_height = 0
        self._chain_digest = b"\x00" * 32
        self._gc_round = 0
        self._base_height = 0
        self._base_committed: List[BlockReference] = []
        self._checkpoint_height = 0
        self._replay_start: WalPosition = 0
        self._replayed_bytes = 0
        self._epoch_chain = b""
        self._exec_state = b""

    def seed_checkpoint(self, checkpoint) -> None:
        """Boot the fold from a durable checkpoint instead of genesis: the
        pending queue, own proposal, handler state, and commit baseline come
        from the checkpoint; replay then starts at its WAL position."""
        self._pending = dict(checkpoint.pending)
        self._last_own_block = checkpoint.last_own_block
        self._state = checkpoint.handler_state
        self._last_committed_leader = checkpoint.last_committed_leader
        self._committed_state = checkpoint.committed_state
        self._commit_height = checkpoint.commit_height
        self._chain_digest = checkpoint.chain_digest
        self._gc_round = checkpoint.gc_round
        self._base_height = checkpoint.commit_height
        self._base_committed = list(checkpoint.committed_refs)
        self._checkpoint_height = checkpoint.commit_height
        self._replay_start = checkpoint.wal_position
        self._epoch_chain = checkpoint.epoch_chain
        self._exec_state = checkpoint.exec_state

    def snapshot(self, manifest) -> None:
        """Fold a persisted snapshot-adoption entry (WAL_ENTRY_SNAPSHOT): the
        node adopted a remote commit baseline mid-run; recovery must resume
        from the SAME baseline, and every commit folded before the adoption
        sits below it (the observer must not re-deliver them)."""
        self._last_committed_leader = manifest.last_committed_leader
        self._commit_height = manifest.commit_height
        self._chain_digest = manifest.chain_digest
        self._gc_round = max(self._gc_round, manifest.gc_round)
        self._base_height = manifest.commit_height
        self._base_committed = list(manifest.committed_refs)
        self._committed_sub_dags = []
        if manifest.epoch_chain:
            self._epoch_chain = manifest.epoch_chain
        if manifest.exec_state:
            self._exec_state = manifest.exec_state

    def note_replayed(self, replayed_bytes: int) -> None:
        self._replayed_bytes = replayed_bytes

    def note_retired_floor(self, floor: int) -> None:
        """Blocks below ``floor`` are known-gone (their segments were GC'd
        after the recovering checkpoint was written): the recovered DAG
        floor must cover them so nothing re-fetches settled history."""
        self._gc_round = max(self._gc_round, floor)

    def block(self, pos: WalPosition, block: StatementBlock) -> None:
        self._pending[pos] = Include(block.reference)
        self._unprocessed_blocks.append(block)

    def payload(self, pos: WalPosition, payload: bytes) -> None:
        self._pending[pos] = Payload(decode_payload(payload))

    def own_block(self, own: OwnBlockData) -> None:
        # Drop pending entries the proposal already consumed (state.rs:49-54);
        # next_entry == POSITION_MAX drops everything.
        self._pending = {
            pos: st for pos, st in self._pending.items() if pos >= own.next_entry
        }
        self._unprocessed_blocks.append(own.block)
        self._last_own_block = own

    def state(self, state: bytes) -> None:
        self._state = state
        self._unprocessed_blocks.clear()

    def commit_data(self, commits: List[CommitData], committed_state: bytes) -> None:
        from .storage import fold_leader_digest

        for commit in commits:
            self._last_committed_leader = commit.leader
            if self._committed_sub_dags:
                assert commit.height > self._committed_sub_dags[-1].height
            self._committed_sub_dags.append(commit)
            self._commit_height = commit.height
            self._chain_digest = fold_leader_digest(
                self._chain_digest, commit.leader
            )
        self._committed_state = committed_state

    def build(
        self, block_store
    ) -> Tuple[CoreRecoveredState, CommitObserverRecoveredState]:
        pending: Deque[Tuple[WalPosition, MetaStatement]] = deque(
            sorted(self._pending.items())
        )
        core = CoreRecoveredState(
            block_store=block_store,
            last_own_block=self._last_own_block,
            pending=pending,
            state=self._state,
            unprocessed_blocks=self._unprocessed_blocks,
            last_committed_leader=self._last_committed_leader,
            commit_height=self._commit_height,
            chain_digest=self._chain_digest,
            gc_round=self._gc_round,
            replay_start=self._replay_start,
            replayed_bytes=self._replayed_bytes,
            checkpoint_height=self._checkpoint_height,
            epoch_chain=self._epoch_chain,
            recovered_commits=list(self._committed_sub_dags),
            exec_state=self._exec_state,
        )
        observer = CommitObserverRecoveredState(
            sub_dags=self._committed_sub_dags,
            state=self._committed_state,
            base_height=self._base_height,
            base_committed=self._base_committed,
            gc_round=self._gc_round,
        )
        return core, observer
