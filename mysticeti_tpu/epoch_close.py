"""Epoch-close state machine: Open -> BeginChange -> SafeToClose.

Capability parity with ``mysticeti-core/src/epoch_close.rs``:

* ``epoch_change_begun`` (:24-29) — entered when the committed-leader round passes
  ``rounds_in_epoch`` (driven from Core.try_commit, core.rs:376-379).
* ``observe_committed_block`` (:31-42) — once committed blocks carrying the epoch
  marker reach quorum stake, the epoch is safe to close; the closing timestamp is
  recorded for the shutdown grace logic (net_sync.rs:466-494).
"""
from __future__ import annotations

import time

from .committee import Committee, QUORUM, StakeAggregator
from .types import StatementBlock

OPEN = 0
BEGIN_CHANGE = 1
SAFE_TO_CLOSE = 2


class EpochManager:
    __slots__ = ("status", "change_aggregator", "epoch_close_time_ms")

    def __init__(self) -> None:
        self.status = OPEN
        self.change_aggregator = StakeAggregator(QUORUM)
        self.epoch_close_time_ms = 0

    def epoch_change_begun(self) -> None:
        if self.status == OPEN:
            self.status = BEGIN_CHANGE

    def observe_committed_block(self, block: StatementBlock, committee: Committee) -> None:
        if not block.epoch_changed():
            return
        is_quorum = self.change_aggregator.add(block.author(), committee)
        if is_quorum and self.status != SAFE_TO_CLOSE:
            # Agreement + total ordering imply we saw BeginChange first.
            assert self.status == BEGIN_CHANGE
            self.status = SAFE_TO_CLOSE
            self.epoch_close_time_ms = int(time.time() * 1000)

    def changing(self) -> bool:
        return self.status != OPEN

    def closed(self) -> bool:
        return self.status == SAFE_TO_CLOSE

    def closing_time(self) -> int:
        return self.epoch_close_time_ms
