"""Node configuration: protocol parameters, synchronizer tuning, storage layout.

Capability parity with ``mysticeti-core/src/config.rs``:

* ``Parameters`` (config.rs:38-117) — identifiers (hostname/ports per authority),
  wave length, leader timeout, rounds per epoch, shutdown grace, leaders per
  round, pipelining, store retention, cleanup switch, synchronizer parameters,
  network latency breaker threshold.
* ``SynchronizerParameters`` (config.rs:76-100).
* YAML print/load (config.rs:16-29).
* ``PrivateConfig`` / ``StorageDir`` (config.rs:197-251) — per-authority key +
  storage paths: wal, certified tx log, committed tx log.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field, asdict
from typing import List, Optional, Tuple

import yaml

ROUNDS_IN_EPOCH_MAX = 2**63  # effectively "never close the epoch"

DEFAULT_PORT_BASE = 1500
DEFAULT_METRICS_PORT_OFFSET = 1000


@dataclass
class Identifier:
    """Network identity of one authority (config.rs:31-36)."""

    hostname: str
    port: int
    metrics_port: int


@dataclass
class SynchronizerParameters:
    """Dissemination/fetch tuning (config.rs:76-100).

    ``disseminate_others_blocks`` arms the helper streams the reference
    keeps dormant (synchronizer.rs:169-205): when on, a node missing a live
    connection to some authority asks up to ``maximum_helpers_per_authority``
    of its connected peers (``absolute_maximum_helpers`` total across
    authorities) to relay that authority's blocks as a push stream.  Off by
    default — it emits a wire tag pre-knob receivers reset on
    (docs/wire-format.md §7), and the pull fetcher already covers the gap
    at higher latency."""

    absolute_maximum_helpers: int = 32
    maximum_helpers_per_authority: int = 2
    batch_size: int = 100
    sample_precision_s: float = 0.25
    stream_interval_s: float = 1.0
    new_stream_threshold: int = 10
    disseminate_others_blocks: bool = False
    # Stamp outgoing block push frames with the sender's monotonic+wall
    # clocks (wire tag 12, docs/wire-format.md §5): the receiver surfaces
    # per-link transit (dissemination_transit_seconds{peer}) and records
    # `transit` spans the fleet-trace merger's skew estimator aligns.  Off
    # by default — like the other soft tags, pre-knob receivers reset the
    # connection on it.
    timestamp_frames: bool = False


@dataclass
class StorageParameters:
    """The storage lifecycle plane's knobs, unified (storage.py).

    Retention used to be scattered: ``Parameters.enable_cleanup`` switched
    the periodic cleanup task, ``Parameters.store_retain_rounds`` sized the
    in-memory cache window, and nothing at all bounded the disk.  This block
    owns all of it:

    * ``segment_bytes`` — the WAL rolls to a new ``wal.NNNNNN`` segment when
      the active one would exceed this size (``<= 0`` = legacy single-file
      log: no rolling, no checkpoints, no GC).
    * ``checkpoint_interval`` — commits between durable checkpoints; ``0``
      disables checkpointing (recovery then replays the whole log).
    * ``gc_depth`` — rounds retained behind the last committed leader;
      segments whose every block is older are deleted.  ``0`` = never GC.
    * ``retain_rounds`` — the in-memory cache-unload window (the old
      ``store_retain_rounds``); independent of the on-disk ``gc_depth``.
    * ``snapshot_catchup`` — arm the snapshot catch-up streams (wire tags
      9/10/11, docs/wire-format.md §5): a far-behind peer bootstraps from a
      serving node's commit baseline + post-GC block window instead of
      pulling all history block-by-block.  Off by default: it is a soft
      wire extension pre-knob receivers reset on.
    * ``catchup_threshold_commits`` — minimum commit-height gap before a
      snapshot is requested/served (below it, the ordinary streams win).
    """

    segment_bytes: int = 64 * 1024 * 1024
    checkpoint_interval: int = 512
    gc_depth: int = 10_000
    retain_rounds: int = 500
    enable_cleanup: bool = True
    snapshot_catchup: bool = False
    catchup_threshold_commits: int = 200


@dataclass
class IngressParameters:
    """The overload-resilient ingress plane's knobs (ingress.py).

    Transactions used to enter through ``BenchmarkFastPathBlockHandler.submit``
    into an UNBOUNDED queue with nothing but the per-block SOFT_MAX drain cap:
    past saturation the queue (and end-to-end latency) grew without limit and
    committed throughput collapsed (MAXLOAD r4: 40.3k committed at 57.6k
    offered).  This block configures the bounded, admission-controlled mempool
    and the client gateway that replace it:

    * ``mempool_max_transactions`` / ``mempool_max_bytes`` — hard caps on the
      pool; submissions beyond them are SHED with a typed reject, never
      silently queued or dropped.
    * ``lane_max_transactions`` — per-client fairness-lane cap.  The
      default equals the pool cap (single-tenant benchmark profile: the one
      generator lane may use the whole pool, so the POOL watermark — the
      AIMD congestion signal — is reachable); multi-tenant deployments set
      it lower so one flooding client fills its own lane, not the pool.
    * ``priority_weight`` — weighted-round-robin drain weight of priority
      lanes relative to normal ones.
    * ``dedup_window`` — recently-admitted transaction keys remembered for
      nonce/digest dedup (count-bounded so seeded sims stay deterministic).
    * ``admission`` — arm the AIMD admission controller: the admitted rate
      closes the loop from live core signals (WAL backlog, core owner queue
      depth, verifier pipeline occupancy, mempool occupancy) so at 2-5x
      offered overload the core keeps running at its measured saturation
      point instead of collapsing.
    * ``admission_initial_tx_s`` / ``admission_min_tx_s`` /
      ``admission_additive_tx_s`` / ``admission_decrease_factor`` — AIMD
      shape: additive raise per tick while healthy, multiplicative cut on
      congestion, floor so a transient stall cannot starve ingress forever.
    * ``high_watermark`` / ``low_watermark`` — mempool occupancy fractions:
      above high = congested (cut), below low = recovered (raise); between
      them the rate holds (hysteresis, so the controller cannot flap).
    * ``queued_watermark`` — occupancy above which an accepted submission is
      acknowledged QUEUED instead of ACK (the gateway's early-backpressure
      hint to well-behaved clients).
    * ``max_per_proposal`` — per-proposal drain budget (0 = the handler's
      SOFT_MAX); sims use a small value to reproduce saturation in virtual
      time.
    * ``gateway_port_base`` — when > 0, serve the client RPC gateway on
      ``gateway_port_base + authority`` (wire tags 13-16,
      docs/wire-format.md); 0 = no gateway listener.
    * ``tick_interval_s`` — admission controller cadence.
    * ``shed_log_capacity`` — bounded structured shed log (the deterministic
      overload sim asserts it byte-identical across same-seed runs).
    * ``finality_sample_every`` — the finality SLI plane's content-based
      count-sampling stride (finality.py): an ingress key participates in
      the submit→finality phase join iff ``key_bytes % N == 0``, so all
      nodes (and client generators) sample the SAME transactions without
      coordination.  1 = every transaction, 0 = tracker disabled.
    """

    enabled: bool = True
    mempool_max_transactions: int = 200_000
    mempool_max_bytes: int = 256 * 1024 * 1024
    lane_max_transactions: int = 200_000
    priority_weight: int = 4
    dedup_window: int = 100_000
    admission: bool = True
    admission_initial_tx_s: float = 100_000.0
    admission_min_tx_s: float = 500.0
    admission_max_tx_s: float = 1_000_000.0
    admission_additive_tx_s: float = 1_000.0
    admission_decrease_factor: float = 0.7
    high_watermark: float = 0.85
    low_watermark: float = 0.5
    queued_watermark: float = 0.5
    max_per_proposal: int = 0
    gateway_port_base: int = 0
    tick_interval_s: float = 0.5
    shed_log_capacity: int = 10_000
    finality_sample_every: int = 16


@dataclass
class Parameters:
    identifiers: List[Identifier] = field(default_factory=list)
    wave_length: int = 3
    leader_timeout_s: float = 2.0
    rounds_in_epoch: int = ROUNDS_IN_EPOCH_MAX
    shutdown_grace_period_s: float = 2.0
    number_of_leaders: int = 1
    enable_pipelining: bool = True
    # Leader liveness scoring (core.ready_new_block): stop gating proposals
    # on a leader whose blocks have not been accepted locally for more than
    # this many rounds (it is crashed, partitioned away, withholding, or
    # signing invalidly — the leader timeout would fire anyway).  0 (the
    # default) disables the filter: rounds are a LOAD-dependent clock, and
    # on a contended host an honest-but-stalled leader can fall a fixed
    # round count behind in well under the leader timeout — measured 18%
    # fewer committed leaders on a loaded 4-validator testbed with an
    # 8-round horizon, every lost slot an honest leader skipped.  The
    # Byzantine scenario profile (scenarios.py) arms it at 4 where silent
    # adversaries are declared and the round clock is the sim's own.
    leader_liveness_horizon_rounds: int = 0
    # Commit-anchored epoch reconfiguration (reconfig.py): committee-change
    # transactions in the committed sequence derive new epochs; the commit
    # rule becomes slot-sequential (one decided leader per try_commit batch)
    # so every node switches stake arithmetic at the same sequence point,
    # and the EpochInfo wire extension (tag 17, docs/wire-format.md §8) is
    # armed.  Off by default: pre-knob peers reset connections on the soft
    # tag, and the frozen-committee fast path skips the per-commit scan.
    reconfig: bool = False
    # Deterministic execution plane (execution.py): fold every committed
    # sub-dag through the account/transfer state machine and chain a
    # per-commit state root.  Off by default: the fold costs a per-commit
    # payload scan, and the checkpoint/manifest soft tail grows with the
    # account table.
    execution: bool = False
    # Legacy spellings of the storage block's knobs: accepted at construction
    # and in YAML for back-compat, migrated into ``storage`` by __post_init__
    # (which then rebinds these names to the storage block's values, so every
    # existing reader keeps working).
    enable_cleanup: Optional[bool] = None
    store_retain_rounds: Optional[int] = None
    storage: StorageParameters = field(default_factory=StorageParameters)
    synchronizer: SynchronizerParameters = field(default_factory=SynchronizerParameters)
    ingress: IngressParameters = field(default_factory=IngressParameters)
    network_connection_max_latency_s: float = 5.0

    def __post_init__(self) -> None:
        if self.enable_cleanup is not None:
            self.storage.enable_cleanup = bool(self.enable_cleanup)
        if self.store_retain_rounds is not None:
            self.storage.retain_rounds = int(self.store_retain_rounds)
        self.enable_cleanup = self.storage.enable_cleanup
        self.store_retain_rounds = self.storage.retain_rounds

    @classmethod
    def new_for_benchmarks(cls, ips: List[str]) -> "Parameters":
        """Benchmark defaults mirroring Parameters::new_for_benchmarks (config.rs:57-72).

        ``MYSTICETI_RETAIN_ROUNDS`` (genesis-time env) overrides the store
        retain window: crash-recovery experiments need peers to retain the
        whole downtime's worth of rounds or the rebooted node cannot fetch
        its backlog (the default 500 rounds is seconds at saturation)."""
        identifiers = [
            Identifier(
                hostname=ip,
                port=DEFAULT_PORT_BASE + i,
                metrics_port=DEFAULT_PORT_BASE + DEFAULT_METRICS_PORT_OFFSET + i,
            )
            for i, ip in enumerate(ips)
        ]
        overrides = {}
        retain = int(os.environ.get("MYSTICETI_RETAIN_ROUNDS", "0") or 0)
        if retain > 0:
            overrides["store_retain_rounds"] = retain
        # Local fleets don't need the 2 s WAN leader timeout; fault benches
        # override it so a crashed leader's slots cost ms, not seconds.
        timeout = float(os.environ.get("MYSTICETI_LEADER_TIMEOUT", "0") or 0)
        if timeout > 0:
            overrides["leader_timeout_s"] = timeout
        return cls(identifiers=identifiers, **overrides)

    def address(self, authority: int) -> Tuple[str, int]:
        ident = self.identifiers[authority]
        return ident.hostname, ident.port

    def metrics_address(self, authority: int) -> Tuple[str, int]:
        ident = self.identifiers[authority]
        return ident.hostname, ident.metrics_port

    def all_network_addresses(self) -> List[Tuple[str, int]]:
        return [(i.hostname, i.port) for i in self.identifiers]

    # -- YAML round-trip (config.rs:16-29) --

    def dump(self, path: str) -> None:
        raw = asdict(self)
        # The storage block is the canonical spelling; the migrated legacy
        # keys would otherwise shadow a hand-edited storage block on reload.
        raw.pop("enable_cleanup", None)
        raw.pop("store_retain_rounds", None)
        with open(path, "w") as f:
            yaml.safe_dump(raw, f, sort_keys=False)

    @classmethod
    def load(cls, path: str) -> "Parameters":
        with open(path) as f:
            raw = yaml.safe_load(f)
        sync = SynchronizerParameters(**raw.pop("synchronizer", {}))
        storage = StorageParameters(**raw.pop("storage", {}))
        # Absent on pre-r11 parameter files: defaults apply (the ingress
        # plane is on with generous caps, same as a fresh genesis).
        ingress = IngressParameters(**raw.pop("ingress", {}))
        identifiers = [Identifier(**i) for i in raw.pop("identifiers", [])]
        return cls(
            identifiers=identifiers, synchronizer=sync, storage=storage,
            ingress=ingress, **raw
        )


@dataclass
class PrivateConfig:
    """Per-authority private material + storage paths (config.rs:197-251)."""

    authority: int
    storage_path: str
    keypair_seed: bytes = b""

    @classmethod
    def new_in_dir(cls, authority: int, dir_: str) -> "PrivateConfig":
        os.makedirs(dir_, exist_ok=True)
        return cls(authority=authority, storage_path=dir_)

    def wal(self) -> str:
        return os.path.join(self.storage_path, "wal")

    def certified_transactions_log(self) -> str:
        return os.path.join(self.storage_path, "certified.txt")

    def committed_transactions_log(self) -> str:
        return os.path.join(self.storage_path, "committed.txt")
