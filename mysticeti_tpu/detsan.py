"""Run-twice determinism sanitizer: the dynamic twin of the ``sim-taint`` lint.

The static rule (:mod:`mysticeti_tpu.analysis.detflow`) proves the *absence*
of known nondeterminism patterns; this module catches the leaks the lint
cannot see — C extensions, dict-iteration drift, an unannotated thread —
by executing the same seeded simulation twice and comparing per-event
digests of the scheduler's behavior:

* :class:`DetsanRecorder` hooks the :class:`DeterministicLoop` callback
  plumbing (``run_simulation(..., detsan=recorder)``) and chains a digest
  over every executed event: ``(event index, virtual time, callback label,
  ready/timer queue depths)``.  The trace is bounded (``cap`` events kept;
  counting and chaining continue past it), so a multi-million-event sim
  costs one hash per event and a fixed amount of memory.

* :func:`find_divergence` compares two recordings.  Because digests are
  *chained*, "runs agree through event i" is monotone in ``i`` — one bit
  flips and stays flipped — so a binary search over the stored prefix
  pinpoints the **first diverging event** in O(log n) digest comparisons,
  naming the callback and virtual time on both sides.

* :class:`Tripwire` is the runtime counterpart of the lint's gate
  discipline: while installed, ``time.monotonic()/time()/perf_counter()``
  (and their ``_ns`` variants) reads from package code **under
  simulation** are counted on ``mysticeti_detsan_wallclock_reads_total``
  and — when :data:`STRICT_ENV` is set (or ``strict=True``) — raise
  :class:`WallClockLeak` at the offending frame, turning a silent
  reproducibility bug into a stack trace.

``tools/detsan.py`` drives all three against a seeded multi-node chaos
sim (clean baseline must be byte-identical; a planted wall-clock leak
must be bisected) and emits the ``DETSAN_*.json`` trend artifact.
"""
from __future__ import annotations

import asyncio
import functools
import hashlib
import os
import sys
import time as _time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

STRICT_ENV = "MYSTICETI_DETSAN_STRICT"
DEFAULT_TRACE_CAP = 262_144


class WallClockLeak(RuntimeError):
    """An un-gated wall-clock read reached package code under simulation."""


# ---------------------------------------------------------------------------
# Event recording


def _callback_label(callback) -> str:
    """Deterministic label for a scheduled callback.

    Must never embed ``id()``/``repr()`` addresses — the label feeds the
    divergence digest, so an address would make every run 'diverge' at
    event 0.  Task steps are named after the coroutine they drive, which
    is what a human needs to locate the diverging code.
    """
    while isinstance(callback, functools.partial):
        callback = callback.func
    owner = getattr(callback, "__self__", None)
    if owner is not None:
        get_coro = getattr(owner, "get_coro", None)
        if get_coro is not None:
            code = getattr(get_coro(), "cr_code", None)
            if code is not None:
                return f"task:{getattr(code, 'co_qualname', code.co_name)}"
        return f"{type(owner).__name__}.{getattr(callback, '__name__', '?')}"
    return getattr(callback, "__qualname__", type(callback).__name__)


@dataclass
class EventRecord:
    """One executed loop event; ``chain`` is the cumulative digest AFTER it."""

    index: int
    vtime: float
    label: str
    chain: str


class DetsanRecorder:
    """Bounded per-event state-digest trace of one simulated run.

    Attach via ``run_simulation(main, seed, detsan=recorder)``; the
    DeterministicLoop wraps every ``call_soon``/``call_at`` callback so
    :meth:`record` fires at *execution* time, in execution order.
    """

    def __init__(self, cap: int = DEFAULT_TRACE_CAP) -> None:
        self.cap = int(cap)
        self.events: List[EventRecord] = []
        self.count = 0
        self._hash = hashlib.sha256(b"mysticeti-detsan-v1")

    # -- hook plumbing (called by DeterministicLoop) --

    def wrap(self, loop, callback, args) -> Tuple[Callable, tuple]:
        def _traced(*call_args):
            self.record(loop, callback)
            return callback(*call_args)

        return _traced, args

    def record(self, loop, callback) -> None:
        label = _callback_label(callback)
        vtime = loop.time()
        ready = len(getattr(loop, "_ready", ()))
        timers = len(getattr(loop, "_scheduled", ()))
        self._hash.update(
            f"{self.count}|{vtime:.9f}|{label}|{ready}|{timers}".encode()
        )
        if len(self.events) < self.cap:
            self.events.append(
                EventRecord(self.count, vtime, label, self._hash.hexdigest()[:16])
            )
        self.count += 1

    @property
    def chain(self) -> str:
        return self._hash.hexdigest()


# ---------------------------------------------------------------------------
# Divergence bisection


@dataclass
class DivergenceReport:
    identical: bool
    events_a: int
    events_b: int
    chain_a: str
    chain_b: str
    first_divergence: Optional[dict] = None
    note: str = ""

    def to_dict(self) -> dict:
        out = {
            "identical": self.identical,
            "events_a": self.events_a,
            "events_b": self.events_b,
            "chain_a": self.chain_a,
            "chain_b": self.chain_b,
        }
        if self.first_divergence is not None:
            out["first_divergence"] = dict(self.first_divergence)
        if self.note:
            out["note"] = self.note
        return out


def find_divergence(a: DetsanRecorder, b: DetsanRecorder) -> DivergenceReport:
    """Compare two recordings; binary-search the first diverging event.

    Chained digests make agreement-through-event-``i`` monotone: once the
    traces differ at some event, every later chain value differs too.  So
    ``events[i].chain == other[i].chain`` is a sorted predicate and the
    first divergence is found with O(log n) comparisons over the stored
    prefix — no full-trace scan, no event re-execution.
    """
    if a.chain == b.chain and a.count == b.count:
        return DivergenceReport(True, a.count, b.count, a.chain, b.chain)

    stored = min(len(a.events), len(b.events))
    if stored and a.events[stored - 1].chain == b.events[stored - 1].chain:
        # Stored prefixes fully agree: the divergence happened past the
        # trace cap (or one run simply outlived the other).  Report the
        # boundary rather than a wrong event.
        return DivergenceReport(
            False, a.count, b.count, a.chain, b.chain,
            first_divergence=None,
            note=(
                f"divergence beyond the {stored} stored events "
                f"(raise cap to localize)"
            ),
        )

    lo, hi = 0, stored - 1  # invariant: divergence at some index <= hi
    while lo < hi:
        mid = (lo + hi) // 2
        if a.events[mid].chain == b.events[mid].chain:
            lo = mid + 1
        else:
            hi = mid
    ea, eb = a.events[lo], b.events[lo]
    return DivergenceReport(
        False, a.count, b.count, a.chain, b.chain,
        first_divergence={
            "index": lo,
            "label_a": ea.label,
            "vtime_a": round(ea.vtime, 9),
            "label_b": eb.label,
            "vtime_b": round(eb.vtime, 9),
        },
    )


def run_twice(
    main_factory: Callable[[], "asyncio.Future"],
    seed: int = 0,
    timeout_s: Optional[float] = None,
    cap: int = DEFAULT_TRACE_CAP,
) -> DivergenceReport:
    """Execute ``main_factory()`` on two fresh seeded loops and diff them.

    ``main_factory`` must build a *new* coroutine per call (a coroutine
    object is single-shot).  A deterministic program yields
    ``identical=True``; anything else names its first diverging event.
    """
    from .runtime.simulated import run_simulation

    recorders = []
    for _ in range(2):
        recorder = DetsanRecorder(cap)
        run_simulation(
            main_factory(), seed=seed, timeout_s=timeout_s, detsan=recorder
        )
        recorders.append(recorder)
    return find_divergence(recorders[0], recorders[1])


# ---------------------------------------------------------------------------
# Wall-clock tripwire


_PATCH_NAMES = (
    "monotonic", "time", "perf_counter",
    "monotonic_ns", "time_ns", "perf_counter_ns",
)
_DEFAULT_PREFIXES = ("mysticeti_tpu",)
_SELF_MODULE = __name__


class Tripwire:
    """Strict-mode detector for un-gated wall-clock reads under simulation.

    While installed, the ``time`` module's clock readers are wrapped: a
    read whose *caller* is package code (``module_prefixes``) executing
    under :func:`~mysticeti_tpu.runtime.is_simulated` is counted per
    call-site (and on ``metrics.mysticeti_detsan_wallclock_reads_total``
    when a metrics object is supplied); in strict mode — ``strict=True``
    or the :data:`STRICT_ENV` environment knob — it raises
    :class:`WallClockLeak` instead, so the leak surfaces as a stack trace
    at the offending line.  Reads outside simulation, and reads from
    third-party code (asyncio, prometheus, the stdlib), pass through
    untouched.  Use as a context manager; install/uninstall is reentrant-
    safe via plain attribute swap.
    """

    def __init__(
        self,
        metrics=None,
        strict: Optional[bool] = None,
        module_prefixes: Tuple[str, ...] = _DEFAULT_PREFIXES,
    ) -> None:
        self.metrics = metrics
        self.strict = (
            bool(os.environ.get(STRICT_ENV)) if strict is None else strict
        )
        self.module_prefixes = tuple(module_prefixes)
        self.reads: Dict[str, int] = {}
        self._originals: Dict[str, Callable] = {}

    @property
    def total_reads(self) -> int:
        return sum(self.reads.values())

    def _flag(self, name: str) -> None:
        # Caller frame of the wrapped time.* function (wrapper is frame 1).
        frame = sys._getframe(2)
        module = frame.f_globals.get("__name__", "")
        if module == _SELF_MODULE or module.startswith(_SELF_MODULE + "."):
            return
        if not module.startswith(self.module_prefixes):
            return
        from .runtime import is_simulated

        if not is_simulated():
            return
        site = f"{module}:{frame.f_lineno}"
        self.reads[site] = self.reads.get(site, 0) + 1
        if self.metrics is not None:
            self.metrics.mysticeti_detsan_wallclock_reads_total.labels(
                site=site
            ).inc()
        if self.strict:
            raise WallClockLeak(
                f"time.{name}() read under simulation at {site}: gate it "
                f"behind `if not is_simulated():` or use runtime.now()/"
                f"timestamp_utc() (virtual under sim)"
            )

    def _make_wrapper(self, name: str, original: Callable) -> Callable:
        tripwire = self

        @functools.wraps(original)
        def wrapper(*args, **kwargs):
            tripwire._flag(name)
            return original(*args, **kwargs)

        return wrapper

    def install(self) -> "Tripwire":
        if self._originals:
            return self
        for name in _PATCH_NAMES:
            original = getattr(_time, name, None)
            if original is None:  # pragma: no cover - platform variance
                continue
            self._originals[name] = original
            setattr(_time, name, self._make_wrapper(name, original))
        return self

    def uninstall(self) -> None:
        for name, original in self._originals.items():
            setattr(_time, name, original)
        self._originals.clear()

    def __enter__(self) -> "Tripwire":
        return self.install()

    def __exit__(self, *exc_info) -> None:
        self.uninstall()


__all__ = [
    "DEFAULT_TRACE_CAP",
    "STRICT_ENV",
    "DetsanRecorder",
    "DivergenceReport",
    "EventRecord",
    "Tripwire",
    "WallClockLeak",
    "find_divergence",
    "run_twice",
]
