"""Batched Ed25519 verification on TPU — the framework's flagship kernel.

Replaces the serial per-block CPU verify of the reference
(``mysticeti-core/src/crypto.rs:174-189`` + call site ``types.rs:315-347``) with a
``vmap``ped, ``jit``ted JAX kernel: twisted-Edwards point decompression and
double-scalar multiplication ``[s]B - [k]A`` in 20x13-bit int32 limb arithmetic
(see :mod:`mysticeti_tpu.ops.field`), one lane per signature.

Verification rule (cofactorless, matching the OpenSSL/`cryptography` oracle and
RFC 8032 decoding): reject if s ≥ L or A is a non-canonical/invalid encoding;
accept iff encode([s]B - [k]A) == R_bytes, with k = SHA-512(R || A || M) mod L.
The byte comparison implies R canonicity exactly like OpenSSL's memcmp.

Host/device split: the host parses signatures, computes k (SHA-512 is cheap and
message-length-dependent; the fused on-device digest lives in ops/sha512.py) and
packs scalars as bit arrays; the device runs decompression + the 256-step
double-and-add ladder under ``lax.scan`` — constant shapes, no data-dependent
control flow, batch dimension mapped across VPU lanes.
"""
from __future__ import annotations

import hashlib
import os
from typing import Optional, Sequence, Tuple

import numpy as np

import jax

# Some PJRT plugins (the axon TPU tunnel among them) register a backend that
# wins platform selection even when JAX_PLATFORMS says otherwise; only the
# config API reliably pins the platform (tests/conftest.py works around the
# same thing).  Mirror the env var into the config HERE — before any backend
# is initialized — so subprocesses launched with JAX_PLATFORMS=cpu (fleet
# verifier services, CI tools) never touch an unavailable accelerator.
_env_platforms = os.environ.get("JAX_PLATFORMS")
if _env_platforms and jax.config.jax_platforms != _env_platforms:
    try:
        jax.config.update("jax_platforms", _env_platforms)
    except Exception:  # already initialized: the env var did its job
        pass

import jax.numpy as jnp

from . import field as F
from . import scalar as SC
from . import sha512 as H

# The ladder costs ~40 s to compile; every process that dispatches it (node
# subprocesses included — not just bench.py/pytest) must share the persistent
# cache or a validator's first verification stalls a whole benchmark run.
if jax.config.jax_compilation_cache_dir is None:
    import tempfile

    _cache = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if _cache is None:
        # Per-user path, created 0700 and ownership-checked: a world-writable
        # or attacker-pre-created dir would let another local user plant
        # crafted cache entries (deserialized executables).  /tmp's sticky bit
        # protects only the top level, so the uid suffix alone is not enough.
        #
        # The path is also keyed on the host's CPU microarchitecture: XLA:CPU
        # AOT executables are compiled for the build machine's features, and a
        # cache dir shared across heterogeneous machines makes every load
        # attempt log a cpu_aot_loader machine-mismatch error ("could lead to
        # SIGILL") before recompiling.  A per-machine key turns that into a
        # silent cache miss.
        import hashlib
        import platform as _platform

        _feat = _platform.machine()
        try:
            with open("/proc/cpuinfo") as _f:
                for _line in _f:
                    if _line.startswith(("flags", "Features")):
                        _feat += _line
                        break
        except OSError:
            pass
        _mkey = hashlib.blake2b(_feat.encode(), digest_size=4).hexdigest()
        _cache = os.path.join(
            tempfile.gettempdir(),
            f"mysticeti-tpu-jax-cache-{os.getuid()}-{_mkey}",
        )
        try:
            os.makedirs(_cache, mode=0o700, exist_ok=True)
            _st = os.stat(_cache)
            if _st.st_uid == os.getuid():
                if _st.st_mode & 0o077:
                    # Our own dir with loose perms (e.g. created by an older
                    # release): tighten in place, keep the stable shared path.
                    os.chmod(_cache, 0o700)
            else:
                _cache = tempfile.mkdtemp(prefix="mysticeti-tpu-jax-cache-")
        except OSError:
            _cache = tempfile.mkdtemp(prefix="mysticeti-tpu-jax-cache-")
    jax.config.update("jax_compilation_cache_dir", _cache)
    if os.environ.get("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS") is None:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    # jax latches cache initialization on the FIRST compile of the process —
    # and this module's own field/scalar/sha512 imports (above) build
    # module-level jnp constants that compile before this config block runs,
    # so the latch lands with the dir still unset and the persistent cache
    # stays SILENTLY DISABLED for the process lifetime.  Measured on the r6
    # fleet box: the cache dir had never held a single entry, and every
    # verifier-service boot re-paid a 2-4 min kernel compile.  Un-latch so
    # the next compile re-initializes against the configured dir.
    try:
        from jax._src import compilation_cache as _cc

        if getattr(_cc, "_cache_initialized", False) and _cc._cache is None:
            _cc.reset_cache()
    except (ImportError, AttributeError):  # private API: best-effort only
        pass

P = F.P
L = (1 << 252) + 27742317777372353535851937790883648493  # group order

_D = (-121665 * pow(121666, P - 2, P)) % P
_D2 = (2 * _D) % P
_SQRT_M1 = pow(2, (P - 1) // 4, P)

# Base point B: y = 4/5, x recovered with even sign.
_BY = (4 * pow(5, P - 2, P)) % P


def _recover_x(y: int, sign: int) -> Optional[int]:
    x2 = (y * y - 1) * pow(_D * y * y + 1, P - 2, P) % P
    if x2 == 0:
        return None if sign else 0
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * _SQRT_M1 % P
    if (x * x - x2) % P != 0:
        return None
    if x & 1 != sign:
        x = P - x
    return x


_BX = _recover_x(_BY, 0)
assert _BX is not None

# Device-side constants (limb form).
_D_L = F.constant(_D)
_D2_L = F.constant(_D2)
_SQRT_M1_L = F.constant(_SQRT_M1)
_ONE = F.constant(1)
_ZERO = F.constant(0)
_B_POINT = tuple(
    F.constant(v) for v in (_BX, _BY, 1, _BX * _BY % P)
)  # extended (X, Y, Z, T)

# A point is a 4-tuple of limb vectors (X, Y, Z, T) with x=X/Z, y=Y/Z, T=XY/Z.
Point = Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]


def _identity_like(shape_ref: jnp.ndarray) -> Point:
    zero = jnp.zeros_like(shape_ref)
    one = zero.at[..., 0].set(1)
    return (zero, one, one, zero)


def point_add(p: Point, q: Point) -> Point:
    """Unified addition, add-2008-hwcd-3 for a=-1 (8 muls)."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = F.mul(F.sub(y1, x1), F.sub(y2, x2))
    b = F.mul(F.add(y1, x1), F.add(y2, x2))
    c = F.mul(F.mul(t1, _D2_L), t2)
    d = F.mul(F.add(z1, z1), z2)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def point_double(p: Point) -> Point:
    """dbl-2008-hwcd for a=-1 (4 muls + 4 squares)."""
    x1, y1, z1, _ = p
    a = F.square(x1)
    b = F.square(y1)
    c = F.add(F.square(z1), F.square(z1))
    h = F.add(a, b)
    e = F.sub(h, F.square(F.add(x1, y1)))
    g = F.sub(a, b)
    f = F.add(c, g)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def point_neg(p: Point) -> Point:
    x, y, z, t = p
    return (F.neg(x), y, z, F.neg(t))


def _select(cond: jnp.ndarray, a: Point, b: Point) -> Point:
    """Per-item point select; cond is batch-shaped bool."""
    c = cond[..., None]
    return tuple(jnp.where(c, ai, bi) for ai, bi in zip(a, b))


def decompress(y_limbs: jnp.ndarray, sign: jnp.ndarray) -> Tuple[Point, jnp.ndarray]:
    """RFC 8032 point decompression on device (sqrt via the 2^252-3 chain).

    ``y_limbs``: (..., 20) the y coordinate (already checked < p on host);
    ``sign``: (...,) 0/1 x-parity bit.  Returns (point, ok_mask).
    """
    yy = F.square(y_limbs)
    u = F.sub(yy, _ONE)
    v = F.add(F.mul(_D_L, yy), _ONE)
    # x = u v^3 (u v^7)^((p-5)/8)
    v3 = F.mul(F.square(v), v)
    v7 = F.mul(F.square(v3), v)
    x = F.mul(F.mul(u, v3), F.pow22523(F.mul(u, v7)))
    vxx = F.mul(v, F.square(x))
    ok_direct = F.eq_canonical(vxx, u)
    ok_flipped = F.eq_canonical(vxx, F.neg(u))
    x = jnp.where(ok_direct[..., None], x, F.mul(x, _SQRT_M1_L))
    ok = ok_direct | ok_flipped
    # x == 0 with sign bit set is invalid (no -0).
    x_is_zero = F.is_zero(x)
    ok = ok & ~(x_is_zero & (sign == 1))
    # Match parity to the requested sign.
    flip = (F.parity(x) != sign) & ~x_is_zero
    x = jnp.where(flip[..., None], F.neg(x), x)
    point = (x, y_limbs, jnp.broadcast_to(_ONE, y_limbs.shape), F.mul(x, y_limbs))
    return point, ok


# ---------------------------------------------------------------------------
# Windowed double-scalar multiplication
# ---------------------------------------------------------------------------
#
# [s]B uses a positional comb table precomputed ONCE on the host with python
# ints (B is a protocol constant): T_B[w][v] = v * 16^w * B.  [s]B is then just
# 64 table additions — zero doublings.  [k]A runs a 4-bit windowed ladder with
# a 16-entry per-item table (15 vmapped adds to build), i.e. 256 doublings +
# 64 adds instead of 256 doublings + ~128 conditional adds.  Verification is
# not secret-dependent, so data-dependent *gathers* are fine (no constant-time
# requirement); shapes remain static.

_WINDOWS = 64  # 4-bit windows covering 256 bits


def _affine_add(p, q):
    """Host-side python-int Edwards addition (for table generation only)."""
    if p is None:
        return q
    if q is None:
        return p
    x1, y1 = p
    x2, y2 = q
    den1 = pow(1 + _D * x1 * x2 * y1 * y2, P - 2, P)
    den2 = pow(1 - _D * x1 * x2 * y1 * y2, P - 2, P)
    return ((x1 * y2 + x2 * y1) * den1 % P, (y1 * y2 + x1 * x2) * den2 % P)


def _build_base_comb() -> np.ndarray:
    """(64, 16, 4, 20) int32: extended-coordinate entries of v*16^w*B."""
    table = np.zeros((_WINDOWS, 16, 4, F.NLIMBS), np.int32)
    step = (_BX, _BY)  # 16^w * B
    for w in range(_WINDOWS):
        entry = None  # v * step
        for v in range(16):
            if entry is None:
                x, y = 0, 1
            else:
                x, y = entry
            table[w, v, 0] = F.int_to_limbs(x)
            table[w, v, 1] = F.int_to_limbs(y)
            table[w, v, 2] = F.int_to_limbs(1)
            table[w, v, 3] = F.int_to_limbs(x * y % P)
            entry = _affine_add(entry, step)
        for _ in range(4):
            step = _affine_add(step, step)
    return table


_B_COMB = jnp.asarray(_build_base_comb())


def _gather_point(table: Point, idx: jnp.ndarray) -> Point:
    """Select per-item entries: table coords (..., 16, 20), idx (...,).

    Implemented as a one-hot masked sum, not a gather — dynamic gathers
    serialize on the TPU VPU while the 16 multiply-adds stay lane-parallel.
    """
    onehot = (idx[..., None] == jnp.arange(16, dtype=jnp.int32)).astype(jnp.int32)
    return tuple(
        jnp.sum(onehot[..., :, None] * c, axis=-2) for c in table
    )


def _double_scalar_mul(
    s_windows: jnp.ndarray, k_windows: jnp.ndarray, neg_a: Point
) -> Point:
    """[s]B + [k]negA.

    ``s_windows``: (..., 64) int32 in 0..15, index 0 = LEAST significant window
    (positional, matches the comb table).  ``k_windows``: same layout; the
    ladder consumes them most-significant first.
    """
    # --- [k]negA: per-item 16-entry table, then 4-bit ladder ---
    identity = _identity_like(neg_a[0])
    tab = [identity, neg_a]
    for v in range(2, 16):
        tab.append(point_add(tab[v - 1], neg_a))
    # (..., 16, 20) per coordinate.
    tab_a: Point = tuple(
        jnp.stack([t[c] for t in tab], axis=-2) for c in range(4)
    )

    def ladder_step(acc: Point, kw):
        for _ in range(4):
            acc = point_double(acc)
        acc = point_add(acc, _gather_point(tab_a, kw))
        return acc, None

    kw_msb_first = jnp.moveaxis(k_windows[..., ::-1], -1, 0)  # scan axis front
    acc, _ = jax.lax.scan(ladder_step, identity, kw_msb_first)

    # --- [s]B: 64 comb-table additions, no doublings ---
    def comb_step(acc: Point, inputs):
        entries, sw = inputs  # entries: (16, 4, 20) const slice; sw: (...,)
        table: Point = tuple(
            jnp.broadcast_to(
                entries[:, c, :], (*sw.shape, 16, F.NLIMBS)
            )
            for c in range(4)
        )
        return point_add(acc, _gather_point(table, sw)), None

    sw = jnp.moveaxis(s_windows, -1, 0)
    acc_b, _ = jax.lax.scan(comb_step, identity, (_B_COMB, sw))

    return point_add(acc, acc_b)


def verify_impl(
    a_y: jnp.ndarray,  # (B, 20) public key y limbs
    a_sign: jnp.ndarray,  # (B,)
    r_y: jnp.ndarray,  # (B, 20) signature R y limbs (raw, unvalidated)
    r_sign: jnp.ndarray,  # (B,)
    s_windows: jnp.ndarray,  # (B, 64) 4-bit windows of s, LSB window first
    k_windows: jnp.ndarray,  # (B, 64) 4-bit windows of k, LSB window first
    host_ok: jnp.ndarray,  # (B,) host-side checks (s < L, canonical A, ...)
) -> jnp.ndarray:
    """Batched device verification; returns (B,) bool."""
    neg_a, decompress_ok = jax.vmap(decompress)(a_y, a_sign)
    neg_a = point_neg(neg_a)
    res = _double_scalar_mul(s_windows, k_windows, neg_a)
    x, y, z, _ = res
    zinv = F.invert(z)
    x_aff = F.mul(x, zinv)
    y_aff = F.mul(y, zinv)
    # Canonical-encode and compare against raw R limbs (memcmp semantics).
    # The compare is EXACT on the raw (unreduced) R representation: a
    # non-canonical R (y >= p) has a unique limb pattern that canonical()
    # output can never produce, so it is rejected — exactly like OpenSSL's
    # memcmp of the canonical encoding against the raw signature bytes.
    match = jnp.all(F.canonical(y_aff) == r_y, axis=-1) & (
        F.parity(x_aff) == r_sign
    )
    return match & decompress_ok & host_ok


verify_kernel = jax.jit(verify_impl)


# ---------------------------------------------------------------------------
# Fused path: raw signature bytes in, verification bits out — zero per-item
# host work.  SHA-512, the mod-L reduction, window extraction, point-encoding
# parsing, and all canonicity checks run on device (BASELINE config #4).
# ---------------------------------------------------------------------------


def _parse_point_words(le_words: jnp.ndarray):
    """(..., 8) uint32 LE words of a 32-byte point encoding ->
    (y limbs, sign, is_canonical)."""
    sign = (le_words[..., 7] >> 31).astype(jnp.int32)
    masked = le_words.at[..., 7].set(le_words[..., 7] & 0x7FFFFFFF)
    y_limbs = SC.words_to_limbs(masked, F.NLIMBS)
    return y_limbs, sign, SC.lt_P(y_limbs)


def prepare_fused(
    msg_words: jnp.ndarray,  # (B, 24) uint32 BIG-endian words of R || A || M
    s_words: jnp.ndarray,  # (B, 8) uint32 LITTLE-endian words of s
    host_ok: jnp.ndarray,  # (B,) bool (length checks only)
):
    """Device-side preparation: returns the 7 arrays verify_impl consumes.

    Fuses the challenge hash k = SHA-512(R||A||M) mod L (previously a per-item
    host hashlib loop — the reference's serial path, crypto.rs:174-189) with
    the encoding parse and the canonicity checks (s < L, A < p).  R canonicity
    needs no explicit check: the final compare is exact on raw limbs.
    """
    dig = H.sha512_96(msg_words)
    k = SC.mod_L(SC.words_to_limbs(SC.digest_words_to_le(dig), 40))
    k_windows = SC.windows4(k)

    r_y, r_sign, _ = _parse_point_words(SC.bswap32(msg_words[..., :8]))
    a_y, a_sign, a_canonical = _parse_point_words(SC.bswap32(msg_words[..., 8:16]))

    s_limbs = SC.words_to_limbs(s_words, F.NLIMBS)
    s_ok = SC.lt_L(s_limbs)
    s_windows = SC.windows4(s_limbs)

    ok = host_ok & a_canonical & s_ok
    return a_y, a_sign, r_y, r_sign, s_windows, k_windows, ok


def verify_fused_impl(msg_words, s_words, host_ok) -> jnp.ndarray:
    """Batched fused verification; (B,) bool from raw byte words."""
    return verify_impl(*prepare_fused(msg_words, s_words, host_ok))


verify_fused_kernel = jax.jit(verify_fused_impl)


def _pack_fixed_rows(items: Sequence[bytes], width: int) -> Tuple[np.ndarray, np.ndarray]:
    """(n, width) uint8 rows + per-row well-formedness.  Vectorized single
    concatenation when every item has the right length; rows of wrong length
    zero-fill (callers mask them via host_ok — verify-returns-False
    semantics, never an exception)."""
    n = len(items)
    ok = np.fromiter((len(x) == width for x in items), bool, count=n)
    if ok.all():
        return np.frombuffer(b"".join(items), np.uint8).reshape(n, width), ok
    arr = np.zeros((n, width), np.uint8)
    for i in range(n):
        if ok[i]:
            arr[i] = np.frombuffer(items[i], np.uint8)
    return arr, ok


def pack_bytes(
    public_keys: Sequence[bytes],
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host-side packing for the fused kernel: pure byte concatenation.

    Requires 32-byte messages (the framework always signs a blake2b-256 block
    digest, types.py signed_digest); malformed-length items are masked out via
    host_ok rather than raising, matching verify-returns-False semantics.
    """
    sig_arr, sig_ok = _pack_fixed_rows(signatures, 64)
    pk_arr, pk_ok = _pack_fixed_rows(public_keys, 32)
    msg_arr, msg_ok = _pack_fixed_rows(messages, 32)
    host_ok = sig_ok & pk_ok & msg_ok
    blob = np.ascontiguousarray(
        np.concatenate([sig_arr[:, :32], pk_arr, msg_arr], axis=1)
    )
    msg_words = blob.view(">u4").astype(np.uint32)  # (n, 24) big-endian words
    s_words = np.ascontiguousarray(sig_arr[:, 32:]).view("<u4").astype(np.uint32)
    return msg_words, s_words, host_ok


def pack_blob(
    public_keys: Sequence[bytes],
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
) -> np.ndarray:
    """Pack a batch into ONE (n, 33) uint32 array: columns 0-23 the big-endian
    R||A||M words, 24-31 the little-endian s words, 32 the host_ok flag.

    One array means one host->device transfer per dispatch — on hosts where
    the accelerator sits behind a high-latency link (e.g. a tunneled chip),
    per-transfer latency dominates, so fewer transfers directly buys
    throughput.
    """
    msg_words, s_words, host_ok = pack_bytes(public_keys, messages, signatures)
    return np.concatenate(
        [msg_words, s_words, host_ok[:, None].astype(np.uint32)], axis=1
    )


def verify_fused_blob_impl(blob: jnp.ndarray) -> jnp.ndarray:
    """(B, 33) packed blob -> (B,) bool, everything on device."""
    return verify_fused_impl(blob[..., :24], blob[..., 24:32], blob[..., 32] != 0)


verify_fused_blob_kernel = jax.jit(verify_fused_blob_impl)


# ---------------------------------------------------------------------------
# Indexed path: the signer set is a known committee, so the public key rides
# as an INDEX into a device-resident key table instead of 32 raw bytes —
# 26 words/sig on the wire instead of 33 (~21% less host->device transfer,
# the binding resource on remote/tunneled chips).  The table is uploaded once
# per committee.
# ---------------------------------------------------------------------------


def pk_table_words(public_keys: Sequence[bytes]) -> np.ndarray:
    """(K, 8) uint32 big-endian words of the raw 32-byte A encodings — the
    exact layout the fused blob carries in its A section."""
    arr = np.frombuffer(b"".join(public_keys), np.uint8).reshape(
        len(public_keys), 32
    )
    return np.ascontiguousarray(arr).view(">u4").astype(np.uint32)


def pack_blob_indexed(
    indices: np.ndarray,
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
    host_ok: Optional[np.ndarray] = None,
    num_keys: Optional[int] = None,
) -> np.ndarray:
    """Pack a batch into ONE (n, 26) uint32 array: columns 0-7 big-endian R
    words, 8-15 big-endian M words, 16-23 little-endian s words, 24 the key
    index, 25 the host_ok flag.

    Out-of-range indices (including the -1 "unknown key" sentinel from
    ``KeyTable.indices_for``) are masked host_ok=False here — never silently
    verified against some other table row.
    """
    n = len(signatures)
    idx = np.asarray(indices, np.int64)
    ok = np.ones(n, bool) if host_ok is None else np.asarray(host_ok, bool).copy()
    ok &= idx >= 0
    if num_keys is not None:
        ok &= idx < num_keys
    sig_arr, sig_ok = _pack_fixed_rows(signatures, 64)
    msg_arr, msg_ok = _pack_fixed_rows(messages, 32)
    ok &= sig_ok & msg_ok
    rm = np.ascontiguousarray(
        np.concatenate([sig_arr[:, :32], msg_arr], axis=1)
    )
    rm_words = rm.view(">u4").astype(np.uint32)  # (n, 16) R then M
    s_words = np.ascontiguousarray(sig_arr[:, 32:]).view("<u4").astype(np.uint32)
    return np.concatenate(
        [
            rm_words,
            s_words,
            np.clip(idx, 0, None).astype(np.uint32)[:, None],
            ok[:, None].astype(np.uint32),
        ],
        axis=1,
    )


def indexed_to_msg_words(blob: jnp.ndarray, table: jnp.ndarray):
    """Rebuild the fused-kernel inputs from an indexed blob + key table:
    gather the A words by index and splice them between R and M."""
    idx = blob[..., 24].astype(jnp.int32)
    a_words = table[jnp.clip(idx, 0, table.shape[0] - 1)]
    msg_words = jnp.concatenate(
        [blob[..., :8], a_words, blob[..., 8:16]], axis=-1
    )
    return msg_words, blob[..., 16:24], blob[..., 25] != 0


def verify_fused_indexed_impl(blob: jnp.ndarray, table: jnp.ndarray) -> jnp.ndarray:
    """(B, 26) indexed blob + (K, 8) key table -> (B,) bool."""
    return verify_fused_impl(*indexed_to_msg_words(blob, table))


verify_fused_indexed_kernel = jax.jit(verify_fused_indexed_impl)


# ---------------------------------------------------------------------------
# Keyed-tile path: the committee keys are FIXED at table build time, so each
# key gets a full positional comb table -(v * 16^w * A) precomputed once —
# per-signature verification then needs ZERO doublings and NO on-device A
# decompression (the two dominant costs of the generic ladder: ~252 doublings
# + a ~250-mul sqrt chain per lane).  Tiles are grouped by key on the host so
# the Pallas kernel selects one key's comb per tile via scalar prefetch.
# ---------------------------------------------------------------------------


def _ext_add(p, q):
    """Python-int extended twisted-Edwards addition (add-2008-hwcd-3, a=-1,
    complete) — table generation only."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = t1 * _D2 % P * t2 % P
    d = 2 * z1 * z2 % P
    e, f, g, h = b - a, d - c, d + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def _ext_double(p):
    """Python-int dbl-2008-hwcd (a=-1) — table generation only."""
    x1, y1, z1, _ = p
    a = x1 * x1 % P
    b = y1 * y1 % P
    c = 2 * z1 * z1 % P
    h = a + b
    e = h - (x1 + y1) * (x1 + y1)
    g = a - b
    f = c + g
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def _decode_point(pk32: bytes) -> Optional[Tuple[int, int]]:
    """RFC 8032 decode of a 32-byte encoding to affine (x, y); None when the
    encoding is non-canonical or not on the curve."""
    enc = int.from_bytes(pk32, "little")
    sign, y = enc >> 255, enc & ((1 << 255) - 1)
    if y >= P:
        return None
    x = _recover_x(y, sign)
    if x is None:
        return None
    return x, y


def build_neg_key_combs(public_keys: Sequence[bytes]) -> Tuple[np.ndarray, np.ndarray]:
    """(K, 64, 3, NLIMBS, 16) int32 Niels-form combs of -(v * 16^w * A_j),
    plus a (K,) validity mask.

    An invalid key (non-canonical / off-curve encoding) gets identity-only
    entries and valid=False; the keyed dispatch force-rejects its lanes,
    matching the generic kernel's decompression failure bit-for-bit.

    Built with python ints; all 960 affine conversions per key share ONE
    modular inversion (Montgomery batch-inversion), so a 100-key committee
    builds in seconds, once.
    """
    K = len(public_keys)
    out = np.zeros((K, _WINDOWS, 3, F.NLIMBS, 16), np.int32)
    valid = np.zeros(K, bool)
    one = F.int_to_limbs(1)
    # v=0 entries are the identity's Niels form (1, 1, 0) for every window.
    out[:, :, 0, :, 0] = one
    out[:, :, 1, :, 0] = one
    for j, pk in enumerate(public_keys):
        dec = _decode_point(bytes(pk))
        if dec is None:
            continue
        valid[j] = True
        x, y = dec
        step = (x, y, 1, x * y % P)  # 16^w * A in extended coords
        entries = []  # (w, v, point)
        for w in range(_WINDOWS):
            entry = step
            for v in range(1, 16):
                entries.append((w, v, entry))
                entry = _ext_add(entry, step)
            for _ in range(4):
                step = _ext_double(step)
        # Montgomery batch inversion of every Z.
        prefix = [1]
        for _, _, (_, _, z, _) in entries:
            prefix.append(prefix[-1] * z % P)
        inv = pow(prefix[-1], P - 2, P)
        for i in range(len(entries) - 1, -1, -1):
            w, v, (ex, ey, ez, _) = entries[i]
            zi = prefix[i] * inv % P
            inv = inv * ez % P
            xa, ya = ex * zi % P, ey * zi % P
            # Niels form of the NEGATED point (-xa, ya):
            out[j, w, 0, :, v] = F.int_to_limbs((ya + xa) % P)  # y - (-x)
            out[j, w, 1, :, v] = F.int_to_limbs((ya - xa) % P)  # y + (-x)
            out[j, w, 2, :, v] = F.int_to_limbs(
                (P - _D2 * xa % P * ya % P) % P  # 2d * (-x) * y
            )
    return out, valid


def group_blob_for_tiles(
    blob: np.ndarray, num_keys: int, tile: int, bucket: int
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Rearrange an indexed blob so every ``tile``-lane tile holds one key.

    Returns (grouped (bucket, C), tile_keys (bucket//tile,) int32,
    positions (n,) int32 — row of each original item in the grouped layout),
    or None when the per-key padding cannot fit the bucket (callers fall back
    to the generic kernel).  Padded lanes are zero rows (host_ok=0).
    """
    n = blob.shape[0]
    ntiles = bucket // tile
    idx = blob[:, 24].astype(np.int64)
    ok = blob[:, 25] != 0
    # Rejected/unknown lanes carry no constraint (host_ok=0 forces False);
    # park them under key 0.
    key = np.where(ok, np.clip(idx, 0, num_keys - 1), 0)
    counts = np.bincount(key, minlength=num_keys)
    tiles_per_key = -(-counts // tile)
    if int(tiles_per_key.sum()) > ntiles:
        return None
    tile_starts = np.zeros(num_keys, np.int64)
    np.cumsum(tiles_per_key[:-1] * tile, out=tile_starts[1:])
    order = np.argsort(key, kind="stable")
    csum = np.zeros(num_keys, np.int64)
    np.cumsum(counts[:-1], out=csum[1:])
    rank_sorted = np.arange(n, dtype=np.int64) - np.repeat(csum, counts)
    positions = np.empty(n, np.int64)
    positions[order] = tile_starts[key[order]] + rank_sorted
    grouped = np.zeros((bucket, blob.shape[1]), blob.dtype)
    grouped[positions] = blob
    tile_keys = np.zeros(ntiles, np.int32)
    tile_keys[: int(tiles_per_key.sum())] = np.repeat(
        np.arange(num_keys), tiles_per_key
    )
    return grouped, tile_keys, positions.astype(np.int32)


class KeyTable:
    """A committee's keys resident on device: upload once, verify by index.

    ``indices_for`` maps raw pk bytes to table rows; unknown keys map to -1
    (callers mask them out or route them through the generic path).

    ``neg_combs`` lazily builds the per-key negated comb tables for the
    keyed-tile Pallas kernel (see build_neg_key_combs)."""

    def __init__(self, public_keys: Sequence[bytes]) -> None:
        if not public_keys:
            raise ValueError("empty key table")
        if any(len(pk) != 32 for pk in public_keys):
            raise ValueError("key table entries must be 32-byte encodings")
        self.words = jnp.asarray(pk_table_words(public_keys))
        self._index = {pk: i for i, pk in enumerate(public_keys)}
        self._keys = [bytes(pk) for pk in public_keys]
        self._neg_combs: Optional[Tuple[jnp.ndarray, np.ndarray]] = None

    def __len__(self) -> int:
        return self.words.shape[0]

    def indices_for(self, public_keys: Sequence[bytes]) -> np.ndarray:
        return np.fromiter(
            (self._index.get(pk, -1) for pk in public_keys),
            np.int64,
            count=len(public_keys),
        )

    def neg_combs(self) -> Tuple[jnp.ndarray, np.ndarray]:
        """(device (K, 64, 3, NLIMBS, 16) comb array, (K,) host valid mask)."""
        if self._neg_combs is None:
            arr, valid = build_neg_key_combs(self._keys)
            self._neg_combs = (jnp.asarray(arr), valid)
        return self._neg_combs


def _dispatch_indexed(blob, table) -> jnp.ndarray:
    if _backend() == "pallas":
        from . import ed25519_pallas as PK

        return PK.verify_fused_indexed_blob_pallas(blob, table)
    return verify_fused_indexed_kernel(blob, table)


def _dispatch_indexed_keyed(chunk: np.ndarray, table: "KeyTable", bucket: int):
    """Keyed-tile Pallas dispatch (zero doublings, no A decompression);
    returns None when the per-key tile padding doesn't fit the bucket —
    callers fall back to the generic ladder."""
    from . import ed25519_pallas as PK

    tile = min(PK.default_tile(), bucket)
    acomb, valid = table.neg_combs()
    if not valid.all():
        # Lanes under an off-curve committee key must reject exactly like the
        # generic kernel's decompression failure; the identity comb entries
        # would otherwise turn them into an [s]B == R check.
        chunk = chunk.copy()
        keyv = np.clip(chunk[:, 24].astype(np.int64), 0, len(valid) - 1)
        chunk[:, 25] &= valid[keyv]
    g = group_blob_for_tiles(chunk, len(table), tile, bucket)
    if g is None:
        return None
    grouped, tile_keys, positions = g
    # positions stay on HOST (fetch_handles un-permutes after the transfer):
    # uploading them spent 4 B/sig of a bandwidth-bound link on data the
    # device only needed for a final gather (+5% measured e2e).  The
    # narrower 96 B/sig flat layout (idx reconstructed from tile_keys, ok as
    # a bitmask — verify_keyed_flat) measured consistently SLOWER e2e
    # (~343k vs ~388k sig/s) despite fewer bytes: the device-side
    # reshape/expand costs more than the wire saves here, so the plain
    # 26-column grouped upload stays the deployed path.
    # positions never ride the link (see above), so only the grouped blob
    # and the per-tile key ids count as upload traffic.
    _note_transfer("to_device", grouped.nbytes + tile_keys.nbytes)
    handle = PK.verify_keyed_blob(
        grouped, table.words, acomb, tile_keys, None, tile=tile
    )
    return handle, positions


def dispatch_indexed_chunks(blob: np.ndarray, table: "KeyTable"):
    """Bucket-shaped async dispatch of an indexed blob (pack_blob_indexed
    layout); returns fetch_handles entries — ``(count, handle)`` for generic
    chunks, ``(count, handle, positions)`` for keyed-tile chunks whose
    results come back in GROUPED order (fetch_handles un-permutes on host).

    On the Pallas backend each chunk takes the keyed-tile kernel when its
    per-key grouping fits the bucket (the common case: committee authorship
    is roughly uniform), falling back to the generic ladder otherwise.
    MYSTICETI_KEYED=0 disables the keyed path."""
    keyed = _backend() == "pallas" and os.environ.get("MYSTICETI_KEYED") != "0"
    handles = []
    for start, count, b in iter_buckets(blob.shape[0]):
        chunk = blob[start : start + count]
        hp = _dispatch_indexed_keyed(chunk, table, b) if keyed else None
        if hp is None:
            padded = _pad_to(chunk, b)
            _note_transfer("to_device", padded.nbytes)
            h = _dispatch_indexed(jnp.asarray(padded), table.words)
            handles.append((count, h))
        else:
            h, positions = hp
            handles.append((count, h, positions))
    return handles


class VerifyDispatch:
    """Future-like handle over one batch's in-flight bucket dispatches.

    The explicit seam of the staged verify pipeline: ``dispatch_batch*``
    packs on the host (numpy) and submits every bucket chunk through JAX's
    async dispatch, returning immediately; ``result()`` forces everything
    with ONE combined device sync (``fetch_handles``) only at consumption.
    Between the two, the caller can pack and submit further batches — the
    device streams chunk after chunk instead of idling a full round-trip
    per dispatch.

    ``patches`` carries straggler sub-dispatches (unknown-key items routed
    through the generic kernel): ``(row indices, handle)`` pairs whose
    results overwrite those rows at fetch time.
    """

    __slots__ = ("_entries", "_patches")

    def __init__(self, entries, patches=()) -> None:
        self._entries = list(entries)
        self._patches = tuple(patches)

    def result(self) -> np.ndarray:
        out = fetch_handles(self._entries)
        for rows, handle in self._patches:
            out[rows] = handle.result()
        return out


def dispatch_batch_table(
    table: "KeyTable",
    public_keys: Sequence[bytes],
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
) -> VerifyDispatch:
    """Non-blocking committee-indexed dispatch: pack (host) + submit every
    bucket chunk asynchronously; the returned handle fetches on demand.
    Items whose pk is not in the table ride a generic-path patch."""
    n = len(signatures)
    if n == 0:
        return VerifyDispatch([])
    if not all(len(m) == 32 for m in messages):
        return dispatch_batch(public_keys, messages, signatures)
    idx = table.indices_for(public_keys)
    known = idx >= 0
    blob = pack_blob_indexed(idx, messages, signatures, num_keys=len(table))
    handles = dispatch_indexed_chunks(blob, table)
    if known.all():
        return VerifyDispatch(handles)
    stragglers = np.flatnonzero(~known)
    generic = dispatch_batch(
        [public_keys[i] for i in stragglers],
        [messages[i] for i in stragglers],
        [signatures[i] for i in stragglers],
    )
    return VerifyDispatch(handles, [(stragglers, generic)])


def verify_batch_table(
    table: "KeyTable",
    public_keys: Sequence[bytes],
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
) -> np.ndarray:
    """verify_batch against a known signer set: per-sig transfer drops to 26
    words.  Items whose pk is not in the table fall back to the generic path
    (correctness is identical; only the wire format differs)."""
    return dispatch_batch_table(
        table, public_keys, messages, signatures
    ).result()


# ---------------------------------------------------------------------------
# Host-side packing
# ---------------------------------------------------------------------------


def _windows_lsb_first(x: int) -> np.ndarray:
    return np.array([(x >> (4 * w)) & 15 for w in range(_WINDOWS)], dtype=np.int32)


def _ylimbs_and_sign(data32: bytes) -> Tuple[np.ndarray, int, int]:
    """Parse a 32-byte point encoding: (y limbs, sign bit, y-as-int)."""
    enc = int.from_bytes(data32, "little")
    sign = enc >> 255
    y = enc & ((1 << 255) - 1)
    return F.int_to_limbs(y), sign, y


def pack_batch(
    public_keys: Sequence[bytes],
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
) -> Tuple[np.ndarray, ...]:
    """Host-side preparation of a verification batch.

    Computes k = SHA-512(R || A || M) mod L per item (the fused on-device
    digest path replaces this for 32-byte block digests), performs the cheap
    integer checks, and packs limb/bit arrays for :func:`verify_kernel`.
    """
    n = len(signatures)
    a_y = np.zeros((n, F.NLIMBS), np.int32)
    a_sign = np.zeros(n, np.int32)
    r_y = np.zeros((n, F.NLIMBS), np.int32)
    r_sign = np.zeros(n, np.int32)
    s_bits = np.zeros((n, _WINDOWS), np.int32)
    k_bits = np.zeros((n, _WINDOWS), np.int32)
    host_ok = np.zeros(n, bool)
    for i, (pk, msg, sig) in enumerate(zip(public_keys, messages, signatures)):
        if len(pk) != 32 or len(sig) != 64:
            continue
        r_bytes, s_bytes = sig[:32], sig[32:]
        s = int.from_bytes(s_bytes, "little")
        if s >= L:
            continue  # non-canonical s: reject (RFC 8032 / OpenSSL)
        limbs, sign, y = _ylimbs_and_sign(pk)
        if y >= P:
            continue  # non-canonical A encoding
        a_y[i], a_sign[i] = limbs, sign
        r_limbs, rs, ry = _ylimbs_and_sign(r_bytes)
        if ry >= P:
            # Non-canonical R encoding: OpenSSL's memcmp of encode([s]B - [k]A)
            # against the raw R bytes can never match a y >= p encoding, so
            # reject on host.  Keeps the device compare (eq_canonical, which
            # would reduce mod p) exactly equivalent to memcmp semantics.
            continue
        r_y[i], r_sign[i] = r_limbs, rs
        k = int.from_bytes(hashlib.sha512(r_bytes + pk + msg).digest(), "little") % L
        s_bits[i] = _windows_lsb_first(s)
        k_bits[i] = _windows_lsb_first(k)
        host_ok[i] = True
    return a_y, a_sign, r_y, r_sign, s_bits, k_bits, host_ok


# Fixed device batch shapes: every dispatch is padded up to one of these, so
# XLA compiles at most len(BUCKETS) variants per process (shape stability is
# the TPU contract; stragglers ride as padding lanes with host_ok=False).
# All are multiples of the Pallas tile (256) used on real TPUs.  The top
# bucket matters for throughput: the VMEM ladder amortizes better at 16k
# lanes (~515k sig/s on v5e vs ~450k at 4k).
BUCKETS = (256, 1024, 4096, 16384)


def _backend() -> str:
    """'pallas' (VMEM-resident ladder) on real TPUs, 'xla' elsewhere;
    override with MYSTICETI_VERIFY_BACKEND=xla|pallas."""
    import os

    forced = os.environ.get("MYSTICETI_VERIFY_BACKEND")
    if forced in ("xla", "pallas"):
        return forced
    return "pallas" if jax.default_backend() == "tpu" else "xla"


# ---------------------------------------------------------------------------
# Host attribution plane: device-side counters (the JAX half of
# profiling.py's per-subsystem accountant).  All host-side bookkeeping — no
# kernel changes.

_attr_metrics = None
_attr_listeners_installed = False


def install_device_attribution(metrics) -> bool:
    """Wire JAX compile events, compile-cache hits/misses, and the transfer
    byte counters below into the node's registry (``mysticeti_jax_*`` and
    ``mysticeti_device_transfer_bytes_total``, metrics.py).  Called once by
    validators that verify in-process; re-calling swaps the target registry.
    Returns whether the ``jax.monitoring`` listeners landed (the module is
    semi-private, so every hook is best-effort)."""
    global _attr_metrics, _attr_listeners_installed
    _attr_metrics = metrics
    if _attr_listeners_installed:
        return True
    try:
        from jax import monitoring as _monitoring

        def _on_event(event: str, **kwargs) -> None:
            m = _attr_metrics
            if m is None:
                return
            if "cache_hit" in event:
                m.mysticeti_jax_cache_hits_total.inc()
            elif "cache_miss" in event or "cache_nonhit" in event:
                m.mysticeti_jax_cache_misses_total.inc()

        def _on_duration(event: str, duration: float, **kwargs) -> None:
            m = _attr_metrics
            if m is None:
                return
            if "compil" in event:  # matches compile/compilation variants
                m.mysticeti_jax_compiles_total.inc()
                m.mysticeti_jax_compile_seconds_total.inc(max(0.0, duration))

        _monitoring.register_event_listener(_on_event)
        _monitoring.register_event_duration_secs_listener(_on_duration)
        _attr_listeners_installed = True
        return True
    except Exception:  # noqa: BLE001 - attribution must never break verify
        return False


def _note_transfer(direction: str, nbytes: int) -> None:
    """Count host<->device bytes at the dispatch/fetch seams: JAX exposes no
    portable transfer counter, but every verifier transfer flows through
    dispatch_blob_chunks / dispatch_batch / fetch_handles, so counting the
    (padded) array sizes there IS the device link traffic."""
    m = _attr_metrics
    if m is not None and nbytes > 0:
        m.mysticeti_device_transfer_bytes_total.labels(direction).inc(nbytes)


def _dispatch_fused(msg_words, s_words, host_ok) -> jnp.ndarray:
    if _backend() == "pallas":
        from . import ed25519_pallas as PK

        return PK.verify_fused_pallas(msg_words, s_words, host_ok)
    return verify_fused_kernel(msg_words, s_words, host_ok)


def _dispatch_blob(blob) -> jnp.ndarray:
    """Async dispatch of one packed blob chunk; returns the device handle.
    The chunk must already be bucket-shaped (use dispatch_blob_chunks)."""
    if _backend() == "pallas":
        from . import ed25519_pallas as PK

        return PK.verify_fused_blob_pallas(blob)
    return verify_fused_blob_kernel(blob)


def iter_buckets(n: int):
    """Yield (start, count, bucket) chunk descriptors covering n items with
    the fixed bucket shapes — the single source of truth for chunking.

    Rounding up to the next bucket is taken only when the padding stays
    under 25% of that bucket; otherwise the largest bucket that fits is
    dispatched full and the remainder recurses.  This keeps wasted lanes
    small (5000 items -> 4096 + 1024 lanes, not one 16384-lane dispatch)
    without fragmenting near-bucket batches into many tiny chunks."""
    start = 0
    while start < n:
        rem = n - start
        s = next((c for c in BUCKETS if c >= rem), None)
        g = next((c for c in reversed(BUCKETS) if c <= rem), None)
        if s is not None and (g is None or s - rem <= s // 4):
            yield start, rem, s
            return
        b = g if g is not None else BUCKETS[0]
        count = min(b, rem)
        yield start, count, b
        start += count


def dispatch_blob_chunks(blob: np.ndarray):
    """Slice a packed (n, 33) blob into fixed-bucket chunks, pad each, and
    dispatch all of them asynchronously.  Returns [(count, device handle)];
    force with np.asarray(handle)[:count]."""
    out = []
    for start, count, b in iter_buckets(blob.shape[0]):
        padded = _pad_to(blob[start : start + count], b)
        _note_transfer("to_device", padded.nbytes)
        out.append((count, _dispatch_blob(jnp.asarray(padded))))
    return out


def fetch_handles(handles) -> np.ndarray:
    """Force a list of ``(count, device_handle[, positions])`` chunk results
    with ONE device sync: concatenate the (padded) outputs on device,
    transfer once, then drop padding / un-permute grouped-order keyed
    results on host.

    Per-handle ``np.asarray`` costs a full device round-trip each; on a
    tunneled chip (~100 ms RTT) that alone caps throughput, so the single
    combined fetch is the difference between RTT-bound and compute-bound.
    """
    if not handles:
        return np.zeros(0, bool)
    # Entries are (count, handle) in dispatch order, or (count, handle,
    # positions) for keyed-tile chunks whose results come back in GROUPED
    # order (positions maps original row -> grouped row; un-permuted here,
    # on host, so they never ride the upload link).
    unpacked = [
        (e[0], e[1], e[2] if len(e) > 2 else None) for e in handles
    ]
    if len(unpacked) == 1:
        count, h, positions = unpacked[0]
        res = np.asarray(h)
        _note_transfer("from_device", res.nbytes)
        if positions is not None:
            return np.array(res[positions])
        # np.array (not asarray): a writable copy, matching the multi-chunk
        # path — callers patch straggler entries in place.  The copy is a
        # bool row per signature, noise next to the transfer itself.
        return np.array(res[:count])
    flat = np.asarray(jnp.concatenate([h for _, h, _ in unpacked]))
    _note_transfer("from_device", flat.nbytes)
    out = np.empty(sum(count for count, _, _ in unpacked), bool)
    src = dst = 0
    for count, h, positions in unpacked:
        chunk = flat[src : src + h.shape[0]]
        if positions is not None:
            out[dst : dst + count] = chunk[positions]
        else:
            out[dst : dst + count] = chunk[:count]
        src += h.shape[0]
        dst += count
    return out


def dispatch_batch(
    public_keys: Sequence[bytes],
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
) -> VerifyDispatch:
    """Non-blocking batched dispatch: the pack stage runs here on the host
    (pure numpy for the fused path; the per-item SHA-512 loop otherwise),
    every bucket chunk is submitted through JAX's async dispatch, and the
    returned handle fetches on demand — ``block_until_ready`` semantics only
    at consumption."""
    n = len(signatures)
    if n == 0:
        return VerifyDispatch([])
    fused = all(len(m) == 32 for m in messages)
    if fused:
        blob = pack_blob(public_keys, messages, signatures)
        # Dispatch every chunk asynchronously (one transfer each); the
        # handle forces all results with a single combined fetch, so device
        # work and transfers overlap across chunks and only one round-trip
        # is paid at the end.
        return VerifyDispatch(dispatch_blob_chunks(blob))
    arrays = pack_batch(public_keys, messages, signatures)
    handles = []
    for start, count, b in iter_buckets(n):
        padded = [_pad_to(x[start : start + count], b) for x in arrays]
        _note_transfer("to_device", sum(p.nbytes for p in padded))
        handles.append(
            (count, verify_kernel(*[jnp.asarray(p) for p in padded]))
        )
    return VerifyDispatch(handles)


def verify_batch(
    public_keys: Sequence[bytes],
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
) -> np.ndarray:
    """End-to-end batched verify; returns np.ndarray of bool, one per item.

    Fused path (32-byte messages — always true for block digests): bytes are
    packed with pure numpy and everything else happens on device.  Other
    message lengths fall back to the host-hash packing path.
    """
    return dispatch_batch(public_keys, messages, signatures).result()


def _pad_to(x: np.ndarray, size: int) -> np.ndarray:
    if x.shape[0] == size:
        return np.ascontiguousarray(x)
    widths = [(0, size - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
    return np.pad(x, widths)
