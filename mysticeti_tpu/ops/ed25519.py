"""Batched Ed25519 verification on TPU — the framework's flagship kernel.

Replaces the serial per-block CPU verify of the reference
(``mysticeti-core/src/crypto.rs:174-189`` + call site ``types.rs:315-347``) with a
``vmap``ped, ``jit``ted JAX kernel: twisted-Edwards point decompression and
double-scalar multiplication ``[s]B - [k]A`` in 20x13-bit int32 limb arithmetic
(see :mod:`mysticeti_tpu.ops.field`), one lane per signature.

Verification rule (cofactorless, matching the OpenSSL/`cryptography` oracle and
RFC 8032 decoding): reject if s ≥ L or A is a non-canonical/invalid encoding;
accept iff encode([s]B - [k]A) == R_bytes, with k = SHA-512(R || A || M) mod L.
The byte comparison implies R canonicity exactly like OpenSSL's memcmp.

Host/device split: the host parses signatures, computes k (SHA-512 is cheap and
message-length-dependent; the fused on-device digest lives in ops/sha512.py) and
packs scalars as bit arrays; the device runs decompression + the 256-step
double-and-add ladder under ``lax.scan`` — constant shapes, no data-dependent
control flow, batch dimension mapped across VPU lanes.
"""
from __future__ import annotations

import hashlib
from functools import partial
from typing import Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from . import field as F

P = F.P
L = (1 << 252) + 27742317777372353535851937790883648493  # group order

_D = (-121665 * pow(121666, P - 2, P)) % P
_D2 = (2 * _D) % P
_SQRT_M1 = pow(2, (P - 1) // 4, P)

# Base point B: y = 4/5, x recovered with even sign.
_BY = (4 * pow(5, P - 2, P)) % P


def _recover_x(y: int, sign: int) -> Optional[int]:
    x2 = (y * y - 1) * pow(_D * y * y + 1, P - 2, P) % P
    if x2 == 0:
        return None if sign else 0
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * _SQRT_M1 % P
    if (x * x - x2) % P != 0:
        return None
    if x & 1 != sign:
        x = P - x
    return x


_BX = _recover_x(_BY, 0)
assert _BX is not None

# Device-side constants (limb form).
_D_L = F.constant(_D)
_D2_L = F.constant(_D2)
_SQRT_M1_L = F.constant(_SQRT_M1)
_ONE = F.constant(1)
_ZERO = F.constant(0)
_B_POINT = tuple(
    F.constant(v) for v in (_BX, _BY, 1, _BX * _BY % P)
)  # extended (X, Y, Z, T)

# A point is a 4-tuple of limb vectors (X, Y, Z, T) with x=X/Z, y=Y/Z, T=XY/Z.
Point = Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]


def _identity_like(shape_ref: jnp.ndarray) -> Point:
    zero = jnp.zeros_like(shape_ref)
    one = zero.at[..., 0].set(1)
    return (zero, one, one, zero)


def point_add(p: Point, q: Point) -> Point:
    """Unified addition, add-2008-hwcd-3 for a=-1 (8 muls)."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = F.mul(F.sub(y1, x1), F.sub(y2, x2))
    b = F.mul(F.add(y1, x1), F.add(y2, x2))
    c = F.mul(F.mul(t1, _D2_L), t2)
    d = F.mul(F.add(z1, z1), z2)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def point_double(p: Point) -> Point:
    """dbl-2008-hwcd for a=-1 (4 muls + 4 squares)."""
    x1, y1, z1, _ = p
    a = F.square(x1)
    b = F.square(y1)
    c = F.add(F.square(z1), F.square(z1))
    h = F.add(a, b)
    e = F.sub(h, F.square(F.add(x1, y1)))
    g = F.sub(a, b)
    f = F.add(c, g)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def point_neg(p: Point) -> Point:
    x, y, z, t = p
    return (F.neg(x), y, z, F.neg(t))


def _select(cond: jnp.ndarray, a: Point, b: Point) -> Point:
    """Per-item point select; cond is batch-shaped bool."""
    c = cond[..., None]
    return tuple(jnp.where(c, ai, bi) for ai, bi in zip(a, b))


def decompress(y_limbs: jnp.ndarray, sign: jnp.ndarray) -> Tuple[Point, jnp.ndarray]:
    """RFC 8032 point decompression on device (sqrt via the 2^252-3 chain).

    ``y_limbs``: (..., 20) the y coordinate (already checked < p on host);
    ``sign``: (...,) 0/1 x-parity bit.  Returns (point, ok_mask).
    """
    yy = F.square(y_limbs)
    u = F.sub(yy, _ONE)
    v = F.add(F.mul(_D_L, yy), _ONE)
    # x = u v^3 (u v^7)^((p-5)/8)
    v3 = F.mul(F.square(v), v)
    v7 = F.mul(F.square(v3), v)
    x = F.mul(F.mul(u, v3), F.pow22523(F.mul(u, v7)))
    vxx = F.mul(v, F.square(x))
    ok_direct = F.eq_canonical(vxx, u)
    ok_flipped = F.eq_canonical(vxx, F.neg(u))
    x = jnp.where(ok_direct[..., None], x, F.mul(x, _SQRT_M1_L))
    ok = ok_direct | ok_flipped
    # x == 0 with sign bit set is invalid (no -0).
    x_is_zero = F.is_zero(x)
    ok = ok & ~(x_is_zero & (sign == 1))
    # Match parity to the requested sign.
    flip = (F.parity(x) != sign) & ~x_is_zero
    x = jnp.where(flip[..., None], F.neg(x), x)
    point = (x, y_limbs, jnp.broadcast_to(_ONE, y_limbs.shape), F.mul(x, y_limbs))
    return point, ok


# ---------------------------------------------------------------------------
# Windowed double-scalar multiplication
# ---------------------------------------------------------------------------
#
# [s]B uses a positional comb table precomputed ONCE on the host with python
# ints (B is a protocol constant): T_B[w][v] = v * 16^w * B.  [s]B is then just
# 64 table additions — zero doublings.  [k]A runs a 4-bit windowed ladder with
# a 16-entry per-item table (15 vmapped adds to build), i.e. 256 doublings +
# 64 adds instead of 256 doublings + ~128 conditional adds.  Verification is
# not secret-dependent, so data-dependent *gathers* are fine (no constant-time
# requirement); shapes remain static.

_WINDOWS = 64  # 4-bit windows covering 256 bits


def _affine_add(p, q):
    """Host-side python-int Edwards addition (for table generation only)."""
    if p is None:
        return q
    if q is None:
        return p
    x1, y1 = p
    x2, y2 = q
    den1 = pow(1 + _D * x1 * x2 * y1 * y2, P - 2, P)
    den2 = pow(1 - _D * x1 * x2 * y1 * y2, P - 2, P)
    return ((x1 * y2 + x2 * y1) * den1 % P, (y1 * y2 + x1 * x2) * den2 % P)


def _build_base_comb() -> np.ndarray:
    """(64, 16, 4, 20) int32: extended-coordinate entries of v*16^w*B."""
    table = np.zeros((_WINDOWS, 16, 4, F.NLIMBS), np.int32)
    step = (_BX, _BY)  # 16^w * B
    for w in range(_WINDOWS):
        entry = None  # v * step
        for v in range(16):
            if entry is None:
                x, y = 0, 1
            else:
                x, y = entry
            table[w, v, 0] = F.int_to_limbs(x)
            table[w, v, 1] = F.int_to_limbs(y)
            table[w, v, 2] = F.int_to_limbs(1)
            table[w, v, 3] = F.int_to_limbs(x * y % P)
            entry = _affine_add(entry, step)
        for _ in range(4):
            step = _affine_add(step, step)
    return table


_B_COMB = jnp.asarray(_build_base_comb())


def _gather_point(table: Point, idx: jnp.ndarray) -> Point:
    """Select per-item entries: table coords (..., 16, 20), idx (...,).

    Implemented as a one-hot masked sum, not a gather — dynamic gathers
    serialize on the TPU VPU while the 16 multiply-adds stay lane-parallel.
    """
    onehot = (idx[..., None] == jnp.arange(16, dtype=jnp.int32)).astype(jnp.int32)
    return tuple(
        jnp.sum(onehot[..., :, None] * c, axis=-2) for c in table
    )


def _double_scalar_mul(
    s_windows: jnp.ndarray, k_windows: jnp.ndarray, neg_a: Point
) -> Point:
    """[s]B + [k]negA.

    ``s_windows``: (..., 64) int32 in 0..15, index 0 = LEAST significant window
    (positional, matches the comb table).  ``k_windows``: same layout; the
    ladder consumes them most-significant first.
    """
    # --- [k]negA: per-item 16-entry table, then 4-bit ladder ---
    identity = _identity_like(neg_a[0])
    tab = [identity, neg_a]
    for v in range(2, 16):
        tab.append(point_add(tab[v - 1], neg_a))
    # (..., 16, 20) per coordinate.
    tab_a: Point = tuple(
        jnp.stack([t[c] for t in tab], axis=-2) for c in range(4)
    )

    def ladder_step(acc: Point, kw):
        for _ in range(4):
            acc = point_double(acc)
        acc = point_add(acc, _gather_point(tab_a, kw))
        return acc, None

    kw_msb_first = jnp.moveaxis(k_windows[..., ::-1], -1, 0)  # scan axis front
    acc, _ = jax.lax.scan(ladder_step, identity, kw_msb_first)

    # --- [s]B: 64 comb-table additions, no doublings ---
    def comb_step(acc: Point, inputs):
        entries, sw = inputs  # entries: (16, 4, 20) const slice; sw: (...,)
        table: Point = tuple(
            jnp.broadcast_to(
                entries[:, c, :], (*sw.shape, 16, F.NLIMBS)
            )
            for c in range(4)
        )
        return point_add(acc, _gather_point(table, sw)), None

    sw = jnp.moveaxis(s_windows, -1, 0)
    acc_b, _ = jax.lax.scan(comb_step, identity, (_B_COMB, sw))

    return point_add(acc, acc_b)


def verify_impl(
    a_y: jnp.ndarray,  # (B, 20) public key y limbs
    a_sign: jnp.ndarray,  # (B,)
    r_y: jnp.ndarray,  # (B, 20) signature R y limbs (raw, unvalidated)
    r_sign: jnp.ndarray,  # (B,)
    s_windows: jnp.ndarray,  # (B, 64) 4-bit windows of s, LSB window first
    k_windows: jnp.ndarray,  # (B, 64) 4-bit windows of k, LSB window first
    host_ok: jnp.ndarray,  # (B,) host-side checks (s < L, canonical A, ...)
) -> jnp.ndarray:
    """Batched device verification; returns (B,) bool."""
    neg_a, decompress_ok = jax.vmap(decompress)(a_y, a_sign)
    neg_a = point_neg(neg_a)
    res = _double_scalar_mul(s_windows, k_windows, neg_a)
    x, y, z, _ = res
    zinv = F.invert(z)
    x_aff = F.mul(x, zinv)
    y_aff = F.mul(y, zinv)
    # Canonical-encode and compare against raw R bytes (memcmp semantics): a
    # non-canonical R can never equal the canonical encoding -> rejected.
    match = F.eq_canonical(y_aff, r_y) & (F.parity(x_aff) == r_sign)
    return match & decompress_ok & host_ok


verify_kernel = jax.jit(verify_impl)


# ---------------------------------------------------------------------------
# Host-side packing
# ---------------------------------------------------------------------------


def _windows_lsb_first(x: int) -> np.ndarray:
    return np.array([(x >> (4 * w)) & 15 for w in range(_WINDOWS)], dtype=np.int32)


def _ylimbs_and_sign(data32: bytes) -> Tuple[np.ndarray, int, int]:
    """Parse a 32-byte point encoding: (y limbs, sign bit, y-as-int)."""
    enc = int.from_bytes(data32, "little")
    sign = enc >> 255
    y = enc & ((1 << 255) - 1)
    return F.int_to_limbs(y), sign, y


def pack_batch(
    public_keys: Sequence[bytes],
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
) -> Tuple[np.ndarray, ...]:
    """Host-side preparation of a verification batch.

    Computes k = SHA-512(R || A || M) mod L per item (the fused on-device
    digest path replaces this for 32-byte block digests), performs the cheap
    integer checks, and packs limb/bit arrays for :func:`verify_kernel`.
    """
    n = len(signatures)
    a_y = np.zeros((n, F.NLIMBS), np.int32)
    a_sign = np.zeros(n, np.int32)
    r_y = np.zeros((n, F.NLIMBS), np.int32)
    r_sign = np.zeros(n, np.int32)
    s_bits = np.zeros((n, _WINDOWS), np.int32)
    k_bits = np.zeros((n, _WINDOWS), np.int32)
    host_ok = np.zeros(n, bool)
    for i, (pk, msg, sig) in enumerate(zip(public_keys, messages, signatures)):
        if len(pk) != 32 or len(sig) != 64:
            continue
        r_bytes, s_bytes = sig[:32], sig[32:]
        s = int.from_bytes(s_bytes, "little")
        if s >= L:
            continue  # non-canonical s: reject (RFC 8032 / OpenSSL)
        limbs, sign, y = _ylimbs_and_sign(pk)
        if y >= P:
            continue  # non-canonical A encoding
        a_y[i], a_sign[i] = limbs, sign
        r_limbs, rs, ry = _ylimbs_and_sign(r_bytes)
        if ry >= P:
            # Non-canonical R encoding: OpenSSL's memcmp of encode([s]B - [k]A)
            # against the raw R bytes can never match a y >= p encoding, so
            # reject on host.  Keeps the device compare (eq_canonical, which
            # would reduce mod p) exactly equivalent to memcmp semantics.
            continue
        r_y[i], r_sign[i] = r_limbs, rs
        k = int.from_bytes(hashlib.sha512(r_bytes + pk + msg).digest(), "little") % L
        s_bits[i] = _windows_lsb_first(s)
        k_bits[i] = _windows_lsb_first(k)
        host_ok[i] = True
    return a_y, a_sign, r_y, r_sign, s_bits, k_bits, host_ok


# Fixed device batch size: every dispatch is padded to a multiple of this, so
# XLA compiles the kernel exactly once per process (shape stability is the TPU
# contract; stragglers ride along as padding lanes with host_ok=False).
BUCKET = 64


def verify_batch(
    public_keys: Sequence[bytes],
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
) -> np.ndarray:
    """End-to-end batched verify; returns np.ndarray of bool, one per item."""
    n = len(signatures)
    if n == 0:
        return np.zeros(0, bool)
    packed = pack_batch(public_keys, messages, signatures)
    pad = (-n) % BUCKET
    out = np.zeros(n + pad, bool)
    for start in range(0, n + pad, BUCKET):
        chunk = [
            jnp.asarray(np.ascontiguousarray(_pad(x, pad)[start : start + BUCKET]))
            for x in packed
        ]
        out[start : start + BUCKET] = np.asarray(verify_kernel(*chunk))
    return out[:n]


def _pad(x: np.ndarray, pad: int) -> np.ndarray:
    if pad == 0:
        return x
    widths = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
    return np.pad(x, widths)
