"""JAX/TPU kernels for the block-verification hot path.

This package is the TPU-native replacement for the reference's CPU crypto
(``mysticeti-core/src/crypto.rs:174-189`` verify_block): batched Ed25519
verification expressed as int32 limb arithmetic that XLA vectorizes on the
TPU VPU, ``vmap``ped over the signature batch and shardable across chips with
``shard_map`` (see ``mysticeti_tpu.parallel``).

Modules:
  field    — GF(2^255-19) arithmetic in 20x13-bit int32 limbs
  ed25519  — twisted-Edwards point ops + the batched verify kernel
  sha512   — SHA-512 compression in 32-bit lanes (fused digest+verify path)
"""
