"""Pallas TPU kernel for batched Ed25519 verification — the VMEM-resident ladder.

Why this exists: the XLA graph version (:mod:`mysticeti_tpu.ops.ed25519`)
materializes every intermediate limb array between ops, so the 256-step
double-and-add ladder is HBM-bandwidth-bound (~50k sig/s measured on v5e
despite ~8.6G field-muls/s of raw VPU throughput).  This kernel runs the
*entire* verification — decompression, per-item table build, the fused
[s]B + [k](-A) window loop, final inversion and canonical compare — inside one
``pallas_call`` whose working set lives in VMEM, tiled over the batch.

Layout: limb-major ``(NLIMBS, TILE)`` so the batch dimension maps to TPU
*lanes* (128-wide) and the 20 limbs to sublanes; every field op is then a
handful of dense vector registers.  Field arithmetic is the same 20x13-bit
int32 schoolbook design as :mod:`mysticeti_tpu.ops.field` (see its module
docstring for the carry discipline) transposed to limb-major form.

Replaces the reference's serial per-block CPU verify
(``mysticeti-core/src/crypto.rs:174-189``, call site ``types.rs:315-347``).
Verification rule is identical to ``ops/ed25519.verify_impl`` (cofactorless,
OpenSSL memcmp semantics); parity is enforced in tests/test_ed25519_pallas.py.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import ed25519 as E
from . import field as F

RADIX = F.RADIX
NLIMBS = F.NLIMBS
MASK = F.MASK
FOLD_260 = F.FOLD_260
FOLD_256 = F.FOLD_256
_WORK = 2 * NLIMBS + 2

Point = Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]

# ---------------------------------------------------------------------------
# Limb-major field arithmetic: every element is (NLIMBS, T) int32, batch on
# the minor (lane) axis.  Constants broadcast from (NLIMBS, 1).
# ---------------------------------------------------------------------------

def _cst(x: int) -> np.ndarray:
    return F.int_to_limbs(x % F.P).reshape(NLIMBS, 1)


# Pallas kernels cannot close over array constants — the six field constants
# (+ a zero plane) are passed as one (7, NLIMBS, tile) input (_consts_wide)
# and re-bound to this namespace at kernel trace time (_bind_consts).
#
# THREAD-LOCAL: kernel flavors trace concurrently in a validator (the
# verifier warmup thread compiles one kernel while a peer batch traces
# another on an executor thread); a shared namespace lets one trace read the
# other's bindings mid-trace, which surfaces as a "captures constants"
# pallas error (or silently wrong constants).  Each tracing thread gets its
# own bindings.
import threading as _threading


class _ConstNS(_threading.local):
    one: jnp.ndarray
    bias_8p: jnp.ndarray
    p_limbs: jnp.ndarray
    d: jnp.ndarray
    d2: jnp.ndarray
    sqrt_m1: jnp.ndarray
    zero: jnp.ndarray


_C = _ConstNS()

_CONSTS_NP = np.concatenate(
    [
        _cst(1),
        np.array(
            [(1 << RADIX) - 152] + [MASK] * 18 + [(1 << 11) - 1], dtype=np.int32
        ).reshape(NLIMBS, 1),
        np.array(
            [(1 << RADIX) - 19] + [MASK] * 18 + [255], dtype=np.int32
        ).reshape(NLIMBS, 1),
        _cst(E._D),
        _cst(E._D2),
        _cst(E._SQRT_M1),
    ],
    axis=1,
)  # (NLIMBS, 6)


def _consts_wide(tile: int) -> np.ndarray:
    """(7, NLIMBS, tile): the six field constants + a zero plane, materialized
    lane-wide on the host.  In-kernel ``jnp.broadcast_to``/``zeros`` produce
    Mosaic "replicated" vector layouts, and slicing those crashes the Mosaic
    layout pass — loading real data from VMEM sidesteps the whole class of
    bugs and costs only 7*20*tile*4 bytes."""
    cols = np.concatenate([_CONSTS_NP[:, :6], np.zeros((NLIMBS, 1), np.int32)], axis=1)
    return np.ascontiguousarray(
        np.broadcast_to(cols.T[:, :, None], (7, NLIMBS, tile)).astype(np.int32)
    )


def _bind_consts(consts_ref) -> None:
    _C.one = consts_ref[0]
    _C.bias_8p = consts_ref[1]
    _C.p_limbs = consts_ref[2]
    _C.d = consts_ref[3]
    _C.d2 = consts_ref[4]
    _C.sqrt_m1 = consts_ref[5]
    _C.zero = consts_ref[6]


def _carry(x: jnp.ndarray) -> jnp.ndarray:
    c = x >> RADIX
    x = x - (c << RADIX)
    return x + jnp.concatenate([jnp.zeros_like(c[:1]), c[:-1]], axis=0)


def _normalize_top(x: jnp.ndarray) -> jnp.ndarray:
    c = x[NLIMBS - 1 : NLIMBS] >> 9
    x = jnp.concatenate(
        [x[:1] + FOLD_256 * c, x[1 : NLIMBS - 1], x[NLIMBS - 1 :] - (c << 9)], axis=0
    )
    return _carry(x)


def _fold_reduce(wide: jnp.ndarray) -> jnp.ndarray:
    # One carry pass on the wide (42, T) array: diagonal sums < 2^30.4 decay
    # to limbs <= 2^17.4.  Folding immediately is then safe (608 * 2^17.4 +
    # 2^17.4 < 2^27) and moves all later carry work onto a cheap 21-limb
    # workspace instead of the 42-limb one.
    x = _carry(wide)
    lo = jnp.concatenate([x[:NLIMBS], jnp.zeros_like(x[:1])], axis=0)  # (21, T)
    lo = lo + FOLD_260 * x[NLIMBS : 2 * NLIMBS + 1]
    lo = _carry(_carry(lo))
    lo = jnp.concatenate(
        [lo[:1] + FOLD_260 * lo[NLIMBS : NLIMBS + 1], lo[1:NLIMBS]], axis=0
    )
    c = lo[NLIMBS - 1 : NLIMBS] >> RADIX
    lo = jnp.concatenate(
        [lo[:1] + FOLD_260 * c, lo[1 : NLIMBS - 1], lo[NLIMBS - 1 :] - (c << RADIX)],
        axis=0,
    )
    return _normalize_top(_carry(lo))


def fmul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    wide = None
    for i in range(NLIMBS):
        term = a[i : i + 1] * b  # (NLIMBS, T)
        padded = jnp.pad(term, ((i, _WORK - NLIMBS - i), (0, 0)))
        wide = padded if wide is None else wide + padded
    return _fold_reduce(wide)


def fsq(a: jnp.ndarray) -> jnp.ndarray:
    # Triangle squaring was measured perf-neutral here (concat overhead eats
    # the halved product count) — plain schoolbook keeps the code simple.
    return fmul(a, a)


def fadd(a, b):
    # Lazy add: one signed carry pass, top limb left loose (< 2^11 after the
    # shallow add chains in the point formulas) — products and fsub's 8p bias
    # tolerate it, and _fold_reduce restores the tight form after every mul.
    return _carry(a + b)


def fsub(a, b):
    return _normalize_top(_carry(_carry(a + _C.bias_8p - b)))


def fneg(a):
    return fsub(_C.zero, a)


def fpow2k(a: jnp.ndarray, k: int) -> jnp.ndarray:
    return jax.lax.fori_loop(0, k, lambda _, x: fsq(x), a)


def _ladder_chain(z):
    z2 = fsq(z)
    z9 = fmul(fsq(fsq(z2)), z)
    z11 = fmul(z9, z2)
    z2_5_0 = fmul(fsq(z11), z9)
    z2_10_0 = fmul(fpow2k(z2_5_0, 5), z2_5_0)
    z2_20_0 = fmul(fpow2k(z2_10_0, 10), z2_10_0)
    z2_40_0 = fmul(fpow2k(z2_20_0, 20), z2_20_0)
    z2_50_0 = fmul(fpow2k(z2_40_0, 10), z2_10_0)
    z2_100_0 = fmul(fpow2k(z2_50_0, 50), z2_50_0)
    z2_200_0 = fmul(fpow2k(z2_100_0, 100), z2_100_0)
    z2_250_0 = fmul(fpow2k(z2_200_0, 50), z2_50_0)
    return z11, z2_250_0


def finv(z):
    z11, z2_250_0 = _ladder_chain(z)
    return fmul(fpow2k(z2_250_0, 5), z11)


def fpow22523(z):
    _, z2_250_0 = _ladder_chain(z)
    return fmul(fpow2k(z2_250_0, 2), z)


def _full_carry(x):
    return jax.lax.fori_loop(0, NLIMBS + 1, lambda _, v: _carry(v), x)


def fcanonical(x: jnp.ndarray) -> jnp.ndarray:
    for _ in range(2):
        c = x[NLIMBS - 1 : NLIMBS] >> 8
        x = jnp.concatenate(
            [x[:1] + 19 * c, x[1 : NLIMBS - 1], x[NLIMBS - 1 :] - (c << 8)], axis=0
        )
        x = _full_carry(x)
    ge_p = (
        (x[NLIMBS - 1 : NLIMBS] == 255)
        & jnp.all(x[1 : NLIMBS - 1] == MASK, axis=0, keepdims=True)
        & (x[:1] >= (1 << RADIX) - 19)
    )
    return jnp.where(ge_p, x - _C.p_limbs, x)


def feq(a: jnp.ndarray, b_canonical: jnp.ndarray) -> jnp.ndarray:
    """a (partial form) == b (already canonical limbs); returns (1, T) bool."""
    return jnp.all(fcanonical(a) == b_canonical, axis=0, keepdims=True)


def fis_zero(a):
    return jnp.all(fcanonical(a) == 0, axis=0, keepdims=True)


def fparity(a):
    return fcanonical(a)[:1] & 1


# ---------------------------------------------------------------------------
# Point ops (extended twisted-Edwards, a=-1), limb-major
# ---------------------------------------------------------------------------

def point_add(p: Point, q: Point, want_t: bool = True):
    """Unified extended addition (add-2008-hwcd-3, a=-1); 9 muls, 8 with
    ``want_t=False`` (legal when the result only feeds doublings, which never
    read T)."""
    x1, y1, z1, t1 = p[0], p[1], p[2], p[3]
    x2, y2, z2, t2 = q
    a = fmul(fsub(y1, x1), fsub(y2, x2))
    b = fmul(fadd(y1, x1), fadd(y2, x2))
    c = fmul(fmul(t1, _C.d2), t2)
    d = fmul(fadd(z1, z1), z2)
    e = fsub(b, a)
    f = fsub(d, c)
    g = fadd(d, c)
    h = fadd(b, a)
    out = (fmul(e, f), fmul(g, h), fmul(f, g))
    return (*out, fmul(e, h)) if want_t else out


def point_madd(p: Point, q3) -> Point:
    """Mixed addition with a Niels-form precomputed point q3 = (y-x, y+x,
    2d*xy), Z=1 (madd-2008-hwcd): 7 muls.  Used for the fixed-base comb."""
    x1, y1, z1, t1 = p
    q_ymx, q_ypx, q_t2d = q3
    a = fmul(fsub(y1, x1), q_ymx)
    b = fmul(fadd(y1, x1), q_ypx)
    c = fmul(t1, q_t2d)
    d = fadd(z1, z1)
    e = fsub(b, a)
    f = fsub(d, c)
    g = fadd(d, c)
    h = fadd(b, a)
    return (fmul(e, f), fmul(g, h), fmul(f, g), fmul(e, h))


def point_double(p, want_t: bool = True):
    """dbl-2008-hwcd: never reads T; emits it only when the next op is an
    addition (the 4th double of each window group)."""
    x1, y1, z1 = p[0], p[1], p[2]
    a = fsq(x1)
    b = fsq(y1)
    c = fadd(fsq(z1), fsq(z1))
    h = fadd(a, b)
    e = fsub(h, fsq(fadd(x1, y1)))
    g = fsub(a, b)
    f = fadd(c, g)
    out = (fmul(e, f), fmul(g, h), fmul(f, g))
    return (*out, fmul(e, h)) if want_t else out


def point_neg(p: Point) -> Point:
    x, y, z, t = p
    return (fneg(x), y, z, fneg(t))


def _dbl4(p, want_t: bool = True):
    """Four doublings; T materialized only on the last (if requested)."""
    p = point_double(p, want_t=False)
    p = point_double(p, want_t=False)
    p = point_double(p, want_t=False)
    return point_double(p, want_t=want_t)


def _identity(t: int) -> Point:
    del t
    return (_C.zero, _C.one, _C.one, _C.zero)


def decompress(y: jnp.ndarray, sign: jnp.ndarray) -> Tuple[Point, jnp.ndarray]:
    """y (NLIMBS, T) canonical (< p), sign (1, T); returns (point, (1,T) ok)."""
    yy = fsq(y)
    u = fsub(yy, _C.one)
    # Constant operand second: fmul slices rows of its first arg, and a row of
    # a broadcast constant is a (1,1)->both-dims broadcast Mosaic rejects.
    v = fadd(fmul(yy, _C.d), _C.one)
    v3 = fmul(fsq(v), v)
    v7 = fmul(fsq(v3), v)
    x = fmul(fmul(u, v3), fpow22523(fmul(u, v7)))
    vxx = fmul(v, fsq(x))
    vxx_c = fcanonical(vxx)
    ok_direct = jnp.all(vxx_c == fcanonical(u), axis=0, keepdims=True)
    ok_flipped = jnp.all(vxx_c == fcanonical(fneg(u)), axis=0, keepdims=True)
    x = jnp.where(ok_direct, x, fmul(x, _C.sqrt_m1))
    ok = ok_direct | ok_flipped
    x_is_zero = fis_zero(x)
    ok = ok & ~(x_is_zero & (sign == 1))
    flip = (fparity(x) != sign) & ~x_is_zero
    x = jnp.where(flip, fneg(x), x)
    point = (x, y, _C.one, fmul(x, y))
    return point, ok


def _gather16(tab: List[Point], idx: jnp.ndarray) -> Point:
    """One-hot select over a 16-entry per-item point table; idx (1, T)."""
    coords = []
    for c in range(4):
        acc = None
        for v in range(16):
            m = (idx == v).astype(jnp.int32)  # (1, T)
            t = m * tab[v][c]
            acc = t if acc is None else acc + t
        coords.append(acc)
    return tuple(coords)


def _gather_comb(entry: jnp.ndarray, idx: jnp.ndarray):
    """entry (3, NLIMBS, 16) Niels-form slice; idx (1, T) -> (ymx, ypx, t2d)."""
    coords = []
    for c in range(3):
        acc = None
        for v in range(16):
            m = (idx == v).astype(jnp.int32)  # (1, T)
            t = entry[c, :, v : v + 1] * m  # (NLIMBS, 1) * (1, T)
            acc = t if acc is None else acc + t
        coords.append(acc)
    return tuple(coords)


def _build_niels_comb() -> np.ndarray:
    """(64, 3, NLIMBS, 16): the fixed-base comb in Niels form (y-x, y+x,
    2d*xy mod p), one 16-entry table per 4-bit window of s (v * 16^w * B)."""
    raw = E._build_base_comb()  # (64, 16, 4, 20) extended (X, Y, Z=1, T)
    out = np.zeros((64, 3, NLIMBS, 16), np.int32)
    for w in range(64):
        for v in range(16):
            x = F.limbs_to_int(raw[w, v, 0])
            y = F.limbs_to_int(raw[w, v, 1])
            out[w, 0, :, v] = F.int_to_limbs((y - x) % F.P)
            out[w, 1, :, v] = F.int_to_limbs((y + x) % F.P)
            out[w, 2, :, v] = F.int_to_limbs(2 * E._D * x * y % F.P)
    return out


_COMB_T = _build_niels_comb()


# ---------------------------------------------------------------------------
# The kernel
# ---------------------------------------------------------------------------


def _verify_body(
    consts_ref,
    comb_ref,
    a_y_ref,
    a_sign_ref,
    r_y_ref,
    r_sign_ref,
    s_w_ref,
    k_w_ref,
    host_ok_ref,
    out_ref,
):
    t = a_y_ref.shape[1]
    _bind_consts(consts_ref)
    a_y = a_y_ref[...]
    a_sign = a_sign_ref[...]
    neg_a, dec_ok = decompress(a_y, a_sign)
    neg_a = point_neg(neg_a)

    ident = _identity(t)
    tab: List[Point] = [ident, neg_a]
    for v in range(2, 16):
        tab.append(point_add(tab[v - 1], neg_a))

    def step(i, carry):
        acc_a = carry[:3]  # X, Y, Z only — T is dead between window groups
        acc_b = carry[3:]
        acc_a = _dbl4(acc_a)
        kw = k_w_ref[pl.ds(63 - i, 1), :]  # ladder consumes MSB window first
        acc_a = point_add(acc_a, _gather16(tab, kw), want_t=False)
        sw = s_w_ref[pl.ds(i, 1), :]
        entry = comb_ref[i]  # (3, NLIMBS, 16) Niels form
        acc_b = point_madd(acc_b, _gather_comb(entry, sw))
        return (*acc_a, *acc_b)

    carry = jax.lax.fori_loop(0, 63, step, (*ident[:3], *ident))
    # Peeled last window: the final adds must materialize T for the combine.
    acc_a = _dbl4(carry[:3])
    acc_a = point_add(acc_a, _gather16(tab, k_w_ref[pl.ds(0, 1), :]))
    acc_b = point_madd(
        carry[3:], _gather_comb(comb_ref[63], s_w_ref[pl.ds(63, 1), :])
    )
    res = point_add(acc_a, acc_b)

    x, y, z, _ = res
    zinv = finv(z)
    x_aff = fmul(x, zinv)
    y_aff = fmul(y, zinv)
    # Exact compare on the raw R limbs (memcmp semantics): a non-canonical R
    # (y >= p) can never equal fcanonical output, so it is rejected.
    match = feq(y_aff, r_y_ref[...]) & (fparity(x_aff) == r_sign_ref[...])
    ok = match & dec_ok & (host_ok_ref[...] != 0)
    out_ref[...] = ok.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def _verify_pallas_jit(
    a_y, a_sign, r_y, r_sign, s_w, k_w, host_ok, *, tile: int, interpret: bool
):
    b = a_y.shape[0]
    grid = (b // tile,)
    col = lambda i: (0, i)
    kernel = pl.pallas_call(
        _verify_body,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (7, NLIMBS, tile), lambda i: (0, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (64, 4, NLIMBS, 16), lambda i: (0, 0, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((NLIMBS, tile), col, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile), col, memory_space=pltpu.VMEM),
            pl.BlockSpec((NLIMBS, tile), col, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile), col, memory_space=pltpu.VMEM),
            pl.BlockSpec((64, tile), col, memory_space=pltpu.VMEM),
            pl.BlockSpec((64, tile), col, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile), col, memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, tile), col, memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, b), jnp.int32),
        interpret=interpret,
    )
    out = kernel(
        jnp.asarray(_consts_wide(tile)),
        jnp.asarray(_COMB_T),
        a_y.T,
        a_sign[None, :].astype(jnp.int32),
        r_y.T,
        r_sign[None, :].astype(jnp.int32),
        s_w.T,
        k_w.T,
        host_ok[None, :].astype(jnp.int32),
    )
    return out[0].astype(bool)


# ---------------------------------------------------------------------------
# Keyed-tile kernel: every tile holds signatures of ONE committee key, whose
# precomputed negated comb (ops.ed25519.build_neg_key_combs) is DMA'd into
# VMEM via a scalar-prefetched index.  [s]B + [k](-A) is then 128 Niels
# additions — zero doublings, no on-device A decompression — roughly a third
# of the generic ladder's field multiplications.
# ---------------------------------------------------------------------------


def _verify_keyed_body(
    keys_ref,
    consts_ref,
    bcomb_ref,
    acomb_ref,
    r_y_ref,
    r_sign_ref,
    s_w_ref,
    k_w_ref,
    host_ok_ref,
    out_ref,
):
    del keys_ref  # consumed by acomb's index_map; the body never reads it
    t = r_y_ref.shape[1]
    _bind_consts(consts_ref)

    def step(i, acc):
        acc = point_madd(acc, _gather_comb(bcomb_ref[i], s_w_ref[pl.ds(i, 1), :]))
        acc = point_madd(
            acc, _gather_comb(acomb_ref[0, i], k_w_ref[pl.ds(i, 1), :])
        )
        return acc

    res = jax.lax.fori_loop(0, 64, step, _identity(t))
    x, y, z, _ = res
    zinv = finv(z)
    x_aff = fmul(x, zinv)
    y_aff = fmul(y, zinv)
    # Exact compare on the raw R limbs (memcmp semantics, see _verify_body).
    match = feq(y_aff, r_y_ref[...]) & (fparity(x_aff) == r_sign_ref[...])
    ok = match & (host_ok_ref[...] != 0)
    out_ref[...] = ok.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def _verify_keyed_pallas_jit(
    tile_keys, acomb, r_y, r_sign, s_w, k_w, host_ok, positions, *, tile, interpret
):
    b = r_y.shape[0]
    grid = (b // tile,)
    col = lambda i, keys: (0, i)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (7, NLIMBS, tile),
                lambda i, keys: (0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (64, 3, NLIMBS, 16),
                lambda i, keys: (0, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            # The tile's key selects which comb is DMA'd; consecutive tiles
            # sharing a key (the grouped layout sorts them) skip the copy.
            pl.BlockSpec(
                (1, 64, 3, NLIMBS, 16),
                lambda i, keys: (keys[i], 0, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((NLIMBS, tile), col, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile), col, memory_space=pltpu.VMEM),
            pl.BlockSpec((64, tile), col, memory_space=pltpu.VMEM),
            pl.BlockSpec((64, tile), col, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile), col, memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, tile), col, memory_space=pltpu.VMEM),
    )
    kernel = pl.pallas_call(
        _verify_keyed_body,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((1, b), jnp.int32),
        interpret=interpret,
    )
    out = kernel(
        tile_keys,
        jnp.asarray(_consts_wide(tile)),
        jnp.asarray(_COMB_T),
        acomb,
        r_y.T,
        r_sign[None, :].astype(jnp.int32),
        s_w.T,
        k_w.T,
        host_ok[None, :].astype(jnp.int32),
    )
    # Un-permute back to the caller's order on device when positions ride
    # along (positions maps original row -> grouped row); with
    # positions=None the (b,) GROUPED-order lanes return as-is and the
    # caller un-permutes on host — skipping the positions upload entirely
    # (4 B/sig of a bandwidth-bound tunnel transfer).
    if positions is None:
        return out[0].astype(bool)
    return jnp.take(out[0], positions).astype(bool)


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def _verify_keyed_blob_jit(blob, table, acomb, tile_keys, positions, *, tile, interpret):
    # A-word gather + SHA-512 + parse in XLA; the a_y/a_sign outputs of
    # prepare_fused are dead here (no decompression) and DCE'd by XLA.
    msg_words, s_words, host_ok = E.indexed_to_msg_words(blob, table)
    _a_y, _a_sign, r_y, r_sign, s_w, k_w, ok = E.prepare_fused(
        msg_words, s_words, host_ok
    )
    return _verify_keyed_pallas_jit(
        tile_keys, acomb, r_y, r_sign, s_w, k_w, ok, positions,
        tile=tile, interpret=interpret,
    )


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def _verify_keyed_flat_jit(flat, table, acomb, tile_keys, *, tile, interpret):
    # Wire-minimal keyed dispatch: the grouped layout makes the per-lane key
    # index REDUNDANT (every lane of a tile shares tile_keys[tile]) and the
    # host_ok flags compress to one bit per lane, all folded into ONE flat
    # upload — R||M||s (96 B/sig) + ~0.13 B/sig of mask.  Both the byte
    # count AND the transfer count matter on the tunnel: each extra array
    # pays a per-transfer setup comparable to several KB of payload.
    b = tile_keys.shape[0] * tile
    blob24 = flat[: b * 24].reshape(b, 24)
    okmask = flat[b * 24 :]
    idx = jnp.repeat(
        tile_keys.astype(jnp.int32), tile, total_repeat_length=b
    )
    a_words = table[jnp.clip(idx, 0, table.shape[0] - 1)]
    msg_words = jnp.concatenate(
        [blob24[:, :8], a_words, blob24[:, 8:16]], axis=-1
    )
    lane = jnp.arange(b)
    ok = ((okmask[lane // 32] >> (lane % 32)) & 1) != 0
    _a_y, _a_sign, r_y, r_sign, s_w, k_w, okk = E.prepare_fused(
        msg_words, blob24[:, 16:24], ok
    )
    return _verify_keyed_pallas_jit(
        tile_keys, acomb, r_y, r_sign, s_w, k_w, okk, None,
        tile=tile, interpret=interpret,
    )


def verify_keyed_flat(
    flat,
    table_words,
    acomb,
    tile_keys,
    *,
    tile: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Keyed-tile verification of a GROUPED flat upload: b*24 R/M/s words
    followed by b/32 packed little-bit-order ok words; returns (b,) bool in
    GROUPED order (callers un-permute on host via the grouping positions)."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    if tile is None:
        tile = default_tile()
    b = int(tile_keys.shape[0]) * tile
    if b % 32 != 0:
        # The ok mask is read as packed 32-lane words; a floor-sized mask
        # for a ragged tail would alias earlier lanes' bits via the clamped
        # gather — reject instead.
        raise ValueError(f"batch {b} not a multiple of 32")
    if flat.shape[0] != b * 24 + b // 32:
        raise ValueError(
            f"flat upload of {flat.shape[0]} words != {b}*24 + {b}//32"
        )
    return _verify_keyed_flat_jit(
        jnp.asarray(flat),
        jnp.asarray(table_words),
        jnp.asarray(acomb),
        jnp.asarray(tile_keys),
        tile=tile,
        interpret=interpret,
    )


def verify_keyed_blob(
    grouped,
    table_words,
    acomb,
    tile_keys,
    positions,
    *,
    tile: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Keyed-tile fused verification of a GROUPED indexed blob
    (ops.ed25519.group_blob_for_tiles layout).  Returns (b,) bool in the
    ORIGINAL (pre-grouping) order, padding lanes last — or, with
    ``positions=None``, in GROUPED order (the caller un-permutes on host)."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    if tile is None:
        tile = default_tile()
    b = grouped.shape[0]
    if b % tile != 0:
        raise ValueError(f"batch {b} not a multiple of tile {tile}")
    return _verify_keyed_blob_jit(
        jnp.asarray(grouped),
        jnp.asarray(table_words),
        jnp.asarray(acomb),
        jnp.asarray(tile_keys),
        None if positions is None else jnp.asarray(positions),
        tile=tile,
        interpret=interpret,
    )


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def _verify_fused_pallas_jit(msg_words, s_words, host_ok, *, tile, interpret):
    # Parse/hash/reduce in XLA (cheap, fuses well), ladder in Pallas (VMEM).
    a_y, a_sign, r_y, r_sign, s_w, k_w, ok = E.prepare_fused(
        msg_words, s_words, host_ok
    )
    return _verify_pallas_jit(
        a_y, a_sign, r_y, r_sign, s_w, k_w, ok, tile=tile, interpret=interpret
    )


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def _verify_fused_blob_pallas_jit(blob, *, tile, interpret):
    args = E.prepare_fused(blob[..., :24], blob[..., 24:32], blob[..., 32] != 0)
    return _verify_pallas_jit(*args, tile=tile, interpret=interpret)


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def _verify_fused_indexed_pallas_jit(blob, table, *, tile, interpret):
    # Key-table gather + splice in XLA (trivial), everything else as above.
    args = E.prepare_fused(*E.indexed_to_msg_words(blob, table))
    return _verify_pallas_jit(*args, tile=tile, interpret=interpret)


def verify_fused_blob_pallas(
    blob, *, tile: Optional[int] = None, interpret: Optional[bool] = None
) -> jnp.ndarray:
    """Single-array fused verification (ops.ed25519.pack_blob layout): one
    host->device transfer per batch, parse/hash in XLA, ladder in Pallas."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    if tile is None:
        tile = default_tile()
    b = blob.shape[0]
    if b % tile != 0:
        raise ValueError(f"batch {b} not a multiple of tile {tile}")
    return _verify_fused_blob_pallas_jit(
        jnp.asarray(blob), tile=tile, interpret=interpret
    )


def verify_fused_indexed_blob_pallas(
    blob, table, *, tile: Optional[int] = None, interpret: Optional[bool] = None
) -> jnp.ndarray:
    """Indexed-blob fused verification (ops.ed25519.pack_blob_indexed layout +
    device-resident key table): minimum wire bytes, Pallas ladder."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    if tile is None:
        tile = default_tile()
    b = blob.shape[0]
    if b % tile != 0:
        raise ValueError(f"batch {b} not a multiple of tile {tile}")
    return _verify_fused_indexed_pallas_jit(
        jnp.asarray(blob), jnp.asarray(table), tile=tile, interpret=interpret
    )


def verify_fused_pallas(
    msg_words,
    s_words,
    host_ok,
    *,
    tile: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Fused raw-bytes verification with the Pallas ladder: device SHA-512 +
    mod-L + parsing (ops.ed25519.prepare_fused) feeding the VMEM kernel."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    if tile is None:
        tile = default_tile()
    b = msg_words.shape[0]
    if b % tile != 0:
        raise ValueError(f"batch {b} not a multiple of tile {tile}")
    return _verify_fused_pallas_jit(
        jnp.asarray(msg_words),
        jnp.asarray(s_words),
        jnp.asarray(host_ok),
        tile=tile,
        interpret=interpret,
    )


def default_tile() -> int:
    """256 lanes on real TPUs; tiny tiles are fine under the CPU interpreter."""
    return 256 if jax.default_backend() not in ("cpu",) else 8


def verify_pallas(
    a_y,
    a_sign,
    r_y,
    r_sign,
    s_w,
    k_w,
    host_ok,
    *,
    tile: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Drop-in equivalent of ``ops.ed25519.verify_impl`` (batch-major inputs,
    (B,) bool out) backed by the Pallas kernel.  B must be a multiple of
    ``tile`` (callers pad via the bucket dispatcher)."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    if tile is None:
        tile = default_tile()
    b = a_y.shape[0]
    if b % tile != 0:
        raise ValueError(f"batch {b} not a multiple of tile {tile}")
    return _verify_pallas_jit(
        jnp.asarray(a_y),
        jnp.asarray(a_sign),
        jnp.asarray(r_y),
        jnp.asarray(r_sign),
        jnp.asarray(s_w),
        jnp.asarray(k_w),
        jnp.asarray(host_ok),
        tile=tile,
        interpret=interpret,
    )
