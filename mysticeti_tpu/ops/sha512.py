"""SHA-512 compression on TPU in 32-bit lanes — the fused digest+verify path.

The Ed25519 challenge scalar is k = SHA-512(R || A || M) where M is the fixed
32-byte signed block digest (types.py signed_digest), so the hash input is
always 96 bytes = ONE padded 1024-bit block.  This kernel evaluates that single
compression for a whole batch at once, with every 64-bit word represented as a
(hi, lo) pair of uint32 lanes (TPUs have no 64-bit integer datapath):

* add: uint32 wrap + carry-out via unsigned compare,
* rotr/shr: static shift pairs (the round structure is fully unrolled — 80
  rounds of straight-line vector ops, exactly what XLA fuses well).

Parity with ``hashlib.sha512`` is enforced in tests/test_sha512_tpu.py.
Reference context: the CPU path computes this hash per signature on the host
(crypto.rs:174-189 + RFC 8032); batching it on device removes the last serial
per-item hash from the verification pipeline (BASELINE config #4, "fused").
"""
from __future__ import annotations

from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

Word = Tuple[jnp.ndarray, jnp.ndarray]  # (hi, lo) uint32

_K = [
    0x428A2F98D728AE22, 0x7137449123EF65CD, 0xB5C0FBCFEC4D3B2F, 0xE9B5DBA58189DBBC,
    0x3956C25BF348B538, 0x59F111F1B605D019, 0x923F82A4AF194F9B, 0xAB1C5ED5DA6D8118,
    0xD807AA98A3030242, 0x12835B0145706FBE, 0x243185BE4EE4B28C, 0x550C7DC3D5FFB4E2,
    0x72BE5D74F27B896F, 0x80DEB1FE3B1696B1, 0x9BDC06A725C71235, 0xC19BF174CF692694,
    0xE49B69C19EF14AD2, 0xEFBE4786384F25E3, 0x0FC19DC68B8CD5B5, 0x240CA1CC77AC9C65,
    0x2DE92C6F592B0275, 0x4A7484AA6EA6E483, 0x5CB0A9DCBD41FBD4, 0x76F988DA831153B5,
    0x983E5152EE66DFAB, 0xA831C66D2DB43210, 0xB00327C898FB213F, 0xBF597FC7BEEF0EE4,
    0xC6E00BF33DA88FC2, 0xD5A79147930AA725, 0x06CA6351E003826F, 0x142929670A0E6E70,
    0x27B70A8546D22FFC, 0x2E1B21385C26C926, 0x4D2C6DFC5AC42AED, 0x53380D139D95B3DF,
    0x650A73548BAF63DE, 0x766A0ABB3C77B2A8, 0x81C2C92E47EDAEE6, 0x92722C851482353B,
    0xA2BFE8A14CF10364, 0xA81A664BBC423001, 0xC24B8B70D0F89791, 0xC76C51A30654BE30,
    0xD192E819D6EF5218, 0xD69906245565A910, 0xF40E35855771202A, 0x106AA07032BBD1B8,
    0x19A4C116B8D2D0C8, 0x1E376C085141AB53, 0x2748774CDF8EEB99, 0x34B0BCB5E19B48A8,
    0x391C0CB3C5C95A63, 0x4ED8AA4AE3418ACB, 0x5B9CCA4F7763E373, 0x682E6FF3D6B2B8A3,
    0x748F82EE5DEFB2FC, 0x78A5636F43172F60, 0x84C87814A1F0AB72, 0x8CC702081A6439EC,
    0x90BEFFFA23631E28, 0xA4506CEBDE82BDE9, 0xBEF9A3F7B2C67915, 0xC67178F2E372532B,
    0xCA273ECEEA26619C, 0xD186B8C721C0C207, 0xEADA7DD6CDE0EB1E, 0xF57D4F7FEE6ED178,
    0x06F067AA72176FBA, 0x0A637DC5A2C898A6, 0x113F9804BEF90DAE, 0x1B710B35131C471B,
    0x28DB77F523047D84, 0x32CAAB7B40C72493, 0x3C9EBE0A15C9BEBC, 0x431D67C49C100D4C,
    0x4CC5D4BECB3E42B6, 0x597F299CFC657E2A, 0x5FCB6FAB3AD6FAEC, 0x6C44198C4A475817,
]

_H0 = [
    0x6A09E667F3BCC908, 0xBB67AE8584CAA73B, 0x3C6EF372FE94F82B, 0xA54FF53A5F1D36F1,
    0x510E527FADE682D1, 0x9B05688C2B3E6C1F, 0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179,
]


def _const(x: int) -> Word:
    return (
        jnp.uint32((x >> 32) & 0xFFFFFFFF),
        jnp.uint32(x & 0xFFFFFFFF),
    )


def _add(a: Word, b: Word) -> Word:
    lo = a[1] + b[1]
    carry = (lo < a[1]).astype(jnp.uint32)
    hi = a[0] + b[0] + carry
    return hi, lo


def _add_many(*words: Word) -> Word:
    acc = words[0]
    for w in words[1:]:
        acc = _add(acc, w)
    return acc


def _xor(a: Word, b: Word) -> Word:
    return a[0] ^ b[0], a[1] ^ b[1]


def _and(a: Word, b: Word) -> Word:
    return a[0] & b[0], a[1] & b[1]


def _not(a: Word) -> Word:
    return ~a[0], ~a[1]


def _rotr(a: Word, n: int) -> Word:
    hi, lo = a
    if n == 0:
        return a
    if n < 32:
        return (
            (hi >> n) | (lo << (32 - n)),
            (lo >> n) | (hi << (32 - n)),
        )
    if n == 32:
        return lo, hi
    n -= 32
    return (
        (lo >> n) | (hi << (32 - n)),
        (hi >> n) | (lo << (32 - n)),
    )


def _shr(a: Word, n: int) -> Word:
    hi, lo = a
    if n < 32:
        return hi >> n, (lo >> n) | (hi << (32 - n))
    if n == 32:
        return jnp.zeros_like(hi), hi
    return jnp.zeros_like(hi), hi >> (n - 32)


def _big_sigma0(x: Word) -> Word:
    return _xor(_xor(_rotr(x, 28), _rotr(x, 34)), _rotr(x, 39))


def _big_sigma1(x: Word) -> Word:
    return _xor(_xor(_rotr(x, 14), _rotr(x, 18)), _rotr(x, 41))


def _small_sigma0(x: Word) -> Word:
    return _xor(_xor(_rotr(x, 1), _rotr(x, 8)), _shr(x, 7))


def _small_sigma1(x: Word) -> Word:
    return _xor(_xor(_rotr(x, 19), _rotr(x, 61)), _shr(x, 6))


def _ch(e: Word, f: Word, g: Word) -> Word:
    return _xor(_and(e, f), _and(_not(e), g))


def _maj(a: Word, b: Word, c: Word) -> Word:
    return _xor(_xor(_and(a, b), _and(a, c)), _and(b, c))


# Round constants as device arrays (hi, lo), shaped (80,).
_K_HI = jnp.asarray(np.array([(k >> 32) & 0xFFFFFFFF for k in _K], np.uint32))
_K_LO = jnp.asarray(np.array([k & 0xFFFFFFFF for k in _K], np.uint32))


def sha512_96(words: jnp.ndarray) -> jnp.ndarray:
    """SHA-512 of a 96-byte message given as (..., 24) big-endian uint32 words.

    Returns the (..., 16) uint32 digest words (big-endian pairs).  The padding
    for a 96-byte message (0x80 then zeros then bit length 768) is appended
    in-kernel, so callers pass exactly R||A||M.  Both the message schedule and
    the 80 compression rounds run under ``lax.scan`` so the compiled graph
    stays small (a naive unroll is ~12k ops and chokes XLA).
    """
    shape = words.shape[:-1]

    def lift(x: int) -> Word:
        hi, lo = _const(x)
        return jnp.broadcast_to(hi, shape), jnp.broadcast_to(lo, shape)

    # Initial 16-word window: 12 message words + fixed padding.
    pad = [lift(0x8000000000000000), lift(0), lift(0), lift(96 * 8)]
    window_hi = jnp.stack(
        [words[..., 2 * t] for t in range(12)] + [p[0] for p in pad], axis=0
    )  # (16, ...)
    window_lo = jnp.stack(
        [words[..., 2 * t + 1] for t in range(12)] + [p[1] for p in pad], axis=0
    )

    def schedule_step(carry, _):
        whi, wlo = carry  # (16, ...)
        s1 = _small_sigma1((whi[14], wlo[14]))
        s0 = _small_sigma0((whi[1], wlo[1]))
        new = _add_many(s1, (whi[9], wlo[9]), s0, (whi[0], wlo[0]))
        whi = jnp.concatenate([whi[1:], new[0][None]], axis=0)
        wlo = jnp.concatenate([wlo[1:], new[1][None]], axis=0)
        return (whi, wlo), (whi[15], wlo[15])

    # Emit all 80 schedule words: the first 16 are the initial window.
    (_, _), tail = jax.lax.scan(
        schedule_step, (window_hi, window_lo), None, length=64
    )
    w_hi = jnp.concatenate([window_hi, tail[0]], axis=0)  # (80, ...)
    w_lo = jnp.concatenate([window_lo, tail[1]], axis=0)

    def round_step(state, xs):
        a, b, c, d, e, f, g, h = [
            (state[2 * i], state[2 * i + 1]) for i in range(8)
        ]
        whi, wlo, khi, klo = xs
        t1 = _add_many(h, _big_sigma1(e), _ch(e, f, g), (khi, klo), (whi, wlo))
        t2 = _add(_big_sigma0(a), _maj(a, b, c))
        h, g, f = g, f, e
        e = _add(d, t1)
        d, c, b = c, b, a
        a = _add(t1, t2)
        return tuple(x for p in (a, b, c, d, e, f, g, h) for x in p), None

    init = tuple(x for h0 in _H0 for x in lift(h0))
    state, _ = jax.lax.scan(round_step, init, (w_hi, w_lo, _K_HI, _K_LO))

    out = []
    for i, h0 in enumerate(_H0):
        s = _add((state[2 * i], state[2 * i + 1]), lift(h0))
        out.extend(s)
    return jnp.stack(out, axis=-1)


def pack_messages(messages: "list[bytes]") -> np.ndarray:
    """(N, 24) big-endian uint32 words from 96-byte messages (host side)."""
    out = np.zeros((len(messages), 24), np.uint32)
    for i, m in enumerate(messages):
        assert len(m) == 96
        out[i] = np.frombuffer(m, dtype=">u4").astype(np.uint32)
    return out


def digest_bytes(digest_words: np.ndarray) -> "list[bytes]":
    """Inverse of the device output: (N, 16) words -> 64-byte digests."""
    arr = np.asarray(digest_words, dtype=np.uint32)
    out = []
    for row in arr:
        out.append(row.astype(">u4").tobytes())
    return out
