"""Scalar arithmetic mod the Ed25519 group order L — on device, in 13-bit limbs.

This closes the last host/device gap in the verification pipeline: the
challenge scalar k = SHA-512(R || A || M) mod L was previously computed per
item on the host (hashlib + python ints, ``crypto.rs:174-189`` territory).
With :mod:`mysticeti_tpu.ops.sha512` producing the 512-bit digest on device,
this module reduces it mod L and slices it into the 4-bit ladder windows that
:func:`mysticeti_tpu.ops.ed25519.verify_impl` consumes — so raw signature
bytes go in and verification bits come out with zero per-item host work.

Design notes (TPU-first, not a port of ref10's sc_reduce):

* Same 13-bit limb radix as :mod:`mysticeti_tpu.ops.field` — products of
  carried limbs fit int32 with headroom for 20-term diagonal sums.
* L = 2^252 + d with d ~ 2^124.6, so 2^260 = -256*d (mod L): the 512-bit
  digest folds down via three *signed* multiply-by-256d passes (magnitudes
  shrink 2^520 -> 2^394 -> 2^268 -> ~2^260), then a bias of 1024*L makes the
  value positive, a single Barrett-style quotient q = floor(x / 2^252) < 2^11
  removes the top bits (x == r - q*d mod L), and one conditional subtract of L
  canonicalizes.  All passes are static vector ops over the batch — no
  data-dependent control flow, vmap/jit-safe.
* Carry propagation is a handful of vectorized passes (see field.py's module
  docstring); full normalization uses a ``fori_loop`` of width+2 passes.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

RADIX = 13
MASK = (1 << RADIX) - 1

L = (1 << 252) + 27742317777372353535851937790883648493
_DELTA = L - (1 << 252)  # 125 bits
_D256 = _DELTA << 8  # 2^260 mod L == -_D256; 133 bits -> 11 limbs
P = (1 << 255) - 19


def _int_to_limbs_np(x: int, n: int) -> np.ndarray:
    out = np.zeros(n, dtype=np.int32)
    for i in range(n):
        out[i] = x & MASK
        x >>= RADIX
    assert x == 0, "constant exceeds limb capacity"
    return out


_D256_LIMBS = _int_to_limbs_np(_D256, 11)
_DELTA_LIMBS = _int_to_limbs_np(_DELTA, 10)
_L_LIMBS = jnp.asarray(_int_to_limbs_np(L, 20))
_L1024_LIMBS = jnp.asarray(_int_to_limbs_np(1024 * L, 21))
_P_LIMBS_20 = _int_to_limbs_np(P, 20)


def limbs_to_int(limbs) -> int:
    """Host-side debugging helper: limb vector -> python int."""
    return sum(int(v) << (RADIX * i) for i, v in enumerate(np.asarray(limbs).tolist()))


def _carry_once(x: jnp.ndarray) -> jnp.ndarray:
    """One signed carry pass.  The TOP limb is left raw (it carries the sign
    of the whole value); normalizing it would turn a -1 into 8191 and silently
    drop the borrow.  Callers size workspaces so the top limb stays small."""
    c = x >> RADIX
    c = c.at[..., -1].set(0)
    x = x - (c << RADIX)
    return x + jnp.concatenate([jnp.zeros_like(c[..., :1]), c[..., :-1]], axis=-1)


def _full_carry(x: jnp.ndarray) -> jnp.ndarray:
    """Worst-case borrow/carry ripple: width+2 passes under fori_loop."""
    n = x.shape[-1]
    return jax.lax.fori_loop(0, n + 2, lambda _, v: _carry_once(v), x)


def _pad_limbs(x: jnp.ndarray, width: int) -> jnp.ndarray:
    pad = width - x.shape[-1]
    if pad <= 0:
        return x[..., :width]
    return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])


def _mul_const(x: jnp.ndarray, const_limbs: np.ndarray) -> jnp.ndarray:
    """x (..., n) signed carried limbs times a small nonneg constant limb
    vector; returns (..., n + len(const)) UNCARRIED product (|sums| < 2^30)."""
    n = x.shape[-1]
    m = len(const_limbs)
    out_w = n + m
    acc = None
    for j in range(m):
        cj = int(const_limbs[j])
        if cj == 0:
            continue
        term = x * cj
        padded = jnp.pad(term, [(0, 0)] * (x.ndim - 1) + [(j, out_w - n - j)])
        acc = padded if acc is None else acc + padded
    if acc is None:
        acc = jnp.zeros((*x.shape[:-1], out_w), x.dtype)
    return acc


def _fold_once(x: jnp.ndarray, out_width: int) -> jnp.ndarray:
    """One signed fold: value(x) == lo + 2^260*hi -> lo - 256d*hi (mod L)."""
    lo = x[..., :20]
    hi = x[..., 20:]
    prod = _mul_const(hi, _D256_LIMBS)
    res = _pad_limbs(lo, out_width) - _pad_limbs(prod, out_width)
    return _carry_once(_carry_once(_carry_once(res)))


def mod_L(x: jnp.ndarray) -> jnp.ndarray:
    """Reduce a 512-bit value given as (..., 40) carried limbs mod L.

    Returns (..., 20) int32 canonical limbs of the value in [0, L).
    """
    # Signed folds: 40 -> 32 -> 24 -> 22 limbs; |value| ends < 2^261.  Each
    # output width leaves TWO limbs above the highest nonzero raw product
    # term: pass-1 carries (up to 2^17) land one limb up and pass-2 carries
    # one more — with both spares present nothing is ever dropped.
    x = _fold_once(x, 32)
    x = _fold_once(x, 24)
    x = _fold_once(x, 22)
    # Bias positive (+1024L ~ 2^262) and fully normalize to unique limbs.
    x = _pad_limbs(x, 22) + _pad_limbs(_L1024_LIMBS, 22)
    x = _full_carry(x)
    # Barrett step: x == q*2^252 + r, 2^252 == -d (mod L)  =>  x == r - q*d.
    q = (x[..., 19] >> 5) + (x[..., 20] << 8) + (x[..., 21] << 21)  # < 2^11
    r = x[..., :20].at[..., 19].set(x[..., 19] & 31)
    # q*d < 2^136 needs 11 limbs; pad before carrying so the carry out of
    # limb 9 (up to 2^11) is not dropped.
    qd = _pad_limbs(q[..., None] * jnp.asarray(_DELTA_LIMBS), 11)
    qd = _carry_once(_carry_once(qd))
    y = r + _L_LIMBS - _pad_limbs(qd, 20)  # in (0, 2L)
    y = _full_carry(y)
    # One conditional subtract of L finishes canonicalization.
    ge = geq_const(y, _int_to_limbs_np(L, 20))
    y = jnp.where(ge[..., None], y - _L_LIMBS, y)
    return _full_carry(y)


# ---------------------------------------------------------------------------
# Byte/word plumbing
# ---------------------------------------------------------------------------


def bswap32(x: jnp.ndarray) -> jnp.ndarray:
    """Byte-swap uint32 lanes (big-endian word <-> little-endian word)."""
    x = x.astype(jnp.uint32)
    return (
        ((x & 0xFF) << 24)
        | ((x & 0xFF00) << 8)
        | ((x >> 8) & 0xFF00)
        | (x >> 24)
    )


def digest_words_to_le(digest: jnp.ndarray) -> jnp.ndarray:
    """(..., 16) sha512_96 output ([hi0, lo0, ..]) -> (..., 16) uint32 words
    v_j of the digest interpreted as a little-endian integer (sum v_j 2^32j).

    The digest byte stream is the big-endian encoding of each 64-bit word in
    order; little-endian 32-bit value words are therefore just the byte-swap
    of the output words in place.
    """
    return bswap32(digest)


def words_to_limbs(words: jnp.ndarray, n_limbs: int) -> jnp.ndarray:
    """(..., W) uint32 little-endian value words -> (..., n_limbs) int32 limbs."""
    w = words.shape[-1]
    words = words.astype(jnp.uint32)
    out = []
    for m in range(n_limbs):
        bit = RADIX * m
        q, r = bit // 32, bit % 32
        if q >= w:
            out.append(jnp.zeros_like(words[..., 0]))
            continue
        v = words[..., q] >> r
        if r + RADIX > 32 and q + 1 < w:
            v = v | (words[..., q + 1] << (32 - r))
        out.append(v & MASK)
    return jnp.stack(out, axis=-1).astype(jnp.int32)


def windows4(limbs: jnp.ndarray, n_windows: int = 64) -> jnp.ndarray:
    """(..., 20) canonical limbs -> (..., n_windows) 4-bit windows, LSB first
    (the layout ``ed25519._double_scalar_mul`` consumes)."""
    out = []
    for wnd in range(n_windows):
        bit = 4 * wnd
        q, r = bit // RADIX, bit % RADIX
        v = limbs[..., q] >> r
        if r + 4 > RADIX and q + 1 < limbs.shape[-1]:
            v = v | (limbs[..., q + 1] << (RADIX - r))
        out.append(v & 15)
    return jnp.stack(out, axis=-1).astype(jnp.int32)


def geq_const(limbs: jnp.ndarray, const_limbs: np.ndarray) -> jnp.ndarray:
    """Lexicographic (value) compare of unique nonneg limb arrays against a
    constant: returns batch-shaped bool, True iff value(limbs) >= const."""
    n = limbs.shape[-1]
    ge = jnp.zeros(limbs.shape[:-1], bool)
    eq = jnp.ones(limbs.shape[:-1], bool)
    for i in reversed(range(n)):
        c = int(const_limbs[i]) if i < len(const_limbs) else 0
        ge = ge | (eq & (limbs[..., i] > c))
        eq = eq & (limbs[..., i] == c)
    return ge | eq


def lt_L(limbs: jnp.ndarray) -> jnp.ndarray:
    """value(limbs) < L (for s-canonicity: RFC 8032 / OpenSSL reject s >= L)."""
    return ~geq_const(limbs, _int_to_limbs_np(L, limbs.shape[-1]))


def lt_P(limbs: jnp.ndarray) -> jnp.ndarray:
    """value(limbs) < p (canonical field-element encoding check)."""
    return ~geq_const(limbs, _int_to_limbs_np(P, limbs.shape[-1]))
