"""GF(2^255-19) arithmetic in 20 x 13-bit int32 limbs — TPU-native design.

Why this representation (not a port of any CPU bignum):

* TPUs have no 64-bit integer multiplier; the VPU does 32-bit integer ops.
  With 13-bit limbs, a product is ≤ 2^26 and a full 20x20 schoolbook
  anti-diagonal sum is ≤ 20·2^26 < 2^31 — every intermediate of the multiply
  fits int32 with no in-loop carry handling.
* 20 limbs x 13 bits = 260 bits; 2^260 ≡ 19·2^5 = 608 and 2^256 ≡ 38 (mod p),
  so overflow limbs fold back with small constant multipliers.
* Carry propagation is a handful of *vectorized* passes (carry magnitudes decay
  geometrically), never a serial 255-step chain — XLA keeps the whole pipeline
  lane-parallel, and the batch dimension vmaps across VPU lanes.

Representation invariant ("partial" form) maintained by every public op:
  limbs[0..18] ∈ [0, 2^13],  limbs[19] ∈ [0, 2^9]   (value < 2^256, may be ≥ p)
The canonical representative in [0, p) is only produced by :func:`canonical`
(encode/compare time).  All functions operate on ``(..., 20)`` int32 arrays and
are ``vmap``/``jit``-safe.

This replaces the dalek field arithmetic behind the reference's verify hot path
(``mysticeti-core/src/crypto.rs:174-189``); parity is enforced against python-int
math and the ``cryptography`` Ed25519 oracle in ``tests/test_ed25519_tpu.py``.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

RADIX = 13
NLIMBS = 20
MASK = (1 << RADIX) - 1  # 8191
P = (1 << 255) - 19
FOLD_260 = 19 << 5  # 2^260 mod p = 608: limb 20+j folds to limb j
FOLD_256 = 38  # 2^256 mod p: top-limb bits ≥ 9 fold to limb 0

# Anti-diagonal scatter map for the schoolbook product, built once.
_I, _J = np.meshgrid(np.arange(NLIMBS), np.arange(NLIMBS), indexing="ij")
_DIAG = jnp.asarray((_I + _J).reshape(-1), dtype=jnp.int32)

_WORK = 2 * NLIMBS + 2  # product workspace: 39 live limbs + carry headroom

# 8p = 2^258 - 152 as a limb vector with every limb large enough to bias a
# partial-form subtrahend: [2^13-152, 2^13-1 x18, 2^11-1].
_BIAS_8P = jnp.asarray(
    np.array([(1 << RADIX) - 152] + [MASK] * 18 + [(1 << 11) - 1], dtype=np.int32)
)


def int_to_limbs(x: int) -> np.ndarray:
    """Host-side: python int (< 2^260) -> limb vector."""
    out = np.zeros(NLIMBS, dtype=np.int32)
    for i in range(NLIMBS):
        out[i] = x & MASK
        x >>= RADIX
    assert x == 0, "value exceeds 260 bits"
    return out


def limbs_to_int(limbs) -> int:
    """Host-side: limb vector -> python int (no reduction)."""
    return sum(int(l) << (RADIX * i) for i, l in enumerate(np.asarray(limbs).tolist()))


def constant(x: int) -> jnp.ndarray:
    return jnp.asarray(int_to_limbs(x % P), dtype=jnp.int32)


def _carry_once(x: jnp.ndarray) -> jnp.ndarray:
    """One vectorized signed carry pass; remainders land in [0, 2^13).
    The carry out of the top limb is DROPPED — callers guarantee it is zero."""
    c = x >> RADIX  # floor division: correct for negative limbs too
    x = x - (c << RADIX)
    return x + jnp.concatenate([jnp.zeros_like(c[..., :1]), c[..., :-1]], axis=-1)


def _normalize_top(x: jnp.ndarray) -> jnp.ndarray:
    """Restore the tight invariant: fold top-limb bits ≥ 9 (value bits ≥ 256)
    into limb 0 with factor 38, then one carry pass.  Requires value < 2^269."""
    c = x[..., NLIMBS - 1] >> 9
    x = x.at[..., NLIMBS - 1].add(-(c << 9))
    x = x.at[..., 0].add(FOLD_256 * c)
    return _carry_once(x)


def _fold_reduce(wide: jnp.ndarray) -> jnp.ndarray:
    """Reduce a ``_WORK``-limb non-negative value (limbs < 2^31) to partial form."""
    # Three passes bring every limb below 2^13(+1); carries decay 2^18 -> 2^5 -> 1.
    x = _carry_once(_carry_once(_carry_once(wide)))
    lo = x[..., :NLIMBS]
    hi = x[..., NLIMBS : 2 * NLIMBS]
    top = x[..., 2 * NLIMBS :]  # limbs 40,41 (tiny): fold twice => factor 608^2
    lo = lo + FOLD_260 * hi
    lo = lo.at[..., :2].add(FOLD_260 * FOLD_260 * top)
    # lo limbs ≤ 2^13 + 608·2^13 + 608^2·2^5 < 2^24: carry in a 21-limb
    # workspace so the overflow out of limb 19 is captured, then folded (608).
    lo = jnp.concatenate([lo, jnp.zeros_like(lo[..., :1])], axis=-1)
    lo = _carry_once(_carry_once(lo))
    lo = lo[..., :NLIMBS].at[..., 0].add(FOLD_260 * lo[..., NLIMBS])
    # Limb 19 can still hold exactly 2^13 here (carry ripple landed on a full
    # limb); fold its bits ≥ 13 explicitly — _carry_once would DROP them.
    c = lo[..., NLIMBS - 1] >> RADIX
    lo = lo.at[..., NLIMBS - 1].add(-(c << RADIX))
    lo = lo.at[..., 0].add(FOLD_260 * c)
    lo = _carry_once(lo)
    return _normalize_top(lo)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Field multiply: schoolbook product via STATIC shifted adds.

    The anti-diagonal accumulation is expressed as 20 statically-padded
    vector adds (one per limb of ``a``) rather than a scatter — XLA lowers
    scatters with duplicate indices to a serialized loop on TPU, while pads
    and adds stay fully lane-parallel on the VPU.
    """
    parts = []
    for i in range(NLIMBS):
        term = a[..., i : i + 1] * b  # (..., 20), each ≤ 2^26
        parts.append(
            jnp.pad(term, [(0, 0)] * (term.ndim - 1) + [(i, _WORK - NLIMBS - i)])
        )
    wide = parts[0]
    for p in parts[1:]:
        wide = wide + p
    return _fold_reduce(wide)


def square(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a + b in partial form (sum < 2^257: carries stay in range)."""
    return _normalize_top(_carry_once(a + b))


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b (mod p): bias by 8p so the total is positive; signed carries fix the
    few slightly-negative low limbs."""
    x = a + _BIAS_8P - b
    x = _carry_once(_carry_once(x))
    return _normalize_top(x)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return sub(jnp.zeros_like(a), a)


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small non-negative constant (k < 2^17)."""
    wide = jnp.zeros((*a.shape[:-1], _WORK), dtype=jnp.int32)
    wide = wide.at[..., :NLIMBS].set(a * k)
    return _fold_reduce(wide)


def pow2k(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """a^(2^k): k repeated squarings (fori_loop keeps the graph small)."""
    return jax.lax.fori_loop(0, k, lambda _, x: square(x), a)


def _ladder(z: jnp.ndarray):
    """Shared prefix of the inversion / sqrt addition chains: returns
    (z11, z^(2^50-1), z^(2^250-1))."""
    z2 = square(z)
    z9 = mul(square(square(z2)), z)
    z11 = mul(z9, z2)
    z2_5_0 = mul(square(z11), z9)  # 2^5 - 1
    z2_10_0 = mul(pow2k(z2_5_0, 5), z2_5_0)
    z2_20_0 = mul(pow2k(z2_10_0, 10), z2_10_0)
    z2_40_0 = mul(pow2k(z2_20_0, 20), z2_20_0)
    z2_50_0 = mul(pow2k(z2_40_0, 10), z2_10_0)
    z2_100_0 = mul(pow2k(z2_50_0, 50), z2_50_0)
    z2_200_0 = mul(pow2k(z2_100_0, 100), z2_100_0)
    z2_250_0 = mul(pow2k(z2_200_0, 50), z2_50_0)
    return z11, z2_50_0, z2_250_0


def invert(z: jnp.ndarray) -> jnp.ndarray:
    """z^(p-2) = z^(2^255-21) (classic curve25519 chain; 254 squarings)."""
    z11, _, z2_250_0 = _ladder(z)
    return mul(pow2k(z2_250_0, 5), z11)


def pow22523(z: jnp.ndarray) -> jnp.ndarray:
    """z^((p-5)/8) = z^(2^252-3) — the decompression square-root exponent."""
    _, _, z2_250_0 = _ladder(z)
    return mul(pow2k(z2_250_0, 2), z)


# p in limb form, for the final conditional subtract of canonical().
_P_LIMBS = jnp.asarray(
    np.array([(1 << RADIX) - 19] + [MASK] * 18 + [255], dtype=np.int32)
)


def _full_carry(x: jnp.ndarray) -> jnp.ndarray:
    """Enough carry passes for a worst-case full ripple (e.g. p -> 2^255 form)."""
    return jax.lax.fori_loop(0, NLIMBS + 1, lambda _, v: _carry_once(v), x)


def canonical(x: jnp.ndarray) -> jnp.ndarray:
    """Fully reduce partial limbs to the canonical representative in [0, p)."""
    # Fold bits ≥ 255 (factor 19), fully normalize, twice: value -> [0, 2^255).
    for _ in range(2):
        c = x[..., NLIMBS - 1] >> 8
        x = x.at[..., NLIMBS - 1].add(-(c << 8))
        x = x.at[..., 0].add(19 * c)
        x = _full_carry(x)
    # x is now the unique normalized form of a value < 2^255; subtract p iff ≥ p
    # (exact limb comparison — all mid limbs saturated and low limb ≥ p's).
    ge_p = (
        (x[..., NLIMBS - 1] == 255)
        & jnp.all(x[..., 1 : NLIMBS - 1] == MASK, axis=-1)
        & (x[..., 0] >= (1 << RADIX) - 19)
    )
    return jnp.where(ge_p[..., None], x - _P_LIMBS, x)


def eq_canonical(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Equality of field elements given in partial form (bool, batch-shaped)."""
    return jnp.all(canonical(a) == canonical(b), axis=-1)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canonical(a) == 0, axis=-1)


def parity(a: jnp.ndarray) -> jnp.ndarray:
    """Least significant bit of the canonical representative (the sign bit)."""
    return canonical(a)[..., 0] & 1
