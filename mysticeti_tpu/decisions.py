"""Commit-rule decision ledger: why every leader slot committed or skipped.

The universal committer used to collapse every decision into a bare
``commit|skip`` counter label — the Byzantine scenarios (PR 12) and the
chaos-debugging workflow could see *that* a leader was skipped but never
*which* blames, certificates, or anchors decided it.  This module is the
"why" plane over the protocol's actual logic:

* :class:`DecisionTrace` — a per-slot collector the committer threads
  through :class:`~mysticeti_tpu.consensus.base_committer.BaseCommitter`'s
  rule predicates: certificate and blame stake tallies with the
  contributing authorities, and the anchor used by an indirect decision.
  The predicates keep their early-return-on-quorum semantics, so the
  recorded contributors are exactly the deterministic prefix that reached
  the threshold.
* :class:`DecisionLedger` — a bounded, lock-disciplined ring of
  :class:`DecisionRecord` dicts, one per DECIDED leader slot (the committer
  only emits the longest decided prefix and the core advances its cursor
  past it, so every slot is recorded exactly once).  Undecided slots are
  tracked as a frontier snapshot per scan; a slot that was undecided on a
  previous scan and decides later is recorded as *flipped* and lands in the
  flight recorder (``decision-flip``), as does every skip
  (``decision-skip``).
* Canonical serialization (:meth:`DecisionLedger.ledger_bytes`) — sorted
  keys, no whitespace, runtime-clocked timestamps — so a seeded sim
  produces a byte-identical ledger every run (pinned by
  tests/test_decisions.py).
* :func:`explain_record` — the human-readable causal explanation
  ``tools/commit_explain.py`` renders for any (authority, round) slot.

Metrics: ``mysticeti_commit_decision_total{rule,outcome}`` (the migrated
``universal_committer.py`` skip/commit counter, now distinguishing
direct from indirect) and ``mysticeti_decision_rounds_behind`` (how far
behind the DAG frontier each slot was when it decided).
"""
from __future__ import annotations

import hashlib
import json
import threading
from collections import deque
from typing import Deque, Iterable, List, Optional, Set, Tuple

from .consensus import AuthorityRound, LeaderStatus
from .runtime import now as runtime_now

# Ring capacity: one record per decided leader slot; a busy fleet decides a
# few slots per second, so 4096 holds many minutes of decision history.
DEFAULT_CAPACITY = 4096


def _canonical(obj) -> bytes:
    return json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()


class DecisionTrace:
    """Mutable per-slot evidence collector threaded through the rules.

    The committer creates one per evaluated slot; the base committer's
    predicates fill it in as a side channel without changing any decision.
    ``note_certificates`` keeps the highest-stake tally seen — an
    equivocating leader has several candidate blocks and only the (at most
    one) certified tally should explain the slot.
    """

    __slots__ = (
        "blame_stake",
        "blame_authorities",
        "cert_stake",
        "cert_authorities",
        "anchor",
    )

    def __init__(self) -> None:
        self.blame_stake = 0
        self.blame_authorities: List[int] = []
        self.cert_stake = 0
        self.cert_authorities: List[int] = []
        self.anchor: Optional[str] = None

    def note_blames(self, aggregator) -> None:
        self.blame_stake = int(aggregator.stake)
        self.blame_authorities = sorted(int(a) for a in aggregator.voters())

    def note_certificates(self, aggregator) -> None:
        if int(aggregator.stake) >= self.cert_stake:
            self.cert_stake = int(aggregator.stake)
            self.cert_authorities = sorted(
                int(a) for a in aggregator.voters()
            )

    def note_anchor(self, anchor_slot: AuthorityRound) -> None:
        self.anchor = repr(anchor_slot)


def make_record(
    status: LeaderStatus,
    rule: str,
    trace: Optional[DecisionTrace],
    rounds_behind: int,
    t: float,
) -> dict:
    """One canonical ledger entry for a decided (or frontier) slot."""
    ar = status.authority_round
    record = {
        "authority": int(ar.authority),
        "round": int(ar.round),
        "slot": repr(ar),
        "rule": rule,
        "outcome": status.kind,
        "cert_stake": trace.cert_stake if trace else 0,
        "cert_authorities": list(trace.cert_authorities) if trace else [],
        "blame_stake": trace.blame_stake if trace else 0,
        "blame_authorities": list(trace.blame_authorities) if trace else [],
        "anchor": trace.anchor if trace else None,
        "rounds_behind": int(rounds_behind),
        "t": round(t, 6),
    }
    block = status.committed_block()
    if block is not None:
        ref = block.reference
        record["block"] = (
            f"A{ref.authority}R{ref.round}#{ref.digest[:4].hex()}"
        )
    else:
        record["block"] = None
    return record


class DecisionLedger:
    """Bounded ring of decision records for one node's committer."""

    def __init__(
        self,
        metrics=None,
        capacity: int = DEFAULT_CAPACITY,
        clock=runtime_now,
    ) -> None:
        self.metrics = metrics
        self.clock = clock
        self.capacity = max(1, capacity)
        # Flight recorder (flight_recorder.py), wired post-construction by
        # the node assembly exactly like block_store.recorder.
        self.recorder = None
        self._decision_lock = threading.Lock()
        # Guarded by _decision_lock (lint GUARDED_FIELDS): the loop thread
        # records during try_commit while the metrics endpoint serves
        # /debug/consensus and tools snapshot the canonical ledger.
        self._decision_ring: Deque[dict] = deque(maxlen=self.capacity)
        self._undecided_keys: Set[Tuple[int, int]] = set()
        self._undecided_slots: Tuple[str, ...] = ()
        self.recorded = 0
        self.dropped = 0

    # -- recording (loop thread, once per decided slot) --

    def record_decision(
        self,
        status: LeaderStatus,
        rule: str,
        trace: Optional[DecisionTrace],
        rounds_behind: int,
    ) -> dict:
        record = make_record(status, rule, trace, rounds_behind, self.clock())
        with self._decision_lock:
            key = (record["authority"], record["round"])
            flipped = key in self._undecided_keys
            if flipped:
                self._undecided_keys.discard(key)
            record["flipped"] = flipped
            if len(self._decision_ring) == self._decision_ring.maxlen:
                self.dropped += 1
            self._decision_ring.append(record)
            self.recorded += 1
        if self.metrics is not None:
            self.metrics.mysticeti_commit_decision_total.labels(
                rule, record["outcome"]
            ).inc()
            self.metrics.mysticeti_decision_rounds_behind.observe(
                float(rounds_behind)
            )
        recorder = self.recorder
        if recorder is not None:
            if record["outcome"] == LeaderStatus.SKIP:
                recorder.record(
                    "decision-skip",
                    slot=record["slot"],
                    rule=rule,
                    blame_stake=record["blame_stake"],
                    cert_stake=record["cert_stake"],
                    anchor=record["anchor"],
                    flipped=flipped or None,
                )
            elif flipped:
                recorder.record(
                    "decision-flip",
                    slot=record["slot"],
                    rule=rule,
                    outcome=record["outcome"],
                    rounds_behind=record["rounds_behind"],
                )
        return record

    def note_undecided(self, slots: Iterable[AuthorityRound]) -> None:
        """Note the undecided frontier after one try_commit scan (slots
        above the decided prefix that no rule could decide).

        Keys accumulate (union) so a slot that goes undecided → decided
        but unemitted → emitted across several scans still flags as
        flipped; a key is retired only when its slot is recorded.
        """
        slots = list(slots)
        with self._decision_lock:
            self._undecided_keys.update(
                (int(ar.authority), int(ar.round)) for ar in slots
            )
            self._undecided_slots = tuple(repr(ar) for ar in slots)

    # -- views --

    def records(self, last: Optional[int] = None) -> List[dict]:
        with self._decision_lock:
            records = list(self._decision_ring)
        return records[-last:] if last else records

    def lookup(self, authority: int, round_: int) -> Optional[dict]:
        """The newest record for one (authority, round) slot, or None."""
        with self._decision_lock:
            for record in reversed(self._decision_ring):
                if (
                    record["authority"] == authority
                    and record["round"] == round_
                ):
                    return dict(record)
        return None

    def undecided(self) -> List[str]:
        with self._decision_lock:
            return list(self._undecided_slots)

    def ledger_bytes(self) -> bytes:
        """Canonical serialization — byte-identical across same-seed sims."""
        with self._decision_lock:
            return _canonical(list(self._decision_ring))

    def digest(self) -> str:
        return hashlib.sha256(self.ledger_bytes()).hexdigest()

    def state(self) -> dict:
        with self._decision_lock:
            return {
                "recorded": self.recorded,
                "dropped": self.dropped,
                "undecided": list(self._undecided_slots),
            }


def explain_record(record: dict) -> str:
    """Human-readable causal explanation of one decision record (the
    ``tools/commit_explain.py`` renderer; deterministic for pinning)."""
    lines = [
        f"slot {record['slot']} (authority {record['authority']}, "
        f"round {record['round']}): "
        f"{record['outcome'].upper()} via the {record['rule']} rule"
    ]
    outcome = record["outcome"]
    rule = record["rule"]
    if outcome == "commit":
        voters = ",".join(str(a) for a in record["cert_authorities"])
        lines.append(
            f"  certificates: {record['cert_stake']} stake from "
            f"authorities [{voters}] certified the leader block "
            f"{record['block']}"
        )
        if rule == "indirect" and record.get("anchor"):
            lines.append(
                f"  anchor: committed leader {record['anchor']} carries a "
                "certified link to this slot"
            )
    elif outcome == "skip":
        if rule == "direct":
            blamers = ",".join(str(a) for a in record["blame_authorities"])
            lines.append(
                f"  blames: {record['blame_stake']} stake from authorities "
                f"[{blamers}] proposed in the voting round without linking "
                "this leader"
            )
        else:
            lines.append(
                f"  anchor: committed leader {record['anchor']} has no "
                "certified link to any block of this slot "
                f"(best certificate tally: {record['cert_stake']} stake)"
            )
    else:
        lines.append(
            "  undecided: neither 2f+1 blames nor 2f+1 certificates, and "
            "no committed anchor one wave ahead"
        )
    lines.append(
        f"  decided {record['rounds_behind']} rounds behind the DAG "
        f"frontier at t={record['t']:.6f}"
        + (" (flipped from undecided)" if record.get("flipped") else "")
    )
    return "\n".join(lines)
