"""Device mesh + shard_map wrapper for the Ed25519 batch verifier.

Design: the verify kernel is embarrassingly parallel over the batch, so the
mesh is one axis (``batch``) and every input is sharded along it; XLA runs one
shard per chip over ICI with no inter-chip traffic except the final ``psum``
that reduces the per-shard valid counts (the quantity the consensus vote
aggregator actually needs globally).

Tested on a virtual 8-device CPU mesh (``--xla_force_host_platform_device_count``)
— the same mesh/collective compilation path XLA uses on a real slice.
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PSpec

from ..ops import ed25519 as E


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` local devices (axis: ``batch``)."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), axis_names=("batch",))


def sharded_verify_kernel(mesh: Mesh):
    """Returns a jitted fn(packed arrays) -> (per-item bool, global valid count).

    All inputs are sharded on the leading batch axis; the valid-count reduction
    is an ICI ``psum``.  Batch size must be a multiple of the mesh size.
    """
    spec = PSpec("batch")

    def _shard_body(a_y, a_sign, r_y, r_sign, s_bits, k_bits, host_ok):
        ok = E.verify_impl(a_y, a_sign, r_y, r_sign, s_bits, k_bits, host_ok)
        total = jax.lax.psum(jnp.sum(ok.astype(jnp.int32)), "batch")
        return ok, total

    sharded = shard_map(
        _shard_body,
        mesh=mesh,
        in_specs=(spec,) * 7,
        out_specs=(spec, PSpec()),
        check_rep=False,
    )
    return jax.jit(sharded)


def sharded_verify_batch(
    mesh: Mesh,
    public_keys: Sequence[bytes],
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
) -> Tuple[np.ndarray, int]:
    """Host convenience: pack, pad to the mesh-aligned bucket, dispatch sharded."""
    n = len(signatures)
    n_dev = mesh.devices.size
    kernel = sharded_verify_kernel(mesh)
    packed = E.pack_batch(public_keys, messages, signatures)
    per_shard = max(1, -(-n // n_dev))
    padded = per_shard * n_dev
    arrs = []
    for x in packed:
        pad = padded - n
        widths = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
        arrs.append(jnp.asarray(np.pad(x, widths)))
    ok, total = kernel(*arrs)
    return np.asarray(ok)[:n], int(total)
