"""Device mesh + shard_map wrapper for the Ed25519 batch verifier.

Design: the verify kernel is embarrassingly parallel over the batch, so the
mesh is one axis (``batch``) and every input is sharded along it; XLA runs one
shard per chip over ICI with no inter-chip traffic except the final ``psum``
that reduces the per-shard valid counts (the quantity the consensus vote
aggregator actually needs globally).

Tested on a virtual 8-device CPU mesh (``--xla_force_host_platform_device_count``)
— the same mesh/collective compilation path XLA uses on a real slice.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as PSpec

try:
    from jax import shard_map as _shard_map  # jax >= 0.8

    _REP_KW = "check_vma"
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

    _REP_KW = "check_rep"


def shard_map(f, **kwargs):
    # The replication-check kwarg was renamed check_rep -> check_vma; we
    # disable it either way (the psum'd total is intentionally replicated).
    kwargs[_REP_KW] = kwargs.pop("check_rep")
    return _shard_map(f, **kwargs)

from ..ops import ed25519 as E


def make_mesh(
    n_devices: Optional[int] = None, devices: Optional[Sequence] = None
) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` local devices (axis: ``batch``).

    ``devices`` overrides the default-backend device list — e.g.
    ``jax.devices("cpu")`` to build a virtual host mesh in a process whose
    default backend is already pinned to the TPU.
    """
    devices = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), axis_names=("batch",))


def sharded_verify_kernel(mesh: Mesh):
    """Returns a jitted fn(packed arrays) -> (per-item bool, global valid count).

    All inputs are sharded on the leading batch axis; the valid-count reduction
    is an ICI ``psum``.  Batch size must be a multiple of the mesh size.
    """
    spec = PSpec("batch")

    def _shard_body(a_y, a_sign, r_y, r_sign, s_bits, k_bits, host_ok):
        ok = E.verify_impl(a_y, a_sign, r_y, r_sign, s_bits, k_bits, host_ok)
        total = jax.lax.psum(jnp.sum(ok.astype(jnp.int32)), "batch")
        return ok, total

    sharded = shard_map(
        _shard_body,
        mesh=mesh,
        in_specs=(spec,) * 7,
        out_specs=(spec, PSpec()),
        check_rep=False,
    )
    return jax.jit(sharded)


def sharded_verify_batch(
    mesh: Mesh,
    public_keys: Sequence[bytes],
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
) -> Tuple[np.ndarray, int]:
    """Host convenience: pack, pad to the mesh-aligned bucket, dispatch sharded."""
    n = len(signatures)
    n_dev = mesh.devices.size
    kernel = sharded_verify_kernel(mesh)
    packed = E.pack_batch(public_keys, messages, signatures)
    per_shard = max(1, -(-n // n_dev))
    padded = per_shard * n_dev
    arrs = []
    for x in packed:
        pad = padded - n
        widths = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
        arrs.append(jnp.asarray(np.pad(x, widths)))
    ok, total = kernel(*arrs)
    return np.asarray(ok)[:n], int(total)


# One compiled kernel per (mesh, flavor) — rebuilding the shard_map wrapper on
# every dispatch would recompile each time.
_KERNEL_CACHE: dict = {}


def _cached_fused_kernel(mesh: Mesh):
    backend = E._backend()
    key = ("fused", mesh, backend)
    if key not in _KERNEL_CACHE:
        spec = PSpec("batch")

        def _shard_body(msg_words, s_words, host_ok):
            if backend == "pallas":
                # Same Pallas ladder as the single-chip path, one grid per
                # shard; the tile shrinks if a shard is narrower than 256.
                from ..ops import ed25519_pallas as PK

                per_shard = msg_words.shape[0]
                args = E.prepare_fused(msg_words, s_words, host_ok)
                ok = PK._verify_pallas_jit(
                    *args,
                    tile=min(PK.default_tile(), per_shard),
                    interpret=False,
                )
            else:
                ok = E.verify_fused_impl(msg_words, s_words, host_ok)
            total = jax.lax.psum(jnp.sum(ok.astype(jnp.int32)), "batch")
            return ok, total

        _KERNEL_CACHE[key] = jax.jit(
            shard_map(
                _shard_body,
                mesh=mesh,
                in_specs=(spec,) * 3,
                out_specs=(spec, PSpec()),
                check_rep=False,
            )
        )
    return _KERNEL_CACHE[key]


def _cached_indexed_kernel(mesh: Mesh):
    """Indexed flavor: the (K, 8) key table is replicated to every device
    (a committee table is a few KB), the blob shards on the batch axis."""
    backend = E._backend()
    key = ("indexed", mesh, backend)
    if key not in _KERNEL_CACHE:
        spec = PSpec("batch")

        def _shard_body(blob, table):
            msg_words, s_words, host_ok = E.indexed_to_msg_words(blob, table)
            if backend == "pallas":
                from ..ops import ed25519_pallas as PK

                per_shard = blob.shape[0]
                args = E.prepare_fused(msg_words, s_words, host_ok)
                ok = PK._verify_pallas_jit(
                    *args,
                    tile=min(PK.default_tile(), per_shard),
                    interpret=False,
                )
            else:
                ok = E.verify_fused_impl(msg_words, s_words, host_ok)
            total = jax.lax.psum(jnp.sum(ok.astype(jnp.int32)), "batch")
            return ok, total

        _KERNEL_CACHE[key] = jax.jit(
            shard_map(
                _shard_body,
                mesh=mesh,
                in_specs=(spec, PSpec()),
                out_specs=(spec, PSpec()),
                check_rep=False,
            )
        )
    return _KERNEL_CACHE[key]


def dispatch_sharded_indexed(
    mesh: Mesh,
    table: "E.KeyTable",
    public_keys: Sequence[bytes],
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
) -> "E.VerifyDispatch":
    """Non-blocking sharded committee-indexed dispatch: pack on the host,
    submit every bucket chunk through the mesh kernel, return a handle that
    fetches on demand (the staged pipeline's device stage)."""
    n = len(signatures)
    if n == 0:
        return E.VerifyDispatch([])
    idx = table.indices_for(public_keys)
    known = idx >= 0
    kernel = _cached_indexed_kernel(mesh)
    blob = E.pack_blob_indexed(idx, messages, signatures, num_keys=len(table))
    # The psum'd per-chunk total is compiled and executed (the ICI collective
    # is part of the sharded program) but not fetched: padded lanes are
    # host_ok=False, so the global count equals the host-side sum of the
    # combined single fetch — one round-trip instead of 2 per chunk.
    handles = [
        (
            count,
            kernel(
                jnp.asarray(E._pad_to(blob[start : start + count], b)),
                table.words,
            )[0],
        )
        for start, count, b in E.iter_buckets(n)
    ]
    patches = []
    if not known.all():
        stragglers = np.flatnonzero(~known)
        patches.append(
            (
                stragglers,
                dispatch_sharded_fused(
                    mesh,
                    [public_keys[i] for i in stragglers],
                    [messages[i] for i in stragglers],
                    [signatures[i] for i in stragglers],
                ),
            )
        )
    return E.VerifyDispatch(handles, patches)


def sharded_verify_batch_indexed(
    mesh: Mesh,
    table: "E.KeyTable",
    public_keys: Sequence[bytes],
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
) -> Tuple[np.ndarray, int]:
    """Committee-indexed fused verification sharded over the mesh: minimum
    wire format (26 words/sig) AND batch-axis parallelism.  Unknown-key items
    route through the generic sharded path so results never depend on table
    contents."""
    out = dispatch_sharded_indexed(
        mesh, table, public_keys, messages, signatures
    ).result()
    return out, int(out.sum())


def dispatch_sharded_fused(
    mesh: Mesh,
    public_keys: Sequence[bytes],
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
) -> "E.VerifyDispatch":
    """Non-blocking sharded fused dispatch (raw-bytes wire format)."""
    n = len(signatures)
    if n == 0:
        return E.VerifyDispatch([])
    kernel = _cached_fused_kernel(mesh)
    msg_words, s_words, host_ok = E.pack_bytes(public_keys, messages, signatures)
    # Dispatch every chunk asynchronously, force once at the end — same
    # overlap policy as ops.ed25519.dispatch_blob_chunks.  The psum total is
    # compiled (the ICI collective stays in the program) but recomputed from
    # the combined fetch: padded lanes are host_ok=False, so the sums agree.
    handles = [
        (
            count,
            kernel(
                jnp.asarray(E._pad_to(msg_words[start : start + count], b)),
                jnp.asarray(E._pad_to(s_words[start : start + count], b)),
                jnp.asarray(E._pad_to(host_ok[start : start + count], b)),
            )[0],
        )
        for start, count, b in E.iter_buckets(n)
    ]
    return E.VerifyDispatch(handles)


def sharded_verify_batch_fused(
    mesh: Mesh,
    public_keys: Sequence[bytes],
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
) -> Tuple[np.ndarray, int]:
    """Fused raw-bytes verification sharded over the mesh batch axis.

    Uses the fixed bucket shapes of :mod:`..ops.ed25519` (all divisible by
    any power-of-two mesh up to 256 devices) so XLA compiles at most
    len(BUCKETS) shard programs per mesh.  Returns (per-item bool, global
    valid count via ICI psum).
    """
    out = dispatch_sharded_fused(
        mesh, public_keys, messages, signatures
    ).result()
    return out, int(out.sum())
