"""Multi-chip execution: mesh construction + sharded batch verification.

The reference scales with a NCCL-free TCP mesh between validators
(``network.rs``) and has no intra-validator accelerator parallelism.  Here the
TPU-native story (SURVEY §2.5): consensus traffic stays on the host NIC
(trust-domain boundary), while *inside* one validator the verification batch is
sharded across the chips of a pod slice with ``shard_map`` — pure data
parallelism over the batch axis, plus an ICI ``psum`` for the aggregate
valid-count that the vote tally consumes.
"""
from .mesh import (
    make_mesh,
    sharded_verify_kernel,
    sharded_verify_batch,
    sharded_verify_batch_fused,
)

__all__ = [
    "make_mesh",
    "sharded_verify_kernel",
    "sharded_verify_batch",
    "sharded_verify_batch_fused",
]
