"""Digests, keys and signatures for the consensus engine (CPU reference path).

Mirrors the capability surface of ``mysticeti-core/src/crypto.rs``:

* 32-byte Blake2b-256 block digests (``crypto.rs:21-22,33-61``).
* Ed25519 signing/verification keyed per authority (``crypto.rs:24-31,174-223``).
* The signature/digest layering subtlety (``crypto.rs:77-84``): the *signature* covers
  the digest computed **without** the signature field, while the *block digest* covers
  everything **including** the signature.  This lets descendants of a certified block
  skip signature verification during sync — the TPU batch verifier exploits the same
  property to drop already-covered items from a batch.

The CPU path here uses ``hashlib.blake2b`` and the ``cryptography`` library's Ed25519
(the correctness oracle) when that package is installed; otherwise the pure-Python
RFC 8032 implementation in :mod:`mysticeti_tpu._ed25519_py` fills in with the same
class surface and the same strict accept/reject semantics.  The TPU path lives in
``mysticeti_tpu.ops`` and is checked against this module bit-for-bit (accept/reject
parity) by the test suite.
"""
from __future__ import annotations

import hashlib
from typing import Optional

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )

    HAVE_CRYPTOGRAPHY = True
except ModuleNotFoundError:  # optional fast path absent: pure-Python oracle
    from ._ed25519_py import (  # type: ignore[assignment]
        Ed25519PrivateKey,
        Ed25519PublicKey,
        InvalidSignature,
    )

    HAVE_CRYPTOGRAPHY = False

DIGEST_SIZE = 32
SIGNATURE_SIZE = 64
PUBLIC_KEY_SIZE = 32

BLOCK_DIGEST_NONE = b"\x00" * DIGEST_SIZE
SIGNATURE_NONE = b"\x00" * SIGNATURE_SIZE


def blake2b_256(data: bytes) -> bytes:
    """32-byte Blake2b digest — the reference's BlockDigest hash (crypto.rs:33-61)."""
    return hashlib.blake2b(data, digest_size=DIGEST_SIZE).digest()


class PublicKey:
    """An authority's Ed25519 verifying key (crypto.rs:24)."""

    __slots__ = ("bytes", "_key")

    def __init__(self, raw: bytes) -> None:
        if len(raw) != PUBLIC_KEY_SIZE:
            raise ValueError(f"public key must be {PUBLIC_KEY_SIZE} bytes")
        self.bytes = raw
        self._key: Optional[Ed25519PublicKey] = None

    def _loaded(self) -> Ed25519PublicKey:
        if self._key is None:
            self._key = Ed25519PublicKey.from_public_bytes(self.bytes)
        return self._key

    def verify(self, signature: bytes, message: bytes) -> bool:
        try:
            self._loaded().verify(signature, message)
            return True
        except InvalidSignature:
            return False

    def __eq__(self, other: object) -> bool:
        return isinstance(other, PublicKey) and self.bytes == other.bytes

    def __hash__(self) -> int:
        return hash(self.bytes)

    def __repr__(self) -> str:
        return f"PublicKey({self.bytes.hex()[:8]})"


class Signer:
    """An authority's Ed25519 signing key (crypto.rs:26,199-223).

    Key material is held only by this object; ``dummy_signer`` (crypto.rs:355-357)
    equivalent is ``Signer.dummy()`` used by tests and the DAG DSL.
    """

    __slots__ = ("_key", "public_key")

    def __init__(self, key: Ed25519PrivateKey) -> None:
        self._key = key
        self.public_key = PublicKey(key.public_key().public_bytes_raw())

    @classmethod
    def generate(cls) -> "Signer":
        return cls(Ed25519PrivateKey.generate())

    @classmethod
    def from_seed(cls, seed: bytes) -> "Signer":
        """Deterministic signer from a 32-byte seed (test/genesis tooling)."""
        if len(seed) != 32:
            seed = hashlib.blake2b(seed, digest_size=32).digest()
        return cls(Ed25519PrivateKey.from_private_bytes(seed))

    @classmethod
    def dummy(cls) -> "Signer":
        return cls.from_seed(b"\x00" * 32)

    def sign(self, message: bytes) -> bytes:
        return self._key.sign(message)

    def __repr__(self) -> str:
        return f"Signer({self.public_key.bytes.hex()[:8]})"
