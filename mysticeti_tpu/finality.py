"""Client-perceived finality SLI plane: submit → finalized, phase by phase.

The ingress plane (PR 11) already names every transaction with a 16-byte
BLAKE2b key (``ingress_key``) for dedup and commit notifications.  This
module joins those keys across the transaction lifecycle to measure what a
client actually experiences — the latency-to-finality number the paper
leads with (arXiv 2310.14821) — split into the phases a regression can
hide in:

=============  =====================================================
phase          interval
=============  =====================================================
``admission``  gateway/handler submit → mempool accept
``proposal``   mempool accept → drained into a block proposal
``commit``     proposal inclusion → leader-sequence commit decision
``finalize``   commit decision → commit observer finalized the subdag
``execute``    finalized → execution state machine folded the commit
``notify``     finalized/executed → gateway commit notification queued
``total``      submit → finalized — or submit → EXECUTED when the
               execution plane is on (``execute_expected``): finality
               then means results, not sequencing
=============  =====================================================

Cost is bounded by *content-based count sampling*: a key participates iff
``key_sampled(key, every)`` — a pure function of the key bytes — so every
node samples the SAME transactions without coordination, the sampled set
is deterministic under the seeded simulator, and the per-transaction hot
path cost for unsampled keys is one modulo.

Exports ``mysticeti_e2e_finality_seconds{phase}`` histograms plus rolling
``p50/p99`` gauges (exact percentiles over a bounded recent-sample window,
refreshed from the ingress tick), feeds the ``finality-p99`` SLO watchdog
via :meth:`FinalityTracker.state`, and cross-checks against the
CLIENT-observed numbers the closed-loop ``TransactionGenerator`` records.
"""
from __future__ import annotations

from collections import OrderedDict, deque
from typing import Deque, Dict, Iterable, List, Optional

import threading

from .runtime import now as runtime_now

# Pending-entry cap: sampled keys awaiting commit.  At sample_every=16 and
# 100k tx/s offered, ~6k sampled keys/s enter; 8192 pending bounds memory
# while surviving multi-second commit latency at that extreme.
DEFAULT_PENDING_CAP = 8192
# Recent-sample window for the exact p50/p99 gauges.
DEFAULT_SAMPLE_WINDOW = 512

PHASES = (
    "admission", "proposal", "commit", "finalize", "execute", "notify",
    "total",
)


def key_sampled(key: bytes, every: int) -> bool:
    """Deterministic content-based sampling decision for one ingress key.

    Uses the key's first two bytes (already uniform — BLAKE2b output) so
    all nodes and the client generators agree on the sampled set without
    coordination.
    """
    if every <= 1:
        return True
    return int.from_bytes(key[:2], "little") % every == 0


def percentile(samples: List[float], q: float) -> float:
    """Exact nearest-rank percentile over a small sample list (0 if empty)."""
    if not samples:
        return 0.0
    ordered = sorted(samples)
    rank = min(len(ordered) - 1, max(0, int(q * len(ordered))))
    return ordered[rank]


class FinalityTracker:
    """Per-node submit→finality phase joiner over sampled ingress keys."""

    def __init__(
        self,
        metrics=None,
        sample_every: int = 16,
        pending_cap: int = DEFAULT_PENDING_CAP,
        sample_window: int = DEFAULT_SAMPLE_WINDOW,
        clock=runtime_now,
    ) -> None:
        self.metrics = metrics
        self.sample_every = max(1, sample_every)
        self.pending_cap = max(16, pending_cap)
        self.clock = clock
        self._finality_lock = threading.Lock()
        # Guarded by _finality_lock (lint GUARDED_FIELDS): stamps arrive
        # from the submit path, the proposal drain, and the commit
        # observer, while the ingress tick reads percentiles.
        self._finality_pending: "OrderedDict[bytes, Dict[str, float]]" = (
            OrderedDict()
        )
        self._finality_samples: Deque[float] = deque(maxlen=sample_window)
        self.completed = 0
        self.expired = 0
        # Execution-backed finality: set by the ingress plane when the core
        # runs the execution state machine.  The ``total`` SLI then closes
        # at :meth:`on_execute` (results), not :meth:`on_commit`
        # (sequencing).
        self.execute_expected = False

    def sampled(self, key: bytes) -> bool:
        return key_sampled(key, self.sample_every)

    def _observe(self, phase: str, seconds: float) -> None:
        if self.metrics is not None:
            self.metrics.mysticeti_e2e_finality_seconds.labels(phase).observe(
                max(0.0, seconds)
            )

    # -- lifecycle stamps (all tolerate unknown/unsampled keys) --

    def on_submit(self, key: bytes, t_submit: float, t_admitted: float) -> None:
        """A sampled key was admitted into the mempool."""
        self._observe("admission", t_admitted - t_submit)
        with self._finality_lock:
            self._finality_pending[key] = {
                "submit": t_submit,
                "admitted": t_admitted,
            }
            while len(self._finality_pending) > self.pending_cap:
                self._finality_pending.popitem(last=False)
                self.expired += 1

    def on_proposal(self, key: bytes, t: float) -> None:
        """A sampled key was drained into a block proposal."""
        with self._finality_lock:
            entry = self._finality_pending.get(key)
            if entry is None or "proposal" in entry:
                return
            entry["proposal"] = t
            admitted = entry["admitted"]
        self._observe("proposal", t - admitted)

    def on_commit(self, key: bytes, t_commit: float, t_finalize: float) -> None:
        """A sampled key's transaction was committed (``t_commit`` = the
        commit decision, from the observer's entry clock) and finalized
        (``t_finalize`` = observer completion).  Completes the ``total``
        sample — unless ``execute_expected``, in which case the total
        waits for :meth:`on_execute`; either way the entry stays so later
        execute/notify stamps can close their phases."""
        with self._finality_lock:
            entry = self._finality_pending.get(key)
            if entry is None or "finalize" in entry:
                return
            entry["finalize"] = t_finalize
            submit = entry["submit"]
            upstream = entry.get("proposal", entry["admitted"])
            total = t_finalize - submit
            if not self.execute_expected:
                self._finality_samples.append(max(0.0, total))
                self.completed += 1
        self._observe("commit", t_commit - upstream)
        self._observe("finalize", t_finalize - t_commit)
        if not self.execute_expected:
            self._observe("total", total)

    def on_execute(self, keys: Iterable[bytes], t: float) -> None:
        """Sampled keys' transactions were folded through the execution
        state machine.  With the execution plane on this is where the
        headline ``total`` SLI closes: a client waiting on the EXECUTED
        notification waited for results, not sequencing."""
        phases: List[float] = []
        totals: List[float] = []
        with self._finality_lock:
            for key in keys:
                entry = self._finality_pending.get(key)
                if entry is None or "finalize" not in entry or "execute" in entry:
                    continue
                entry["execute"] = t
                phases.append(t - entry["finalize"])
                total = t - entry["submit"]
                self._finality_samples.append(max(0.0, total))
                self.completed += 1
                totals.append(total)
        for seconds in phases:
            self._observe("execute", seconds)
        for total in totals:
            self._observe("total", total)

    def on_notify(self, keys: Iterable[bytes], t: float) -> None:
        """Sampled keys' commit notifications were queued to a gateway
        subscriber (the last measurable server-side hop)."""
        stamps: List[float] = []
        with self._finality_lock:
            for key in keys:
                entry = self._finality_pending.pop(key, None)
                if entry is None or "finalize" not in entry:
                    continue
                stamps.append(entry.get("execute", entry["finalize"]))
        for done in stamps:
            self._observe("notify", t - done)

    # -- views --

    def samples(self) -> List[float]:
        """The recent completed-total samples (fleet aggregation helper)."""
        with self._finality_lock:
            return list(self._finality_samples)

    def percentiles(self) -> Dict[str, float]:
        with self._finality_lock:
            samples = list(self._finality_samples)
        return {
            "p50_s": percentile(samples, 0.50),
            "p99_s": percentile(samples, 0.99),
            "samples": len(samples),
        }

    def export_gauges(self) -> None:
        """Refresh the rolling percentile gauges (ingress tick cadence)."""
        if self.metrics is None:
            return
        p = self.percentiles()
        self.metrics.mysticeti_e2e_finality_p50_seconds.set(p["p50_s"])
        self.metrics.mysticeti_e2e_finality_p99_seconds.set(p["p99_s"])

    def state(self) -> Dict[str, float]:
        """Health/debug snapshot (feeds ``health_state()`` → the
        ``finality-p99`` watchdog and ``/health``)."""
        p = self.percentiles()
        with self._finality_lock:
            pending = len(self._finality_pending)
        return {
            "samples": p["samples"],
            "completed": self.completed,
            "expired": self.expired,
            "pending": pending,
            "p50_s": round(p["p50_s"], 6),
            "p99_s": round(p["p99_s"], 6),
        }


class ClientFinalityRecorder:
    """Client-side mirror of the tracker for closed-loop generators.

    Lives entirely on the generator's loop thread (no lock): stamps
    sampled keys at submit time and closes them when the commit-sink /
    gateway notification echoes the key back, so client-observed finality
    can cross-check the server-side series in one artifact.
    """

    def __init__(
        self,
        sample_every: int = 16,
        pending_cap: int = DEFAULT_PENDING_CAP,
        sample_window: int = DEFAULT_SAMPLE_WINDOW,
        clock=runtime_now,
    ) -> None:
        self.sample_every = max(1, sample_every)
        self.pending_cap = max(16, pending_cap)
        self.clock = clock
        self._pending: "OrderedDict[bytes, float]" = OrderedDict()
        self._samples: Deque[float] = deque(maxlen=sample_window)
        self.completed = 0
        self.expired = 0

    def note_submitted(self, key: bytes) -> None:
        if not key_sampled(key, self.sample_every):
            return
        # setdefault: a closed-loop retry must keep the FIRST submit time —
        # the client experienced the whole wait.
        self._pending.setdefault(key, self.clock())
        while len(self._pending) > self.pending_cap:
            self._pending.popitem(last=False)
            self.expired += 1

    def note_finalized(self, keys: Iterable[bytes]) -> None:
        now = self.clock()
        for key in keys:
            submitted = self._pending.pop(key, None)
            if submitted is None:
                continue
            self._samples.append(max(0.0, now - submitted))
            self.completed += 1

    def samples(self) -> List[float]:
        return list(self._samples)

    def percentiles(self) -> Dict[str, float]:
        samples = list(self._samples)
        return {
            "p50_s": percentile(samples, 0.50),
            "p99_s": percentile(samples, 0.99),
            "samples": len(samples),
        }
