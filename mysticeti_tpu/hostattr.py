"""Host attribution plane: loop-lag probe, GIL/blocking-call monitor.

The runtime half of the per-subsystem accountant (``profiling.py``): where
the sampler says *which code* owns host CPU, this module says *what that
costs the event loop* —

* :class:`LoopLagProbe` — measures asyncio scheduling lag by the classic
  sleep-overshoot probe: schedule a callback ``interval`` out, measure how
  late it actually ran.  The delta histogram
  (``mysticeti_loop_lag_seconds``) is the node's direct "is the core owner
  responsive" signal; its p99 rides a gauge, the ``/health`` diagnosis, and
  the ``loop-lag`` SLO watchdog kind.
* :class:`HostMonitor` — bundles the probe with the blocking-call detector:
  the core task dispatcher (``core_task.py``) reports every synchronous
  command's wall duration here, and any hold beyond the threshold
  (``MYSTICETI_BLOCKING_CALL_MS``, default 50) is flagged at runtime — the
  dynamic twin of the ``async-blocking`` lint rule — as a series increment,
  a flight-recorder event, and (through the health probe) a
  ``blocking-call`` SLO alert.

Deterministic-sim discipline: under the virtual-time loop the probe never
starts (sleeps are exact by construction — lag would measure the host, not
the node) and the dispatcher skips duration measurement, so a seeded sim
reports all-zero host state byte-identically.
"""
from __future__ import annotations

import asyncio
import os
from collections import deque
from typing import Optional

from .tracing import logger
from .utils.tasks import spawn_logged

log = logger(__name__)

DEFAULT_BLOCKING_CALL_MS = 50.0


def _percentile(values, pct: float) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    idx = min(len(ordered) - 1, int(pct / 100.0 * len(ordered)))
    return ordered[idx]


class LoopLagProbe:
    """Scheduled-vs-actual callback delta over a bounded ring.

    One coroutine, one short sleep per interval: the overshoot beyond the
    requested interval is exactly the time the loop spent running other
    callbacks (or a blocking call) instead of this one.
    """

    def __init__(
        self,
        interval_s: float = 0.25,
        metrics=None,
        window: int = 256,
    ) -> None:
        self.interval_s = interval_s
        self.metrics = metrics
        self._lags: deque = deque(maxlen=window)
        self._task: Optional[asyncio.Task] = None

    def start(self) -> "LoopLagProbe":
        from .runtime import is_simulated

        if self._task is not None or is_simulated():
            # Virtual time: sleeps complete exactly on schedule, so the
            # probe would only add loop churn to seeded runs.
            return self
        self._task = spawn_logged(self._run(), log, name="loop-lag-probe")
        return self

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            scheduled = loop.time() + self.interval_s
            await asyncio.sleep(self.interval_s)
            lag = max(0.0, loop.time() - scheduled)
            self._lags.append(lag)
            if self.metrics is not None:
                self.metrics.mysticeti_loop_lag_seconds.observe(lag)
                self.metrics.mysticeti_loop_lag_p99_seconds.set(
                    self.percentile(99)
                )

    def percentile(self, pct: float) -> float:
        return _percentile(list(self._lags), pct)

    def sample_count(self) -> int:
        return len(self._lags)


class HostMonitor:
    """The node's host-condition monitor: loop lag + blocking-call census.

    All mutation happens on the event-loop thread (the dispatcher reports
    from its own loop task; the health probe samples from its loop task),
    so no lock is needed — mirroring ``VerifyPipeline``'s discipline.
    """

    def __init__(
        self,
        metrics=None,
        recorder=None,
        blocking_threshold_ms: Optional[float] = None,
    ) -> None:
        if blocking_threshold_ms is None:
            blocking_threshold_ms = float(
                os.environ.get("MYSTICETI_BLOCKING_CALL_MS", "")
                or DEFAULT_BLOCKING_CALL_MS
            )
        self.blocking_threshold_ms = blocking_threshold_ms
        self.metrics = metrics
        self.recorder = recorder
        self.loop_lag = LoopLagProbe(metrics=metrics)
        self._blocking_total = 0
        self._worst_since_drain_ms = 0.0
        self._last_blocking: Optional[dict] = None

    # -- lifecycle --

    def start(self) -> "HostMonitor":
        self.loop_lag.start()
        return self

    def stop(self) -> None:
        self.loop_lag.stop()

    # -- the blocking-call detector (called by CoreTaskDispatcher) --

    def note_command(self, site: str, seconds: float) -> None:
        """One synchronous core command ran for ``seconds`` wall time on
        the core owner task.  Beyond the threshold it is a detected
        blocking call: counted, flight-recorded, and surfaced to the SLO
        watchdog through :meth:`drain_worst_blocking_ms`."""
        ms = seconds * 1000.0
        if ms < self.blocking_threshold_ms:
            return
        self._blocking_total += 1
        if ms > self._worst_since_drain_ms:
            self._worst_since_drain_ms = ms
        self._last_blocking = {"site": site, "ms": round(ms, 3)}
        if self.metrics is not None:
            self.metrics.mysticeti_blocking_calls_total.labels(site).inc()
            self.metrics.mysticeti_blocking_call_last_ms.set(round(ms, 3))
        if self.recorder is not None:
            self.recorder.record(
                "blocking-call",
                site=site,
                ms=round(ms, 3),
                threshold_ms=self.blocking_threshold_ms,
            )
        log.warning(
            "blocking call on core owner: %s held the loop %.1f ms "
            "(threshold %.0f ms)", site, ms, self.blocking_threshold_ms,
        )

    def drain_worst_blocking_ms(self) -> float:
        """Worst blocking hold since the last drain (the health probe's
        per-sample watchdog value); resets so the alert re-arms after a
        clean sample."""
        worst = self._worst_since_drain_ms
        self._worst_since_drain_ms = 0.0
        return worst

    @property
    def blocking_total(self) -> int:
        return self._blocking_total

    # -- the /health diagnosis block --

    def state(self) -> dict:
        from .profiling import active_accountant

        accountant = active_accountant()
        convoy = 0.0
        if accountant is not None:
            report_meta = accountant.report()
            convoy = report_meta["gil_convoy_ratio"]
        return {
            "loop_lag_p50_s": round(self.loop_lag.percentile(50), 6),
            "loop_lag_p99_s": round(self.loop_lag.percentile(99), 6),
            "loop_lag_samples": self.loop_lag.sample_count(),
            "blocking_calls": self._blocking_total,
            "last_blocking": self._last_blocking,
            "blocking_threshold_ms": self.blocking_threshold_ms,
            "gil_convoy_ratio": convoy,
        }


__all__ = ["HostMonitor", "LoopLagProbe", "DEFAULT_BLOCKING_CALL_MS"]
