"""Runtime facade: one import surface for real-asyncio and simulated execution.

Capability parity with ``mysticeti-core/src/runtime/`` (mod.rs:4-14, tokio.rs,
simulated.rs): node code calls ``runtime.sleep/now/timestamp_utc/spawn`` and
works unchanged under (a) the production asyncio loop and (b) the deterministic
virtual-time loop (:mod:`mysticeti_tpu.runtime.simulated`) — because the
simulator IS an asyncio event loop whose clock is virtual, every asyncio
primitive (Event, Queue, Future, call_later) is automatically deterministic
under it.  That one design choice replaces the reference's entire
future_simulator.rs executor (361 LoC of custom wakers) with the platform's
own scheduler.
"""
from __future__ import annotations

import asyncio
import time
from typing import Awaitable, Coroutine, Optional

from .simulated import DeterministicLoop, SimulatedClock


async def sleep(seconds: float) -> None:
    await asyncio.sleep(seconds)


def now() -> float:
    """Monotonic runtime clock (virtual under simulation)."""
    try:
        return asyncio.get_running_loop().time()
    except RuntimeError:
        return time.monotonic()


def timestamp_utc() -> float:
    """Wall-clock seconds (virtual-offset under simulation)."""
    loop = None
    try:
        loop = asyncio.get_running_loop()
    except RuntimeError:
        pass
    if isinstance(loop, DeterministicLoop):
        return loop.utc_time()
    return time.time()


def spawn(coro: Coroutine) -> asyncio.Task:
    return asyncio.get_running_loop().create_task(coro)


def is_simulated() -> bool:
    """True when running under the deterministic virtual-time loop.  Code on
    real-thread boundaries (executor dispatch) uses this to minimize loop
    round-trips: while a real thread works, the virtual clock leaps timers,
    so every extra hop skews a sim's virtual/real time ratio."""
    try:
        return isinstance(asyncio.get_running_loop(), DeterministicLoop)
    except RuntimeError:
        return False


__all__ = [
    "sleep",
    "now",
    "timestamp_utc",
    "spawn",
    "is_simulated",
    "DeterministicLoop",
    "SimulatedClock",
]
