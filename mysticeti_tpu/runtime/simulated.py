"""Deterministic virtual-time asyncio event loop (the DES engine).

Capability parity with ``mysticeti-core/src/simulator.rs`` (seeded event heap)
+ ``future_simulator.rs`` (futures as simulator events): instead of a custom
executor, this subclasses ``asyncio.BaseEventLoop`` so that

* ``loop.time()`` is virtual: when no callback is ready, the clock JUMPS to the
  next scheduled timer instead of blocking (``_NullSelector.select`` advances
  the clock by the requested timeout);
* all ordinary asyncio machinery — timers, Events, Queues, Tasks — therefore
  executes deterministically in virtual time with zero real-world waiting;
* randomness comes only from the seeded ``random.Random`` owned by the loop
  (``simulator.rs:12-32`` seeded-RNG discipline).

Real sockets are structurally impossible here (the selector refuses
registration), which is exactly the guarantee the reference gets from its
``simulator`` feature flag: simulated runs cannot accidentally touch the OS.
"""
from __future__ import annotations

import asyncio
import random
import selectors
from asyncio import base_events
from typing import Awaitable, Optional

_BASE_UTC = 1_700_000_000.0  # arbitrary fixed epoch for reproducible timestamps


class SimulatedClock:
    __slots__ = ("virtual",)

    def __init__(self) -> None:
        self.virtual = 0.0


class _NullSelector(selectors.BaseSelector):
    """Selector that never blocks: 'waiting' advances virtual time instead."""

    def __init__(self, clock: SimulatedClock) -> None:
        self._clock = clock

    def select(self, timeout: Optional[float] = None):
        if timeout is not None and timeout > 0:
            self._clock.virtual += timeout
        return []

    def register(self, fileobj, events, data=None):  # pragma: no cover
        raise RuntimeError("real I/O is not available inside the simulator")

    def unregister(self, fileobj):  # pragma: no cover
        raise RuntimeError("real I/O is not available inside the simulator")

    def close(self) -> None:
        pass

    def get_map(self):
        return {}


class DeterministicLoop(base_events.BaseEventLoop):
    """Seeded, virtual-time asyncio loop."""

    def __init__(self, seed: int = 0) -> None:
        super().__init__()
        self._clock = SimulatedClock()
        self._selector = _NullSelector(self._clock)
        self.rng = random.Random(seed)
        self.seed = seed
        # Determinism-sanitizer seam (detsan.py): when a DetsanRecorder is
        # attached, every scheduled callback is wrapped so the recorder
        # digests events in EXECUTION order.  None = zero overhead.
        self.detsan = None

    # -- detsan event capture --

    def call_soon(self, callback, *args, context=None):
        if self.detsan is not None:
            callback, args = self.detsan.wrap(self, callback, args)
        return super().call_soon(callback, *args, context=context)

    def call_at(self, when, callback, *args, context=None):
        if self.detsan is not None:
            callback, args = self.detsan.wrap(self, callback, args)
        return super().call_at(when, callback, *args, context=context)

    # -- virtual clock --

    def time(self) -> float:
        return self._clock.virtual

    def utc_time(self) -> float:
        return _BASE_UTC + self._clock.virtual

    # -- plumbing BaseEventLoop expects --

    def _process_events(self, event_list) -> None:
        pass

    def call_soon_threadsafe(self, callback, *args, context=None):
        # Single-threaded simulation: no wakeup pipe needed.
        return self.call_soon(callback, *args, context=context)

    def _write_to_self(self) -> None:
        pass


def run_simulation(
    main: Awaitable,
    seed: int = 0,
    timeout_s: Optional[float] = None,
    detsan=None,
):
    """Run ``main`` to completion on a fresh DeterministicLoop; returns its result.

    ``timeout_s`` bounds *virtual* time: exceeding it raises TimeoutError —
    reproducibly, since everything is seeded.  ``detsan`` attaches a
    :class:`mysticeti_tpu.detsan.DetsanRecorder` that digests every executed
    event for run-twice divergence bisection.
    """
    loop = DeterministicLoop(seed)
    loop.detsan = detsan
    from mysticeti_tpu.types import StatementBlock

    StatementBlock.enable_decode_memo()
    try:
        asyncio.set_event_loop(loop)
        if timeout_s is not None:
            main = asyncio.wait_for(main, timeout=timeout_s)
        result = loop.run_until_complete(main)
        # The detsan trace certifies the run THROUGH its result.  The
        # straggler sweep below iterates the all_tasks() set, whose order
        # is interpreter address noise, not simulated behavior — recording
        # it would make every run-twice diff 'diverge' during teardown.
        loop.detsan = None
        # Cancel stragglers and let their cancellation run, so no coroutine is
        # destroyed mid-await after the loop closes.
        pending = [t for t in asyncio.all_tasks(loop) if not t.done()]
        for t in pending:
            t.cancel()
        if pending:
            loop.run_until_complete(
                asyncio.gather(*pending, return_exceptions=True)
            )
        return result
    finally:
        StatementBlock.disable_decode_memo()
        asyncio.set_event_loop(None)
        loop.close()
