"""Commit-anchored epoch reconfiguration: dynamic committee membership.

The committed leader sequence of an uncertified DAG is a total order every
honest node derives identically, which makes it a natural reconfiguration
anchor (the Mysticeti paper notes this; the reference implementation never
built it).  This module is the pure machinery:

* ``CommitteeChange`` — an add/remove/reweight transaction that rides the
  committed sequence as an ordinary ``Share`` payload prefixed with
  ``RECONFIG_MAGIC``.
* ``committee_digest`` — canonical 32-byte digest of (epoch, stakes, keys);
  two nodes in the same epoch with different digests have diverged.
* ``apply_change`` — pure committee derivation (epoch + 1); invalid changes
  (activating an active member, removing an inactive one, reweighting to the
  current stake) are deterministic no-ops, which makes duplicate transactions
  idempotent without any extra bookkeeping.
* ``EpochRecord`` / ``EpochChain`` — the durable epoch history: each record
  pins (epoch, boundary commit height, boundary leader round, digest, stake
  vector).  The chain rides checkpoints and snapshot manifests as a soft
  serialization tail, so crash recovery and cross-boundary catch-up both
  reboot into the right epoch.
* ``ReconfigState`` — the per-node state machine owned by the consensus
  core: scans each committed sub-dag (in linearized order, one commit at a
  time) for change transactions and produces :class:`EpochTransition`\\ s.

Membership model — stable indices
---------------------------------
The full *potential* membership is registered at genesis; every authority
keeps its index, key, and genesis block forever.  An ADD activates a
registered member (stake 0 → s), a REMOVE deactivates one (stake → 0, index
retained), a REWEIGHT changes a positive stake.  The active set is exactly
the positive-stake set: zero-stake members contribute nothing to quorum or
validity thresholds and are provably unelectable under the stake-weighted
leader PRF (the accumulator never advances past them).  Keeping indices
stable means ``BlockReference.authority`` and every persisted structure stay
valid across epochs.  Registering *new* keys after genesis is out of scope
(see docs/reconfiguration.md trust notes).
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from .committee import Authority, Committee
from .serde import Reader, SerdeError, Writer
from .types import Share, StatementBlock

# Share-payload prefix marking a committee-change transaction.  8 bytes so an
# accidental collision with benchmark payloads (8-byte little-endian counters
# and stamped random bytes) is vanishingly unlikely, and the first byte 0xFF
# is unreachable for any counter below 2**63.
RECONFIG_MAGIC = b"\xffRECONF\x01"

CHANGE_ADD = 0  # activate a registered authority: stake 0 -> stake
CHANGE_REMOVE = 1  # deactivate: stake -> 0 (index and key retained)
CHANGE_REWEIGHT = 2  # change a positive stake to another positive stake

_KIND_NAMES = {CHANGE_ADD: "add", CHANGE_REMOVE: "remove", CHANGE_REWEIGHT: "reweight"}


@dataclass(frozen=True)
class CommitteeChange:
    """One membership/stake change riding the committed sequence."""

    kind: int
    authority: int
    stake: int = 0

    def __post_init__(self) -> None:
        if self.kind not in _KIND_NAMES:
            raise ValueError(f"unknown change kind {self.kind}")
        if self.kind in (CHANGE_ADD, CHANGE_REWEIGHT) and self.stake <= 0:
            raise ValueError(f"{_KIND_NAMES[self.kind]} requires positive stake")
        if self.stake < 0:
            raise ValueError("stake must be non-negative")

    def to_bytes(self) -> bytes:
        w = Writer()
        w.fixed(RECONFIG_MAGIC)
        w.u8(self.kind)
        w.u64(self.authority)
        w.u64(self.stake)
        return w.finish()

    @staticmethod
    def from_bytes(data: bytes) -> "CommitteeChange":
        r = Reader(data)
        magic = r.fixed(len(RECONFIG_MAGIC))
        if magic != RECONFIG_MAGIC:
            raise SerdeError("not a reconfiguration transaction")
        kind = r.u8()
        authority = r.u64()
        stake = r.u64()
        r.expect_done()
        return CommitteeChange(kind, authority, stake)

    def describe(self) -> str:
        return f"{_KIND_NAMES[self.kind]}(authority={self.authority}, stake={self.stake})"


def parse_reconfig_tx(payload: bytes) -> Optional[CommitteeChange]:
    """Decode a Share payload into a change, or None for ordinary
    transactions.  A payload that carries the magic but fails to decode is
    treated as ordinary data (a garbled change must not fork honest nodes on
    whether to error — ignoring it is the deterministic choice)."""
    if not payload.startswith(RECONFIG_MAGIC):
        return None
    try:
        return CommitteeChange.from_bytes(payload)
    except (SerdeError, ValueError):
        return None


def committee_digest(committee: Committee) -> bytes:
    """Canonical digest of one epoch's committee: blake2b-256 over
    (epoch, count, per-authority (key, stake)) in index order.  Hostnames and
    election strategy are deployment-local and excluded."""
    h = hashlib.blake2b(b"mysticeti-tpu/committee", digest_size=32)
    h.update(committee.epoch.to_bytes(8, "little"))
    h.update(len(committee).to_bytes(4, "little"))
    for a in committee.authorities:
        h.update(a.public_key.bytes)
        h.update(a.stake.to_bytes(8, "little"))
    return h.digest()


def change_is_valid(committee: Committee, change: CommitteeChange) -> bool:
    """Is ``change`` applicable to ``committee``?  Validity against the
    *current* committee is what makes duplicate submissions idempotent: the
    first application flips the state the duplicate's validity depends on."""
    if not committee.known_authority(change.authority):
        return False
    current = committee.get_stake(change.authority)
    if change.kind == CHANGE_ADD:
        return current == 0
    if change.kind == CHANGE_REMOVE:
        if current == 0:
            return False
        # Never deactivate the last active member: an empty active set has
        # no quorum and the fleet would halt unrecoverably.
        return sum(1 for a in committee.authorities if a.stake > 0) > 1
    # CHANGE_REWEIGHT
    return current > 0 and change.stake != current


def apply_change(committee: Committee, change: CommitteeChange) -> Optional[Committee]:
    """Derive the next epoch's committee, or None when the change is a
    no-op.  Pure: keys, hostnames, and election strategy carry over; only the
    targeted stake and the epoch number move."""
    if not change_is_valid(committee, change):
        return None
    stakes = [a.stake for a in committee.authorities]
    stakes[change.authority] = 0 if change.kind == CHANGE_REMOVE else change.stake
    return committee.with_stakes(stakes, committee.epoch + 1)


@dataclass(frozen=True)
class EpochRecord:
    """One epoch boundary: the commit that finalized the change and the
    committee it produced (as its full stake vector — keys are stable, so
    stakes + the genesis registry reproduce the committee exactly)."""

    epoch: int
    boundary_height: int  # commit height whose sub-dag carried the change
    boundary_round: int  # that commit's anchor (leader) round
    digest: bytes  # committee_digest of the epoch's committee
    stakes: Tuple[int, ...]

    def encode(self, w: Writer) -> None:
        w.u64(self.epoch).u64(self.boundary_height).u64(self.boundary_round)
        w.fixed(self.digest)
        w.u32(len(self.stakes))
        for s in self.stakes:
            w.u64(s)

    @staticmethod
    def decode(r: Reader) -> "EpochRecord":
        epoch, height, round_ = r.u64(), r.u64(), r.u64()
        digest = r.fixed(32)
        stakes = tuple(r.u64() for _ in range(r.u32()))
        return EpochRecord(epoch, height, round_, digest, stakes)


class EpochChain:
    """The ordered epoch history since genesis (epoch 0 is implicit: the
    genesis committee itself).  Serialized into checkpoints and snapshot
    manifests so recovery and catch-up re-derive the same epoch."""

    __slots__ = ("records",)

    def __init__(self, records: Sequence[EpochRecord] = ()) -> None:
        self.records: List[EpochRecord] = list(records)
        self._check()

    def _check(self) -> None:
        prev_epoch, prev_height = 0, -1
        for rec in self.records:
            if rec.epoch != prev_epoch + 1:
                raise SerdeError(
                    f"epoch chain not contiguous: {rec.epoch} after {prev_epoch}"
                )
            if rec.boundary_height < prev_height:
                raise SerdeError("epoch chain boundary heights must not decrease")
            prev_epoch, prev_height = rec.epoch, rec.boundary_height

    @property
    def epoch(self) -> int:
        return self.records[-1].epoch if self.records else 0

    @property
    def last_height(self) -> int:
        """Highest commit height already folded into the chain; commits at or
        below it must not be re-scanned (crash replay re-delivers them)."""
        return self.records[-1].boundary_height if self.records else 0

    def append(self, record: EpochRecord) -> None:
        self.records.append(record)
        self._check()

    def to_bytes(self) -> bytes:
        w = Writer()
        w.u32(len(self.records))
        for rec in self.records:
            rec.encode(w)
        return w.finish()

    @staticmethod
    def from_bytes(data: bytes) -> "EpochChain":
        if not data:
            return EpochChain()
        r = Reader(data)
        records = [EpochRecord.decode(r) for _ in range(r.u32())]
        r.expect_done()
        return EpochChain(records)

    def derive_committee(self, genesis: Committee) -> Committee:
        """Rebuild the current epoch's committee from the genesis registry +
        the last record's stake vector.  The vector length must match the
        registered membership (stable-index model)."""
        if not self.records:
            return genesis
        last = self.records[-1]
        if len(last.stakes) != len(genesis):
            raise SerdeError(
                f"epoch chain stake vector has {len(last.stakes)} entries for a"
                f" {len(genesis)}-member registry"
            )
        committee = genesis.with_stakes(list(last.stakes), last.epoch)
        if committee_digest(committee) != last.digest:
            raise SerdeError(
                f"epoch {last.epoch} digest mismatch: chain record does not"
                " describe this genesis registry"
            )
        return committee


@dataclass(frozen=True)
class EpochTransition:
    """The outcome of folding one or more finalized changes: the committee to
    switch to and the record(s) appended to the chain."""

    committee: Committee
    records: Tuple[EpochRecord, ...]


class ReconfigState:
    """Per-node reconfiguration state machine, owned by the consensus core
    (single-owner discipline: only the core task mutates it).

    ``observe_commit`` is called once per committed sub-dag, in linearized
    order.  It scans the sub-dag's blocks (in their committed order) for
    change transactions and folds every valid one; each application is its
    own epoch.  Because every honest node sees the same committed sequence
    and the fold is pure, all nodes derive identical chains."""

    def __init__(self, genesis: Committee, chain: Optional[EpochChain] = None) -> None:
        if genesis.epoch != 0:
            raise ValueError("reconfiguration requires an epoch-0 genesis committee")
        self.genesis = genesis
        self.chain = chain if chain is not None else EpochChain()
        self.committee = self.chain.derive_committee(genesis)

    @property
    def epoch(self) -> int:
        return self.chain.epoch

    def digest(self) -> bytes:
        return committee_digest(self.committee)

    def committee_for_epoch(self, epoch: int) -> Optional[Committee]:
        """The committee a given epoch ran under, rebuilt from the chain's
        stake vector (stable-index model).  Historical blocks must be
        structurally judged by THEIR epoch's quorum arithmetic — catch-up
        replays pre-boundary rounds long after the switch, and the old
        quorum is what their include sets were built against.  Returns
        None for epochs this chain has not derived (including claimed
        FUTURE epochs: a lying author gets the current committee's rules,
        not lenient ones)."""
        if epoch == 0:
            return self.genesis
        for rec in self.chain.records:
            if rec.epoch == epoch:
                return self.genesis.with_stakes(list(rec.stakes), epoch)
        return None

    def scan_blocks(
        self, blocks: Sequence[StatementBlock]
    ) -> List[CommitteeChange]:
        """Change transactions in committed-block order (duplicates and
        ordinary payloads included/excluded as-is; validity is judged at
        fold time against the then-current committee)."""
        changes: List[CommitteeChange] = []
        for block in blocks:
            for st in block.statements:
                if isinstance(st, Share):
                    change = parse_reconfig_tx(st.transaction)
                    if change is not None:
                        changes.append(change)
        return changes

    def observe_commit(
        self,
        height: int,
        anchor_round: int,
        blocks: Sequence[StatementBlock],
    ) -> Optional[EpochTransition]:
        """Fold one committed sub-dag.  Heights at or below the chain's last
        boundary were already folded (checkpoint recovery replays them) and
        are skipped wholesale."""
        if height <= self.chain.last_height and self.chain.records:
            return None
        applied: List[EpochRecord] = []
        for change in self.scan_blocks(blocks):
            derived = apply_change(self.committee, change)
            if derived is None:
                continue
            self.committee = derived
            record = EpochRecord(
                epoch=derived.epoch,
                boundary_height=height,
                boundary_round=anchor_round,
                digest=committee_digest(derived),
                stakes=tuple(a.stake for a in derived.authorities),
            )
            self.chain.append(record)
            applied.append(record)
        if not applied:
            return None
        return EpochTransition(self.committee, tuple(applied))

    def adopt_chain(self, chain_bytes: bytes) -> Optional[EpochTransition]:
        """Adopt a longer epoch chain from a snapshot manifest (cross-boundary
        catch-up: the rejoiner was absent for the boundary commits, so the
        manifest's chain is its only source of the epoch history).  Returns a
        transition when the adopted chain extends ours; a shorter or equal
        chain is ignored (we are already at or past it)."""
        remote = EpochChain.from_bytes(chain_bytes)
        if remote.epoch <= self.epoch:
            return None
        if self.chain.records and (
            remote.records[: len(self.chain.records)] != self.chain.records
        ):
            raise SerdeError(
                "snapshot epoch chain does not extend the local chain"
            )
        committee = remote.derive_committee(self.genesis)
        new_records = tuple(remote.records[len(self.chain.records):])
        self.chain = remote
        self.committee = committee
        return EpochTransition(committee, new_records)
