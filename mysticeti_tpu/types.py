"""The DAG data model: statement blocks, references, statements, authority bitsets.

Capability parity with ``mysticeti-core/src/types.rs``:

* ``BlockReference`` {authority, round, digest}  (types.rs:50-54)
* ``BaseStatement``: Share(tx) | Vote(locator, vote) | VoteRange(range)  (types.rs:57-64)
* ``StatementBlock`` with ordered includes (first include of an (authority, round) pair is
  the one the block conceptually votes for), meta creation time, epoch marker/number, and
  author signature  (types.rs:93-114)
* ``AuthoritySet`` — a 512-bit bitset bounding committee size  (types.rs:116-121)
* ``StatementBlock.verify`` — the consensus-rule verification entry  (types.rs:315-376)
* ``TransactionLocator`` / ``TransactionLocatorRange``  (types.rs:383-394)

Design notes (TPU-first, not a port): blocks are immutable and cache their canonical
serialization at construction, so digesting / signing / wire framing never re-encode
(the role of ``Data<T>`` in data.rs:22-44).  Signature-covered bytes and digest-covered
bytes are the same encoding with/without the trailing signature field, preserving the
reference's layering trick (crypto.rs:77-84) that batch verification relies on.
"""
from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Iterable, Iterator, List, Optional, Sequence, Tuple

from . import crypto
from .serde import Reader, SerdeError, Writer

# Structs for the inline block decoder (from_bytes fast path).
_U64X2 = struct.Struct("<QQ")
_U64_AT = struct.Struct("<Q")
_U32_AT = struct.Struct("<I")

AuthorityIndex = int  # u64 in encodings
RoundNumber = int
Epoch = int

GENESIS_ROUND = 0
MAX_COMMITTEE_SIZE = 512

# Epoch marker carried in each block: has this authority begun epoch change?
EPOCH_OPEN = 0
EPOCH_CHANGED = 1


@dataclass(frozen=True, order=True)
class BlockReference:
    """(authority, round, digest) triple naming a block (types.rs:50-54)."""

    authority: AuthorityIndex
    round: RoundNumber
    digest: bytes  # 32 bytes

    def author_round(self) -> Tuple[AuthorityIndex, RoundNumber]:
        return (self.authority, self.round)

    def encode(self, w: Writer) -> None:
        w.u64(self.authority).u64(self.round).fixed(self.digest)

    @staticmethod
    def decode(r: Reader) -> "BlockReference":
        return BlockReference(r.u64(), r.u64(), r.fixed(crypto.DIGEST_SIZE))

    def __repr__(self) -> str:
        return f"{chr(ord('A') + self.authority % 26)}{self.round}"


@dataclass(frozen=True, order=True)
class TransactionLocator:
    """Names one transaction: the block that shared it + statement offset (types.rs:383-387)."""

    block: BlockReference
    offset: int

    def encode(self, w: Writer) -> None:
        self.block.encode(w)
        w.u64(self.offset)

    @staticmethod
    def decode(r: Reader) -> "TransactionLocator":
        return TransactionLocator(BlockReference.decode(r), r.u64())


# Upper bound on vote-range extent; a Byzantine block must not be able to make a
# validator iterate an unbounded range (reference caps at 1M, types.rs range verify).
LOCATOR_RANGE_MAX_LEN = 1 << 20


@dataclass(frozen=True, order=True)
class TransactionLocatorRange:
    """Half-open offset range of transactions within one block (types.rs:389-394)."""

    block: BlockReference
    offset_start_inclusive: int
    offset_end_exclusive: int

    def verify(self) -> None:
        if self.offset_end_exclusive < self.offset_start_inclusive:
            raise SerdeError(
                f"invalid locator range: end {self.offset_end_exclusive} < "
                f"start {self.offset_start_inclusive}"
            )
        # direct arithmetic: __len__ cannot represent >ssize_t ranges
        if self.offset_end_exclusive - self.offset_start_inclusive > LOCATOR_RANGE_MAX_LEN:
            raise SerdeError(
                f"locator range too long: "
                f"{self.offset_end_exclusive - self.offset_start_inclusive}"
            )
        if self.offset_end_exclusive > LOCATOR_RANGE_MAX_LEN:
            raise SerdeError(
                f"locator range end too large: {self.offset_end_exclusive}"
            )

    def locators(self) -> Iterator[TransactionLocator]:
        for off in range(self.offset_start_inclusive, self.offset_end_exclusive):
            yield TransactionLocator(self.block, off)

    def __len__(self) -> int:
        return max(0, self.offset_end_exclusive - self.offset_start_inclusive)

    def encode(self, w: Writer) -> None:
        self.block.encode(w)
        w.u64(self.offset_start_inclusive).u64(self.offset_end_exclusive)

    @staticmethod
    def decode(r: Reader) -> "TransactionLocatorRange":
        return TransactionLocatorRange(BlockReference.decode(r), r.u64(), r.u64())


# --- Statements -------------------------------------------------------------------

VOTE_ACCEPT = 0
VOTE_REJECT = 1

_ST_SHARE = 0
_ST_VOTE = 1
_ST_VOTE_RANGE = 2


@dataclass(frozen=True)
class Share:
    """Authority shares a transaction without voting on it (types.rs:57-59)."""

    transaction: bytes


@dataclass(frozen=True)
class Vote:
    """Authority votes to accept or reject a transaction (types.rs:30-34,60-61).

    ``conflict`` (the competing locator of a Reject) is only meaningful on reject
    votes; carrying one on an accept would be silently unencodable."""

    locator: TransactionLocator
    accept: bool = True
    conflict: Optional[TransactionLocator] = None  # Reject(Option<locator>)

    def __post_init__(self) -> None:
        if self.accept and self.conflict is not None:
            raise ValueError("accept votes cannot carry a conflict locator")


@dataclass(frozen=True)
class VoteRange:
    """Batched accept votes over a contiguous locator range (types.rs:62-63)."""

    range: TransactionLocatorRange


BaseStatement = object  # Share | Vote | VoteRange


def encode_statements(w: Writer, statements: Sequence[BaseStatement]) -> None:
    """Encode a statement sequence with the Share hot path inlined: a
    saturated proposer encodes ~10k Shares per block (and each statement is
    encoded twice — pending-payload WAL entry, then the proposal), so the
    per-call Writer dispatch was a measurable interpreter cost.  Bytes are
    identical to per-statement ``encode_statement`` (round-trip property
    tests pin canonicality)."""
    buf = w.buf
    pack_len = _U32_AT.pack
    share_tag = bytes([_ST_SHARE])
    for st in statements:
        if type(st) is Share:
            t = st.transaction
            buf += share_tag
            buf += pack_len(len(t))
            buf += t
        else:
            encode_statement(w, st)


def encode_statement(w: Writer, st: BaseStatement) -> None:
    if isinstance(st, Share):
        w.u8(_ST_SHARE).bytes(st.transaction)
    elif isinstance(st, Vote):
        w.u8(_ST_VOTE)
        st.locator.encode(w)
        w.u8(VOTE_ACCEPT if st.accept else VOTE_REJECT)
        if not st.accept:
            w.u8(1 if st.conflict is not None else 0)
            if st.conflict is not None:
                st.conflict.encode(w)
    elif isinstance(st, VoteRange):
        w.u8(_ST_VOTE_RANGE)
        st.range.encode(w)
    else:  # pragma: no cover
        raise SerdeError(f"unknown statement type {type(st)}")


def decode_statement(r: Reader) -> BaseStatement:
    tag = r.u8()
    if tag == _ST_SHARE:
        return Share(r.bytes())
    if tag == _ST_VOTE:
        locator = TransactionLocator.decode(r)
        vote_byte = r.u8()
        if vote_byte not in (VOTE_ACCEPT, VOTE_REJECT):
            raise SerdeError(f"invalid vote byte {vote_byte}")
        accept = vote_byte == VOTE_ACCEPT
        conflict = None
        if not accept:
            presence = r.u8()
            if presence not in (0, 1):
                raise SerdeError(f"invalid conflict-presence byte {presence}")
            if presence == 1:
                conflict = TransactionLocator.decode(r)
        return Vote(locator, accept, conflict)
    if tag == _ST_VOTE_RANGE:
        rng = TransactionLocatorRange.decode(r)
        rng.verify()
        return VoteRange(rng)
    raise SerdeError(f"unknown statement tag {tag}")


# --- AuthoritySet -----------------------------------------------------------------


class AuthoritySet:
    """512-bit authority bitset (types.rs:116-121).

    Backed by a single Python int; insertion order does not matter and membership is O(1).
    Used by the committers' vote/certificate predicates and the threshold clock.
    """

    __slots__ = ("bits",)

    def __init__(self, bits: int = 0) -> None:
        self.bits = bits

    def insert(self, authority: AuthorityIndex) -> bool:
        """Returns False if already present (matches reference insert semantics)."""
        if authority >= MAX_COMMITTEE_SIZE:
            raise ValueError(f"authority {authority} out of range (max {MAX_COMMITTEE_SIZE})")
        mask = 1 << authority
        if self.bits & mask:
            return False
        self.bits |= mask
        return True

    def contains(self, authority: AuthorityIndex) -> bool:
        return bool(self.bits >> authority & 1)

    def present(self) -> Iterator[AuthorityIndex]:
        bits = self.bits
        idx = 0
        while bits:
            if bits & 1:
                yield idx
            bits >>= 1
            idx += 1

    def clear(self) -> None:
        self.bits = 0

    def copy(self) -> "AuthoritySet":
        return AuthoritySet(self.bits)

    def __len__(self) -> int:
        return bin(self.bits).count("1")

    def __eq__(self, other: object) -> bool:
        return isinstance(other, AuthoritySet) and self.bits == other.bits

    def __hash__(self) -> int:
        return hash(self.bits)


# --- StatementBlock ---------------------------------------------------------------


class StatementBlock:
    """An immutable DAG block (types.rs:93-114).

    Construction paths:
      * ``StatementBlock.new_genesis(authority)``           — round-0 anchor per authority
      * ``StatementBlock.build(...)`` + signer               — proposing (signs then digests)
      * ``StatementBlock.from_bytes(data)``                  — wire/storage decode

    The canonical serialization (``to_bytes``) is computed once and cached; digest =
    blake2b-256 over it (including signature), signed message = same encoding without
    the signature field (crypto.rs:77-84).
    """

    __slots__ = (
        "reference",
        "includes",
        "statements",
        "meta_creation_time_ns",
        "epoch_marker",
        "epoch",
        "signature",
        "_bytes",
        "_digest_trusted",
        # Share run-length spans precomputed by the native decoder (None on
        # locally built blocks): committee.shared_ranges was a 26M-iteration
        # interpreter loop per measurement window at saturation, re-walking
        # statements the C decoder had already visited.
        "_share_runs",
        # Concatenated 8-byte submission stamps, also decoder-precomputed
        # (the commit observer's latency input).
        "_stamps",
        # blake2b-256 over signed_bytes, precomputed by the batched native
        # digest path (from_bytes_many) or cached on first computation: the
        # signature verifier re-derives it per block otherwise.
        "_signed_digest",
    )

    def __init__(
        self,
        reference: BlockReference,
        includes: Tuple[BlockReference, ...],
        statements: Tuple[BaseStatement, ...],
        meta_creation_time_ns: int,
        epoch_marker: int,
        epoch: Epoch,
        signature: bytes,
        _bytes: Optional[bytes] = None,
        _digest_trusted: bool = False,
    ) -> None:
        self.reference = reference
        self.includes = includes
        self.statements = statements
        self.meta_creation_time_ns = meta_creation_time_ns
        self.epoch_marker = epoch_marker
        self.epoch = epoch
        self.signature = signature
        self._bytes = _bytes
        self._share_runs = None
        self._stamps = None
        self._signed_digest = None
        # True only on construction paths that DERIVED the reference digest
        # from the exact cached bytes (from_bytes): re-hashing the same
        # bytes in verify_structure would compare a hash with itself — at
        # ~1 GB/s over multi-MB blocks that tautology was a top-3 CPU cost
        # at fleet saturation.  Externally-assembled instances default to
        # False and keep the full check.
        self._digest_trusted = _digest_trusted

    # -- constructors --

    @staticmethod
    def _encode_content(
        w: Writer,
        authority: AuthorityIndex,
        round_: RoundNumber,
        includes: Sequence[BlockReference],
        statements: Sequence[BaseStatement],
        meta_creation_time_ns: int,
        epoch_marker: int,
        epoch: Epoch,
    ) -> None:
        w.u64(authority).u64(round_)
        w.u32(len(includes))
        for inc in includes:
            inc.encode(w)
        w.u32(len(statements))
        encode_statements(w, statements)
        w.u64(meta_creation_time_ns)
        w.u8(epoch_marker)
        w.u64(epoch)

    @classmethod
    def build(
        cls,
        authority: AuthorityIndex,
        round_: RoundNumber,
        includes: Iterable[BlockReference],
        statements: Iterable[BaseStatement],
        meta_creation_time_ns: int = 0,
        epoch_marker: int = EPOCH_OPEN,
        epoch: Epoch = 0,
        signer: Optional[crypto.Signer] = None,
    ) -> "StatementBlock":
        """Build and (optionally) sign a new block (crypto.rs:199-223 sign_block)."""
        includes = tuple(includes)
        statements = tuple(statements)
        w = Writer()
        cls._encode_content(
            w, authority, round_, includes, statements, meta_creation_time_ns,
            epoch_marker, epoch,
        )
        unsigned = w.finish()
        signed_digest = crypto.blake2b_256(unsigned)
        if signer is not None:
            signature = signer.sign(signed_digest)
        else:
            signature = crypto.SIGNATURE_NONE
        full = unsigned + signature
        digest = crypto.blake2b_256(full)
        ref = BlockReference(authority, round_, digest)
        block = cls(
            ref, includes, statements, meta_creation_time_ns, epoch_marker, epoch,
            signature, _bytes=full,
        )
        # The signing pre-hash IS signed_digest; keep it so self-verification
        # (and the TPU verifier's message input) skips a redundant hash pass.
        block._signed_digest = signed_digest
        return block

    @classmethod
    def new_genesis(cls, authority: AuthorityIndex, epoch: Epoch = 0) -> "StatementBlock":
        """Round-0 anchor block; never signed, never verified (committee.rs:98)."""
        return cls.build(authority, GENESIS_ROUND, (), (), epoch=epoch)

    # -- serialization --

    def to_bytes(self) -> bytes:
        if self._bytes is None:
            w = Writer()
            self._encode_content(
                w, self.reference.authority, self.reference.round, self.includes,
                self.statements, self.meta_creation_time_ns, self.epoch_marker, self.epoch,
            )
            w.fixed(self.signature)
            self._bytes = w.finish()
        return self._bytes

    def signed_bytes(self) -> bytes:
        """The encoding covered by the signature: everything but the signature itself."""
        return self.to_bytes()[: -crypto.SIGNATURE_SIZE]

    def signed_digest(self) -> bytes:
        """blake2b-256 of signed_bytes — the 32-byte message Ed25519 actually signs.

        This fixed-width message is what makes the TPU batch verifier's SHA-512 input
        a constant shape (R || A || 32-byte digest = one 128-byte SHA-512 block).
        Cached: the batched native decode path (``from_bytes_many``) precomputes it
        alongside the block digest, one GIL round-trip per frame instead of one
        hash pass per verified block.
        """
        if self._signed_digest is None:
            self._signed_digest = crypto.blake2b_256(self.signed_bytes())
        return self._signed_digest

    # Decode memo, enabled ONLY by the deterministic simulator
    # (runtime/simulated.py): all N simulated validators live in one process
    # and each decodes the same serialized block once — memoizing turns the
    # sim's dominant cost (N redundant decodes per block) into one.  Blocks
    # are immutable after construction, so instance sharing across in-process
    # nodes is safe.  Never enabled on real nodes (each is its own process).
    _decode_memo: Optional[dict] = None
    _DECODE_MEMO_CAP = 8192

    @classmethod
    def enable_decode_memo(cls) -> None:
        cls._decode_memo = {}

    @classmethod
    def disable_decode_memo(cls) -> None:
        cls._decode_memo = None

    @classmethod
    def from_bytes(cls, data) -> "StatementBlock":
        """Single-pass inline decoder over ``bytes`` or any buffer view.

        Wire format identical to the Reader-based encoders above; the
        per-field Reader method calls dominated the receive-path profile at
        load (millions of ``_take`` calls), so this path unpacks with local
        offsets.  Error semantics match: any truncation, bad tag, invalid
        vote byte, or trailing garbage raises SerdeError.

        Memoryview inputs (the zero-copy receive path: block payloads are
        sub-views over a connection's reusable frame buffer) are
        materialized EXACTLY ONCE here — the copy that becomes the cached
        canonical serialization the digest and signature cover; nothing
        downstream retains a view of the caller's buffer."""
        if type(data) is not bytes:  # memoryview/mmap callers
            data = bytes(data)
        memo = cls._decode_memo
        if memo is not None:
            cached = memo.get(data)
            if cached is not None:
                return cached
        if _native_decode is not None:
            # Native single-pass decoder (native/mysticeti_native.cpp):
            # identical wire format and rejection cases, differentially
            # tested in test_serde_property.py.  ~5 MB blocks with ~10k
            # share statements cost the interpreter loop ~77 ms; the C
            # walk builds the same frozen-dataclass objects in a fraction.
            try:
                decoded = _native_decode(data)
            except ValueError as exc:
                raise SerdeError(str(exc)) from None
            # Unpack OUTSIDE the except: an arity mismatch here means a
            # stale compiled extension (build skew) and must fail loudly,
            # not masquerade as malformed wire data.
            (authority, round_, includes, statements, meta_ns,
             epoch_marker, epoch, signature, share_runs, stamps) = decoded
            digest = crypto.blake2b_256(data)
            block = cls(
                BlockReference(authority, round_, digest), tuple(includes),
                tuple(statements), meta_ns, epoch_marker, epoch, signature,
                _bytes=bytes(data), _digest_trusted=True,
            )
            block._share_runs = share_runs
            block._stamps = stamps
            if memo is not None:
                if len(memo) >= cls._DECODE_MEMO_CAP:
                    memo.clear()
                memo[block._bytes] = block
            return block
        try:
            n = len(data)
            authority, round_ = _U64X2.unpack_from(data, 0)
            pos = 16
            (cnt,) = _U32_AT.unpack_from(data, pos)
            pos += 4
            includes = []
            for _ in range(cnt):
                a, rr = _U64X2.unpack_from(data, pos)
                digest = bytes(data[pos + 16 : pos + 48])
                if len(digest) != crypto.DIGEST_SIZE:
                    raise SerdeError("truncated input: include digest")
                includes.append(BlockReference(a, rr, digest))
                pos += 48
            (cnt,) = _U32_AT.unpack_from(data, pos)
            pos += 4
            statements = []
            for _ in range(cnt):
                tag = data[pos]
                pos += 1
                if tag == _ST_SHARE:
                    (ln,) = _U32_AT.unpack_from(data, pos)
                    pos += 4
                    end = pos + ln
                    if end > n:
                        raise SerdeError("truncated input: share payload")
                    statements.append(Share(bytes(data[pos:end])))
                    pos = end
                elif tag == _ST_VOTE:
                    a, rr = _U64X2.unpack_from(data, pos)
                    digest = bytes(data[pos + 16 : pos + 48])
                    if len(digest) != crypto.DIGEST_SIZE:
                        raise SerdeError("truncated input: vote digest")
                    (off,) = _U64_AT.unpack_from(data, pos + 48)
                    locator = TransactionLocator(BlockReference(a, rr, digest), off)
                    pos += 56
                    vote_byte = data[pos]
                    pos += 1
                    if vote_byte not in (VOTE_ACCEPT, VOTE_REJECT):
                        raise SerdeError(f"invalid vote byte {vote_byte}")
                    accept = vote_byte == VOTE_ACCEPT
                    conflict = None
                    if not accept:
                        presence = data[pos]
                        pos += 1
                        if presence not in (0, 1):
                            raise SerdeError(
                                f"invalid conflict-presence byte {presence}"
                            )
                        if presence == 1:
                            a2, rr2 = _U64X2.unpack_from(data, pos)
                            digest2 = bytes(data[pos + 16 : pos + 48])
                            if len(digest2) != crypto.DIGEST_SIZE:
                                raise SerdeError("truncated input: conflict")
                            (off2,) = _U64_AT.unpack_from(data, pos + 48)
                            conflict = TransactionLocator(
                                BlockReference(a2, rr2, digest2), off2
                            )
                            pos += 56
                    statements.append(Vote(locator, accept, conflict))
                elif tag == _ST_VOTE_RANGE:
                    a, rr = _U64X2.unpack_from(data, pos)
                    digest = bytes(data[pos + 16 : pos + 48])
                    if len(digest) != crypto.DIGEST_SIZE:
                        raise SerdeError("truncated input: range digest")
                    s, e = _U64X2.unpack_from(data, pos + 48)
                    rng = TransactionLocatorRange(BlockReference(a, rr, digest), s, e)
                    rng.verify()
                    statements.append(VoteRange(rng))
                    pos += 64
                else:
                    raise SerdeError(f"unknown statement tag {tag}")
            (meta_ns,) = _U64_AT.unpack_from(data, pos)
            pos += 8
            epoch_marker = data[pos]
            pos += 1
            (epoch,) = _U64_AT.unpack_from(data, pos)
            pos += 8
            signature = bytes(data[pos : pos + crypto.SIGNATURE_SIZE])
            if len(signature) != crypto.SIGNATURE_SIZE:
                raise SerdeError("truncated input: signature")
            pos += crypto.SIGNATURE_SIZE
            if pos != n:
                raise SerdeError(f"trailing garbage: {n - pos} bytes")
        except struct.error:
            raise SerdeError("truncated input") from None
        except IndexError:
            raise SerdeError("truncated input") from None
        digest = crypto.blake2b_256(data)
        ref = BlockReference(authority, round_, digest)
        block = cls(
            ref, tuple(includes), tuple(statements), meta_ns, epoch_marker,
            epoch, signature, _bytes=bytes(data), _digest_trusted=True,
        )
        if memo is not None:
            if len(memo) >= cls._DECODE_MEMO_CAP:
                memo.clear()  # bulk FIFO: sims re-see bytes within a window
            memo[block._bytes] = block
        return block

    @classmethod
    def from_bytes_many(cls, raws) -> List[Optional["StatementBlock"]]:
        """Batched decode of N serialized blocks; ``None`` marks a malformed entry.

        The receive-path sibling of ``from_bytes`` for whole-frame ingest
        (net_sync._decode_fresh): all N block digests AND signature
        pre-hashes are computed in ONE native call with the GIL released
        (``block_digests``), so a K-block frame costs one GIL round-trip
        instead of K hashlib calls.  Falls back to per-raw ``from_bytes``
        when the extension is absent or the sim decode memo is active —
        the memo path must stay byte-identical (and instance-identical)
        under seeded simulation.
        """
        if _native_decode is None or _native_block_digests is None \
                or cls._decode_memo is not None:
            out = []
            for data in raws:
                try:
                    out.append(cls.from_bytes(data))
                except SerdeError:
                    out.append(None)
            return out
        datas = [data if type(data) is bytes else bytes(data) for data in raws]
        decoded = []
        good = []
        for data in datas:
            try:
                decoded.append(_native_decode(data))
                good.append(data)
            except ValueError:
                decoded.append(None)
        digests = iter(_native_block_digests(good))
        out: List[Optional["StatementBlock"]] = []
        for data, dec in zip(datas, decoded):
            if dec is None:
                out.append(None)
                continue
            # Unpack OUTSIDE any except (same contract as from_bytes): an
            # arity mismatch means extension build skew, not bad wire data.
            (authority, round_, includes, statements, meta_ns,
             epoch_marker, epoch, signature, share_runs, stamps) = dec
            digest, signed_digest = next(digests)
            block = cls(
                BlockReference(authority, round_, digest), tuple(includes),
                tuple(statements), meta_ns, epoch_marker, epoch, signature,
                _bytes=data, _digest_trusted=True,
            )
            block._share_runs = share_runs
            block._stamps = stamps
            block._signed_digest = signed_digest
            out.append(block)
        return out

    # -- accessors --

    def author(self) -> AuthorityIndex:
        return self.reference.authority

    def round(self) -> RoundNumber:
        return self.reference.round

    def digest(self) -> bytes:
        return self.reference.digest

    def author_round(self) -> Tuple[AuthorityIndex, RoundNumber]:
        return self.reference.author_round()

    def epoch_changed(self) -> bool:
        return self.epoch_marker != EPOCH_OPEN

    def shared_transactions(self) -> Iterator[Tuple["TransactionLocator", bytes]]:
        """(locator, payload) for every Share statement (types.rs shared_transactions)."""
        for offset, st in enumerate(self.statements):
            if isinstance(st, Share):
                yield TransactionLocator(self.reference, offset), st.transaction

    def shared_transaction_stamps(self) -> bytes:
        """Concatenated first-8-byte prefixes of every Share payload — the
        benchmark submission stamps the commit observer's latency metrics
        read.  A dedicated path because ``shared_transactions`` constructs a
        locator per transaction: at saturation that was ~1M frozen-dataclass
        builds per reporting window, discarded immediately (round-5 profile).
        """
        if self._stamps is not None:  # decoder-precomputed (wire blocks)
            return self._stamps
        out = []
        for st in self.statements:
            if isinstance(st, Share):
                t = st.transaction
                # Sub-8-byte payloads carry no stamp: emit ZERO so the
                # ts==0 "unstamped" guard downstream zeroes their latency
                # (padding real bytes would decode as a denormal float).
                out.append(t[:8] if len(t) >= 8 else b"\x00" * 8)
        return b"".join(out)

    # -- verification (types.rs:315-376) --

    def verify_structure(self, committee) -> None:
        """Consensus-rule checks minus the signature: digest match, epoch match, known
        author, include-round monotonicity, vote-range bounds, threshold-clock validity.

        The signature check itself is intentionally *separate* (``signed_digest`` +
        authority key) so the network layer can strip it out of the serial path and
        batch it on TPU; ``verify`` below is the all-in-one CPU equivalent.
        """
        from .threshold_clock import threshold_clock_valid_non_genesis

        if not self._digest_trusted:
            data = self.to_bytes()
            if crypto.blake2b_256(data) != self.reference.digest:
                raise VerificationError(
                    f"digest mismatch for {self.reference!r}"
                )
        if not committee.accepts_epoch(self.epoch):
            raise VerificationError(
                f"block epoch {self.epoch} != committee epoch {committee.epoch}"
            )
        if not committee.known_authority(self.author()):
            raise VerificationError(f"unknown block author {self.author()}")
        if self.round() == GENESIS_ROUND:
            raise VerificationError("genesis block should not go through verification")
        for include in self.includes:
            if not committee.known_authority(include.authority):
                raise VerificationError(f"include {include!r} references unknown authority")
            if include.round >= self.round():
                raise VerificationError(
                    f"include {include!r} round >= own round {self.round()}"
                )
        for st in self.statements:
            if isinstance(st, VoteRange):
                st.range.verify()
        if not threshold_clock_valid_non_genesis(self, committee):
            raise VerificationError(f"threshold clock not valid for {self.reference!r}")

    def verify(self, committee) -> None:
        """Full verification including the Ed25519 signature (types.rs:315-376 +
        crypto.rs:174-189).  The TPU path runs verify_structure on host and the
        signature equation on device."""
        self.verify_structure(committee)
        pub_key = committee.get_public_key(self.author())
        if not pub_key.verify(self.signature, self.signed_digest()):
            raise VerificationError(f"signature verification failed for {self.reference!r}")

    def __repr__(self) -> str:
        return f"{self.reference!r}([{','.join(repr(i) for i in self.includes)}])"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, StatementBlock) and self.reference == other.reference

    def __hash__(self) -> int:
        return hash(self.reference)


class VerificationError(ValueError):
    """A block failed consensus-rule or signature verification."""


# Native decoder wiring: register the statement/reference classes with the
# C++ extension once, then resolve the fast path from_bytes dispatches to.
from .native import native as _native_mod  # noqa: E402

_native_decode = None
_native_block_digests = None
if _native_mod is not None and hasattr(_native_mod, "decode_block"):
    _native_mod.decode_register(
        BlockReference, Share, Vote, VoteRange, TransactionLocator,
        TransactionLocatorRange,
    )
    _native_decode = _native_mod.decode_block
if _native_mod is not None and hasattr(_native_mod, "block_digests"):
    # Batched (digest, signed-prehash) pairs — differentially pinned against
    # crypto.blake2b_256 by the data-plane parity corpus.
    _native_block_digests = _native_mod.block_digests
