"""Prometheus scrape parsing and benchmark aggregation.

Capability parity with ``orchestrator/src/measurement.rs``:

* ``Measurement.from_prometheus`` (:45-106) — extract the benchmark-defining
  series {buckets, sum, count, squared_sum} for a workload label plus
  ``benchmark_duration``.
* throughput = count / duration (:109-117); average latency = sum/count;
  stdev = sqrt(squared_sum/count - avg^2) (:121-142).
* ``MeasurementsCollection`` (:163-281) — per-scraper time series, aggregation
  across validators, JSON save/load, display summary (:283-360).
"""
from __future__ import annotations

import json
import math
import os
import re
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

_RE_LINE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+([0-9.eE+-]+|NaN)")


def _labels(raw: Optional[str]) -> Dict[str, str]:
    if not raw:
        return {}
    out = {}
    for part in raw.strip("{}").split(","):
        if not part:
            continue
        k, _, v = part.partition("=")
        out[k] = v.strip('"')
    return out


def iter_series(text: str):
    """Parse prometheus exposition text into ``(name, labels, value)``
    tuples — the one scrape parser shared by the benchmark measurements
    below and the fleet health plane (``health.py``, ``tools/fleetmon.py``)."""
    for line in text.splitlines():
        match = _RE_LINE.match(line)
        if not match:
            continue
        name, raw_labels, raw_value = match.groups()
        try:
            value = float(raw_value)
        except ValueError:
            continue
        yield name, _labels(raw_labels), value


@dataclass
class Measurement:
    """One scrape's benchmark numbers for one workload label."""

    timestamp_s: float = 0.0
    benchmark_duration_s: float = 0.0
    buckets: Dict[str, float] = field(default_factory=dict)
    sum_s: float = 0.0
    count: int = 0
    squared_sum_s: float = 0.0

    @classmethod
    def from_prometheus(cls, text: str, workload: str = "shared") -> "Measurement":
        m = cls(timestamp_s=time.time())
        for name, labels, value in iter_series(text):
            if name == "benchmark_duration_total" or name == "benchmark_duration":
                m.benchmark_duration_s = value
            elif labels.get("workload") != workload:
                continue
            elif name == "latency_s_bucket":
                m.buckets[labels.get("le", "")] = value
            elif name == "latency_s_sum":
                m.sum_s = value
            elif name == "latency_s_count":
                m.count = int(value)
            elif name in ("latency_squared_s_total", "latency_squared_s"):
                m.squared_sum_s = value
        return m

    def tps(self) -> float:
        if self.benchmark_duration_s == 0:
            return 0.0
        return self.count / self.benchmark_duration_s

    def avg_latency_s(self) -> float:
        return self.sum_s / self.count if self.count else 0.0

    def stdev_latency_s(self) -> float:
        """sqrt(E[X^2] - E[X]^2) (measurement.rs:121-142)."""
        if not self.count:
            return 0.0
        first = self.squared_sum_s / self.count
        second = self.avg_latency_s() ** 2
        return math.sqrt(max(0.0, first - second))

    def to_dict(self) -> dict:
        return {
            "timestamp_s": self.timestamp_s,
            "benchmark_duration_s": self.benchmark_duration_s,
            "buckets": self.buckets,
            "sum_s": self.sum_s,
            "count": self.count,
            "squared_sum_s": self.squared_sum_s,
        }

    @classmethod
    def from_dict(cls, raw: dict) -> "Measurement":
        return cls(**raw)


class MeasurementsCollection:
    """Per-scraper measurement series + cross-validator aggregation
    (measurement.rs:163-360)."""

    def __init__(self, parameters: Optional[dict] = None) -> None:
        self.parameters = parameters or {}
        self.scrapers: Dict[str, List[Measurement]] = {}
        # Host-level series (node_exporter equivalent, hostmon.py): one
        # sample per scrape tick, so saturation is attributable to the host
        # (core-steal between co-located validators) and not just the node.
        self.host_samples: List[dict] = []
        # Fleet health timeline (health.cluster_snapshot per scrape tick):
        # every perf artifact ships with its own diagnosis — quorum
        # participation, stragglers, commit skew, SLO alerts.
        self.health_samples: List[dict] = []

    def add(self, scraper_id: str, measurement: Measurement) -> None:
        self.scrapers.setdefault(scraper_id, []).append(measurement)

    def add_host_sample(self, sample: dict) -> None:
        self.host_samples.append(sample)

    def add_health_sample(self, sample: dict) -> None:
        self.health_samples.append(sample)

    def _last_measurements(self) -> List[Measurement]:
        return [series[-1] for series in self.scrapers.values() if series]

    def benchmark_duration(self) -> float:
        last = self._last_measurements()
        return max((m.benchmark_duration_s for m in last), default=0.0)

    def aggregate_tps(self) -> float:
        """MAX of per-validator tps over the common duration
        (measurement.rs:236-250 takes ``.map(tps).max()``): every validator
        observes every committed shared tx, so per-scraper counts are N
        views of the same total — summing them would report N× the system
        throughput."""
        duration = self.benchmark_duration()
        if duration == 0:
            return 0.0
        return max(
            (m.count / duration for m in self._last_measurements()), default=0.0
        )

    def aggregate_average_latency_s(self) -> float:
        """Mean of per-validator average latencies (measurement.rs:253-262)."""
        last = [m for m in self._last_measurements() if m.count]
        if not last:
            return 0.0
        return sum(m.avg_latency_s() for m in last) / len(last)

    def aggregate_stdev_latency_s(self) -> float:
        """MAX of per-validator latency stdevs (measurement.rs:265-272)."""
        return max(
            (m.stdev_latency_s() for m in self._last_measurements()), default=0.0
        )

    def host_summary(self) -> Optional[dict]:
        """Aggregate the host series: system cpu avg/max, per-process cpu
        averages, net throughput over the sampled span.  None without
        samples (e.g. a runner that cannot observe its hosts)."""
        samples = self.host_samples
        if not samples:
            return None
        # SshRunner samples nest per-host dicts under "hosts" (one fleet
        # sample covers N machines); flatten them into the same stream so the
        # aggregation below reads both shapes.
        flat: List[dict] = []
        for s in samples:
            if "hosts" in s:
                flat.extend(s["hosts"].values())
            else:
                flat.append(s)
        n_raw = len(samples)
        samples = flat
        cpu = [s["cpu_pct"] for s in samples if s.get("cpu_pct") is not None]
        per: Dict[str, List[float]] = {}
        for s in samples:
            for name, p in (s.get("per_process") or {}).items():
                if p.get("cpu_pct") is not None:
                    per.setdefault(name, []).append(p["cpu_pct"])
        out: dict = {"samples": n_raw}
        if cpu:
            out["cpu_pct_avg"] = round(sum(cpu) / len(cpu), 1)
            out["cpu_pct_max"] = round(max(cpu), 1)
        loads = [s["load_1m"] for s in samples if "load_1m" in s]
        if loads:
            out["load_1m_max"] = round(max(loads), 2)
        if per:
            out["per_process_cpu_pct_avg"] = {
                k: round(sum(v) / len(v), 1) for k, v in sorted(per.items())
            }
        span = samples[-1].get("timestamp_s", 0) - samples[0].get(
            "timestamp_s", 0
        )
        if span > 0 and "net_bytes_recv" in samples[-1]:
            out["net_recv_mb_s"] = round(
                (samples[-1]["net_bytes_recv"] - samples[0]["net_bytes_recv"])
                / span / 2**20,
                2,
            )
            out["net_sent_mb_s"] = round(
                (samples[-1]["net_bytes_sent"] - samples[0]["net_bytes_sent"])
                / span / 2**20,
                2,
            )
        return out

    def health_summary(self) -> Optional[dict]:
        """Aggregate the health timeline: the run's worst moments plus the
        final snapshot — enough for an artifact reader to judge whether a
        perf number was taken on a healthy fleet without replaying the
        whole timeline.  None when the health plane never sampled."""
        samples = self.health_samples
        if not samples:
            return None
        last = samples[-1]
        alert_totals: Dict[str, float] = dict(
            last.get("slo_alert_totals") or {}
        )
        return {
            "samples": len(samples),
            "final_status": last.get("status"),
            "quorum_participation_min": min(
                s.get("quorum_participation", 0.0) for s in samples
            ),
            "commit_skew_rounds_max": max(
                s.get("commit_skew_rounds", 0) for s in samples
            ),
            "unreachable_ticks": sum(
                1 for s in samples if s.get("unreachable")
            ),
            "slo_alert_totals": alert_totals,
            "worst_straggler": max(
                (
                    (lag, a)
                    for s in samples
                    for a, lag in (s.get("straggler_score") or {}).items()
                ),
                default=None,
            ),
        }

    def save(self, path: str) -> None:
        data = {
            "parameters": self.parameters,
            "scrapers": {
                k: [m.to_dict() for m in v] for k, v in self.scrapers.items()
            },
            "host_samples": self.host_samples,
            "health_samples": self.health_samples,
        }
        with open(path, "w") as f:
            json.dump(data, f, indent=1)

    @classmethod
    def load(cls, path: str) -> "MeasurementsCollection":
        with open(path) as f:
            raw = json.load(f)
        c = cls(raw.get("parameters"))
        for k, series in raw.get("scrapers", {}).items():
            c.scrapers[k] = [Measurement.from_dict(m) for m in series]
        c.host_samples = raw.get("host_samples", [])
        c.health_samples = raw.get("health_samples", [])
        return c

    def display_summary(self) -> str:
        lines = [
            "Benchmark summary",
            "-----------------",
            f" duration:      {self.benchmark_duration():.0f} s",
            f" tps:           {self.aggregate_tps():.0f} tx/s",
            f" avg latency:   {self.aggregate_average_latency_s() * 1000:.0f} ms",
            f" stdev latency: {self.aggregate_stdev_latency_s() * 1000:.0f} ms",
        ]
        host = self.host_summary()
        if host and "cpu_pct_avg" in host:
            lines.append(
                f" host cpu:      {host['cpu_pct_avg']:.0f}% avg /"
                f" {host['cpu_pct_max']:.0f}% max"
            )
        health = self.health_summary()
        if health is not None:
            alerts = sum(health["slo_alert_totals"].values())
            lines.append(
                f" fleet health:  {health['final_status']} "
                f"(participation >= {health['quorum_participation_min']:.2f},"
                f" commit skew <= {health['commit_skew_rounds_max']},"
                f" {alerts:.0f} SLO alert(s))"
            )
        return "\n".join(lines)
