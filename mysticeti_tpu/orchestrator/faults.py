"""Fault-injection schedules for benchmark runs.

Capability parity with ``orchestrator/src/faults.rs``:

* ``FaultsType``: no faults, ``Permanent`` (kill ``faults`` nodes once), or
  ``CrashRecovery`` (cycle kills/boots on an interval) (:14-22).
* ``CrashRecoverySchedule.update`` — steps by thirds of the fault budget:
  kills grow 1/3, 2/3, 3/3 then recover in the same steps (:104-160).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Set, Tuple


@dataclass
class FaultsType:
    kind: str = "none"  # none | permanent | crash_recovery
    faults: int = 0
    interval_s: float = 60.0

    @classmethod
    def none(cls) -> "FaultsType":
        return cls()

    @classmethod
    def permanent(cls, faults: int) -> "FaultsType":
        return cls("permanent", faults)

    @classmethod
    def crash_recovery(cls, faults: int, interval_s: float = 60.0) -> "FaultsType":
        return cls("crash_recovery", faults, interval_s)

    def describe(self) -> str:
        if self.kind == "none" or self.faults == 0:
            return "0 faults"
        if self.kind == "permanent":
            return f"{self.faults} permanent faults"
        return f"{self.faults} crash-recovery faults every {self.interval_s:.0f}s"

    def to_dict(self) -> dict:
        return {"kind": self.kind, "faults": self.faults, "interval_s": self.interval_s}


class CrashRecoverySchedule:
    """Stateful kill/boot stepper (faults.rs:104-160).

    Each ``update`` returns (to_kill, to_boot) node index lists.  The dead set
    grows by thirds of the fault budget until all ``faults`` nodes are down,
    then recovers in the same pattern — exercising WAL recovery under load.
    """

    def __init__(self, faults: FaultsType, committee_size: int) -> None:
        self.faults = faults
        self.committee_size = committee_size
        self.dead: Set[int] = set()
        self._step = 0

    def update(self) -> Tuple[List[int], List[int]]:
        if self.faults.kind == "none" or self.faults.faults == 0:
            return [], []
        budget = min(self.faults.faults, self.committee_size - 1)
        if self.faults.kind == "permanent":
            if self.dead:
                return [], []
            to_kill = list(range(self.committee_size - budget, self.committee_size))
            self.dead.update(to_kill)
            return to_kill, []

        third = max(1, budget // 3)
        killing_phase = (self._step // 3) % 2 == 0
        self._step += 1
        if killing_phase and len(self.dead) < budget:
            start = self.committee_size - budget
            candidates = [
                i
                for i in range(start, self.committee_size)
                if i not in self.dead
            ][:third]
            self.dead.update(candidates)
            return candidates, []
        if not killing_phase and self.dead:
            to_boot = sorted(self.dead)[:third]
            for b in to_boot:
                self.dead.discard(b)
            return [], to_boot
        return [], []
