"""Benchmark settings model: the orchestrator's persisted configuration.

Capability parity with ``orchestrator/src/settings.rs`` (:53-96) minus the
cloud-SDK fields the environment rules out: runner selection (local
subprocesses vs an ssh fleet), host list, working/results directories, load
generation defaults.  JSON on disk so a testbed description can be checked
in and shared (the reference's ``settings.json``).
"""
from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class Settings:
    runner: str = "local"  # "local" | "ssh"
    hosts: List[str] = field(default_factory=list)  # ssh: 1 per node, may be user@host
    remote_repo: str = "."  # remote checkout path for the ssh runner
    repo_url: str = ""  # clone source for `fleet update` (settings.rs repo field)
    working_dir: str = "benchmark-fleet"
    results_dir: str = "benchmark-results"
    tps_per_node: int = 100
    transaction_size: int = 512
    verifier: str = "cpu"
    # Testbed provisioning (settings.rs cloud_provider/token_file): "static"
    # claims hosts from ``hosts``; "rest" provisions via the JSON-REST cloud
    # client; "aws" via the EC2-surface client (providers.py — regions×AMIs,
    # security group, EC2 lifecycle states).  The API token is read from the
    # env var named by ``provider_token_env`` so checked-in settings never
    # carry secrets.
    provider: str = "static"  # "static" | "rest" | "aws"
    provider_base_url: str = ""
    provider_token_env: str = "CLOUD_API_TOKEN"
    provider_region: str = "ewr"
    provider_plan: str = "vc2-16c-64gb"
    # aws provider: region -> AMI map (settings.rs carries the same pairing
    # for its aws testbeds), instance type, and the ensured security group.
    provider_amis: Dict[str, str] = field(default_factory=dict)
    provider_instance_type: str = "m5d.8xlarge"
    provider_security_group: str = "mysticeti-tpu"

    def validate(self) -> None:
        if self.runner not in ("local", "ssh"):
            raise ValueError(f"unknown runner {self.runner!r}")
        if self.runner == "ssh" and not self.hosts:
            raise ValueError("ssh runner requires at least one host")
        if self.provider not in ("static", "rest", "aws"):
            raise ValueError(f"unknown provider {self.provider!r}")
        if self.provider in ("rest", "aws") and not self.provider_base_url:
            raise ValueError(
                f"{self.provider} provider requires provider_base_url"
            )
        if self.provider == "aws" and not self.provider_amis:
            raise ValueError(
                "aws provider requires provider_amis (region -> AMI)"
            )
        if (
            self.provider == "aws"
            and self.provider_region != "ewr"  # the untouched vultr default
            and self.provider_region not in self.provider_amis
        ):
            # An explicitly-set region with no AMI would silently fall back
            # to the first configured region — a whole fleet in the wrong
            # continent.  Fail the config loudly instead.
            raise ValueError(
                f"provider_region {self.provider_region!r} has no entry in "
                f"provider_amis (configured: {sorted(self.provider_amis)})"
            )

    def make_provider(self, state_path: Optional[str] = None,
                      transport=None):
        """Instantiate the configured ServerProvider (testbed.py seam)."""
        self.validate()
        if self.provider == "rest":
            from .providers import RestCloudProvider

            return RestCloudProvider(
                self.provider_base_url,
                token=os.environ.get(self.provider_token_env, ""),
                region=self.provider_region,
                plan=self.provider_plan,
                transport=transport,
            )
        if self.provider == "aws":
            from .providers import Ec2Provider

            return Ec2Provider(
                self.provider_base_url,
                token=os.environ.get(self.provider_token_env, ""),
                amis=self.provider_amis,
                instance_type=self.provider_instance_type,
                security_group=self.provider_security_group,
                default_region=(
                    self.provider_region
                    if self.provider_region in self.provider_amis
                    else None
                ),
                transport=transport,
            )
        from .testbed import StaticProvider

        return StaticProvider(self.hosts, state_path=state_path)

    def make_runner(self):
        """Instantiate the configured Runner (runner.py)."""
        self.validate()
        if self.runner == "local":
            from .runner import LocalProcessRunner

            return LocalProcessRunner(
                self.working_dir,
                tps_per_node=self.tps_per_node,
                transaction_size=self.transaction_size,
                verifier=self.verifier,
            )
        from .runner import SshRunner

        return SshRunner(
            self.hosts,
            remote_repo=self.remote_repo,
            working_dir=self.working_dir,
            tps_per_node=self.tps_per_node,
            verifier=self.verifier,
        )

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(dataclasses.asdict(self), f, indent=2)
            f.write("\n")

    @classmethod
    def load(cls, path: str) -> "Settings":
        with open(path) as f:
            raw = json.load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        settings = cls(**{k: v for k, v in raw.items() if k in known})
        settings.validate()
        return settings
