"""Benchmark settings model: the orchestrator's persisted configuration.

Capability parity with ``orchestrator/src/settings.rs`` (:53-96) minus the
cloud-SDK fields the environment rules out: runner selection (local
subprocesses vs an ssh fleet), host list, working/results directories, load
generation defaults.  JSON on disk so a testbed description can be checked
in and shared (the reference's ``settings.json``).
"""
from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class Settings:
    runner: str = "local"  # "local" | "ssh"
    hosts: List[str] = field(default_factory=list)  # ssh: 1 per node, may be user@host
    remote_repo: str = "."  # remote checkout path for the ssh runner
    repo_url: str = ""  # clone source for `fleet update` (settings.rs repo field)
    working_dir: str = "benchmark-fleet"
    results_dir: str = "benchmark-results"
    tps_per_node: int = 100
    transaction_size: int = 512
    verifier: str = "cpu"
    # Testbed provisioning (settings.rs cloud_provider/token_file): "static"
    # claims hosts from ``hosts``; "rest" provisions via the JSON-REST cloud
    # client (providers.py).  The API token is read from the env var named
    # by ``provider_token_env`` so checked-in settings never carry secrets.
    provider: str = "static"  # "static" | "rest"
    provider_base_url: str = ""
    provider_token_env: str = "CLOUD_API_TOKEN"
    provider_region: str = "ewr"
    provider_plan: str = "vc2-16c-64gb"

    def validate(self) -> None:
        if self.runner not in ("local", "ssh"):
            raise ValueError(f"unknown runner {self.runner!r}")
        if self.runner == "ssh" and not self.hosts:
            raise ValueError("ssh runner requires at least one host")
        if self.provider not in ("static", "rest"):
            raise ValueError(f"unknown provider {self.provider!r}")
        if self.provider == "rest" and not self.provider_base_url:
            raise ValueError("rest provider requires provider_base_url")

    def make_provider(self, state_path: Optional[str] = None,
                      transport=None):
        """Instantiate the configured ServerProvider (testbed.py seam)."""
        self.validate()
        if self.provider == "rest":
            from .providers import RestCloudProvider

            return RestCloudProvider(
                self.provider_base_url,
                token=os.environ.get(self.provider_token_env, ""),
                region=self.provider_region,
                plan=self.provider_plan,
                transport=transport,
            )
        from .testbed import StaticProvider

        return StaticProvider(self.hosts, state_path=state_path)

    def make_runner(self):
        """Instantiate the configured Runner (runner.py)."""
        self.validate()
        if self.runner == "local":
            from .runner import LocalProcessRunner

            return LocalProcessRunner(
                self.working_dir,
                tps_per_node=self.tps_per_node,
                transaction_size=self.transaction_size,
                verifier=self.verifier,
            )
        from .runner import SshRunner

        return SshRunner(
            self.hosts,
            remote_repo=self.remote_repo,
            working_dir=self.working_dir,
            tps_per_node=self.tps_per_node,
            verifier=self.verifier,
        )

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(dataclasses.asdict(self), f, indent=2)
            f.write("\n")

    @classmethod
    def load(cls, path: str) -> "Settings":
        with open(path) as f:
            raw = json.load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        settings = cls(**{k: v for k, v in raw.items() if k in known})
        settings.validate()
        return settings
