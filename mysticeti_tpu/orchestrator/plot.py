"""Latency-throughput plots from measurement collections.

Capability parity with ``orchestrator/assets/plot.py`` (:19-50): the classic
L-graph — aggregate throughput on x, average latency on y, one point per
benchmark run, one series per (nodes, faults) configuration — written as both
PNG and a plain-text data file so headless environments still get numbers.
"""
from __future__ import annotations

import os
from collections import defaultdict
from typing import Iterable, List

from .measurement import MeasurementsCollection


def _series_key(collection: MeasurementsCollection) -> str:
    p = collection.parameters or {}
    nodes = p.get("nodes", "?")
    faults = (p.get("faults") or {}).get("faults", 0)
    suffix = f" ({faults} faults)" if faults else ""
    return f"{nodes} nodes{suffix}"


def plot_latency_throughput(
    collections: Iterable[MeasurementsCollection],
    out_path: str,
) -> List[str]:
    """Write <out_path>.png (if matplotlib is usable) and <out_path>.txt.

    Returns the list of files written.
    """
    series = defaultdict(list)
    for c in collections:
        series[_series_key(c)].append(
            (c.aggregate_tps(), c.aggregate_average_latency_s())
        )
    for points in series.values():
        points.sort()

    parent = os.path.dirname(os.path.abspath(out_path))
    os.makedirs(parent, exist_ok=True)
    written = []
    txt_path = out_path + ".txt"
    with open(txt_path, "w") as f:
        f.write("# series\ttps\tavg_latency_s\n")
        for name, points in sorted(series.items()):
            for tps, lat in points:
                f.write(f"{name}\t{tps:.1f}\t{lat:.4f}\n")
    written.append(txt_path)

    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except Exception:
        return written

    fig, ax = plt.subplots(figsize=(7, 4.5))
    for name, points in sorted(series.items()):
        xs = [p[0] for p in points]
        ys = [p[1] for p in points]
        ax.plot(xs, ys, marker="o", label=name)
    ax.set_xlabel("throughput (tx/s)")
    ax.set_ylabel("avg latency (s)")
    ax.set_title("latency vs throughput")
    ax.grid(True, alpha=0.3)
    if series:
        ax.legend()
    png_path = out_path + ".png"
    fig.savefig(png_path, dpi=120, bbox_inches="tight")
    plt.close(fig)
    written.append(png_path)
    return written
