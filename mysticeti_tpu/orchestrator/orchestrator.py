"""The benchmark lifecycle driver.

Capability parity with ``orchestrator/src/orchestrator.rs`` ``run_benchmarks``
(:664-727) and the scrape/fault loop (:523-597): for each BenchmarkParameters
from the generator — cleanup, configure (genesis), boot nodes, scrape every
``scrape_interval_s`` while stepping the fault schedule, then summarize and
feed the result back into the (possibly searching) generator.
"""
from __future__ import annotations

import asyncio
import os
from typing import List, Optional

from ..health import cluster_snapshot_from_texts
from .benchmark import BenchmarkParameters, ParametersGenerator
from .faults import CrashRecoverySchedule
from .measurement import Measurement, MeasurementsCollection
from .runner import Runner

SCRAPE_INTERVAL_S = 15.0  # orchestrator.rs:523-530


class Orchestrator:
    def __init__(
        self,
        runner: Runner,
        generator: ParametersGenerator,
        results_dir: str = "benchmark-results",
        scrape_interval_s: float = SCRAPE_INTERVAL_S,
        workload: str = "shared",
    ) -> None:
        self.runner = runner
        self.generator = generator
        self.results_dir = results_dir
        self.scrape_interval_s = scrape_interval_s
        self.workload = workload
        self.collections: List[MeasurementsCollection] = []

    async def run_benchmarks(self) -> List[MeasurementsCollection]:
        os.makedirs(self.results_dir, exist_ok=True)
        run_index = 0
        while (parameters := self.generator.next_parameters()) is not None:
            collection = await self._run_one(parameters)
            self.collections.append(collection)
            collection.save(
                os.path.join(self.results_dir, f"measurements-{run_index}.json")
            )
            self.generator.register_result(parameters, collection)
            run_index += 1
        return self.collections

    async def _run_one(self, parameters: BenchmarkParameters) -> MeasurementsCollection:
        await self.runner.cleanup()
        await self.runner.configure(parameters.nodes, parameters.load)
        for authority in range(parameters.nodes):
            await self.runner.boot_node(authority)

        collection = MeasurementsCollection(parameters.to_dict())
        faults = CrashRecoverySchedule(parameters.faults, parameters.nodes)
        elapsed = 0.0
        next_fault_at = parameters.faults.interval_s
        while elapsed < parameters.duration_s:
            step = min(self.scrape_interval_s, parameters.duration_s - elapsed)
            await asyncio.sleep(step)
            elapsed += step
            # Scrape every node (orchestrator.rs:523-541).
            texts = {}
            for authority in range(parameters.nodes):
                text = await self.runner.scrape(authority)
                texts[str(authority)] = text
                if text is not None:
                    collection.add(
                        str(authority),
                        Measurement.from_prometheus(text, self.workload),
                    )
            # Host-level sample alongside the node scrapes (node_exporter
            # equivalent): attributes saturation to the host, not the node.
            host = await self.runner.host_sample()
            if host is not None:
                collection.add_host_sample(host)
            # Fleet health snapshot from the same scrape (health.py): the
            # run's artifact carries its own diagnosis — which authority
            # straggled, how far commits skewed, whether SLO alerts fired.
            snapshot = cluster_snapshot_from_texts(texts, parameters.nodes)
            snapshot["t"] = round(elapsed, 3)
            if host is not None:
                snapshot["weather"] = {
                    k: host[k]
                    for k in ("cpu_pct", "load_1m", "mem_available_mb")
                    if k in host
                }
            collection.add_health_sample(snapshot)
            # Fault schedule (orchestrator.rs:543-583).
            if (
                parameters.faults.kind != "none"
                and elapsed >= next_fault_at
            ):
                next_fault_at += parameters.faults.interval_s
                to_kill, to_boot = faults.update()
                for node in to_kill:
                    await self.runner.kill_node(node)
                for node in to_boot:
                    await self.runner.boot_node(node)
        await self.runner.cleanup()
        return collection
