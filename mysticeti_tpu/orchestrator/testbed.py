"""Testbed lifecycle: deploy / start / stop / destroy / status over a fleet.

Capability parity with ``orchestrator/src/testbed.rs`` (:21-210) and the
provider seam of ``orchestrator/src/client/mod.rs`` (`ServerProviderClient`
:68), re-targeted for this environment: the cloud SDK backends (aws.rs,
vultr.rs) are out of scope (no cloud credentials / egress), so providers
manage *inventory* — which hosts exist, whether they are active — while the
reference's install/update-over-ssh steps (orchestrator.rs:281-475) are
implemented against any reachable fleet via :class:`~.ssh.SshManager`.

Providers:

* :class:`StaticProvider` — a fixed host list (the operator's machines);
  deploy/destroy toggle inventory membership, start/stop toggle active state.
  State persists as JSON next to the settings so repeated CLI invocations
  see the same testbed (testbed.rs keeps this state in the cloud tags).
* Anything implementing :class:`ServerProvider` can back real provisioning.
"""
from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from .display import action, done, print_table, status
from .ssh import CommandContext, SshManager


@dataclass
class Instance:
    """client/mod.rs:18-60 `Instance` equivalent."""

    id: str
    host: str
    region: str = "local"
    active: bool = True

    def is_active(self) -> bool:
        return self.active


class ServerProvider:
    """client/mod.rs:68 `ServerProviderClient` seam."""

    async def list_instances(self) -> List[Instance]:
        raise NotImplementedError

    async def create_instances(self, count: int, region: str) -> List[Instance]:
        raise NotImplementedError

    async def start_instances(self, ids: Sequence[str]) -> None:
        raise NotImplementedError

    async def stop_instances(self, ids: Sequence[str]) -> None:
        raise NotImplementedError

    async def terminate_instances(self, ids: Sequence[str]) -> None:
        raise NotImplementedError


class StaticProvider(ServerProvider):
    """Inventory over a fixed pool of operator-supplied hosts.

    ``pool`` is every reachable host; "creating" an instance claims the next
    unclaimed pool entry, "terminating" releases it.  State is persisted to
    ``state_path`` as JSON.
    """

    def __init__(self, pool: Sequence[str], state_path: Optional[str] = None) -> None:
        self.pool = list(pool)
        self.state_path = state_path
        self._instances: Dict[str, Instance] = {}
        if state_path and os.path.exists(state_path):
            with open(state_path) as f:
                for raw in json.load(f):
                    inst = Instance(**raw)
                    self._instances[inst.id] = inst
        # Monotonic id source: never reuse a live instance's id after a
        # terminate+create cycle (ids are `i-NNNN`; start past the highest
        # ever persisted).
        self._next_id = 1 + max(
            (int(i.id.rsplit("-", 1)[1]) for i in self._instances.values()),
            default=-1,
        )

    def _save(self) -> None:
        if self.state_path:
            with open(self.state_path, "w") as f:
                json.dump(
                    [dataclasses.asdict(i) for i in self._instances.values()],
                    f,
                    indent=2,
                )
                f.write("\n")

    async def list_instances(self) -> List[Instance]:
        return sorted(self._instances.values(), key=lambda i: i.id)

    async def create_instances(self, count: int, region: str) -> List[Instance]:
        claimed = {i.host for i in self._instances.values()}
        free = [h for h in self.pool if h not in claimed]
        if len(free) < count:
            raise RuntimeError(
                f"pool exhausted: need {count} hosts, {len(free)} free"
            )
        created = []
        for host in free[:count]:
            inst = Instance(id=f"i-{self._next_id:04d}", host=host,
                            region=region, active=True)
            self._next_id += 1
            self._instances[inst.id] = inst
            created.append(inst)
        self._save()
        return created

    async def start_instances(self, ids: Sequence[str]) -> None:
        for iid in ids:
            self._instances[iid].active = True
        self._save()

    async def stop_instances(self, ids: Sequence[str]) -> None:
        for iid in ids:
            self._instances[iid].active = False
        self._save()

    async def terminate_instances(self, ids: Sequence[str]) -> None:
        for iid in ids:
            self._instances.pop(iid, None)
        self._save()


INSTALL_COMMANDS = (
    # orchestrator.rs:281 installs build deps + rust; a Python/JAX node only
    # needs the checkout and an interpreter, so install verifies those.
    "python3 -c 'import sys; assert sys.version_info >= (3, 9)'",
)


class Testbed:
    """testbed.rs:21-210 equivalent: lifecycle operations over a provider."""

    __test__ = False  # not a pytest class, despite the name

    def __init__(
        self,
        provider: ServerProvider,
        ssh: Optional[SshManager] = None,
        repo_url: str = "",
        remote_repo: str = "mysticeti-tpu",
    ) -> None:
        self.provider = provider
        self.ssh = ssh
        self.repo_url = repo_url
        self.remote_repo = remote_repo

    async def deploy(self, count: int, region: str = "local") -> List[Instance]:
        action(f"deploying {count} instance(s) in {region}")
        created = await self.provider.create_instances(count, region)
        done(f"{len(created)} instance(s) deployed")
        return created

    async def start(self) -> None:
        insts = await self.provider.list_instances()
        action(f"starting {len(insts)} instance(s)")
        await self.provider.start_instances([i.id for i in insts])
        if self.ssh is not None:
            for inst in insts:
                await self.ssh.wait_reachable(inst.host)
        done()

    async def stop(self) -> None:
        insts = await self.provider.list_instances()
        action(f"stopping {len(insts)} instance(s)")
        await self.provider.stop_instances([i.id for i in insts])
        done()

    async def destroy(self) -> None:
        insts = await self.provider.list_instances()
        action(f"destroying {len(insts)} instance(s)")
        await self.provider.terminate_instances([i.id for i in insts])
        done()

    async def status(self) -> List[Instance]:
        insts = await self.provider.list_instances()
        print_table(
            ["id", "host", "region", "state"],
            [[i.id, i.host, i.region, "running" if i.active else "stopped"]
             for i in insts],
        )
        return insts

    # -- software lifecycle over ssh (orchestrator.rs:281-475) --

    def _require_ssh(self) -> SshManager:
        if self.ssh is None:
            raise RuntimeError("this operation needs an SshManager")
        return self.ssh

    async def active_hosts(self) -> List[str]:
        return [i.host for i in await self.provider.list_instances()
                if i.is_active()]

    async def install(self) -> None:
        """Verify/install prerequisites on every active instance."""
        ssh = self._require_ssh()
        hosts = await self.active_hosts()
        action(f"installing prerequisites on {len(hosts)} host(s)")
        for cmd in INSTALL_COMMANDS:
            await ssh.execute_all(cmd, hosts=hosts)
        done()

    async def update(self) -> None:
        """Clone or fast-forward the repo on every active instance
        (orchestrator.rs:399 `update`); no build step — the node is Python."""
        ssh = self._require_ssh()
        if not self.repo_url:
            raise RuntimeError("update requires a repo_url")
        hosts = await self.active_hosts()
        action(f"updating {self.remote_repo} on {len(hosts)} host(s)")
        cmd = (
            f"if [ -d {self.remote_repo}/.git ]; then"
            f" git -C {self.remote_repo} pull --ff-only;"
            f" else git clone {self.repo_url} {self.remote_repo}; fi"
        )
        await ssh.execute_all(cmd, hosts=hosts)
        done()

    async def download_logs(self, working_dir: str, dest_dir: str) -> List[str]:
        """Pull node logs from every active instance (orchestrator.rs log
        download step); returns the local paths."""
        ssh = self._require_ssh()
        hosts = await self.active_hosts()
        action(f"downloading logs from {len(hosts)} host(s)")
        paths = []
        for idx, host in enumerate(hosts):
            local = os.path.join(dest_dir, f"host-{idx}")
            await ssh.download(host, working_dir, local)
            paths.append(local)
            status(f"{host} -> {local}")
        done()
        return paths
