"""Console display helpers: status lines, progress, and tables.

Capability parity with ``orchestrator/src/display.rs`` (:1-104) — colored
action/status output and tabular summaries for the benchmark CLI.  ANSI color
is applied only when the stream is a TTY (or ``FORCE_COLOR`` is set), so logs
piped to files stay clean.
"""
from __future__ import annotations

import os
import shutil
import sys
from typing import IO, Iterable, List, Optional, Sequence


def _use_color(stream: IO[str]) -> bool:
    if os.environ.get("NO_COLOR"):
        return False
    if os.environ.get("FORCE_COLOR"):
        return True
    return hasattr(stream, "isatty") and stream.isatty()


def _paint(text: str, code: str, stream: IO[str]) -> str:
    return f"\x1b[{code}m{text}\x1b[0m" if _use_color(stream) else text


def action(message: str, stream: Optional[IO[str]] = None) -> None:
    """A step being started: bold cyan arrow prefix (display.rs `action`)."""
    stream = stream or sys.stdout
    print(f"{_paint('==>', '1;36', stream)} {message}", file=stream, flush=True)


def status(message: str, stream: Optional[IO[str]] = None) -> None:
    """A normal progress line, indented under the current action."""
    stream = stream or sys.stdout
    print(f"    {message}", file=stream, flush=True)


def done(message: str = "done", stream: Optional[IO[str]] = None) -> None:
    stream = stream or sys.stdout
    print(f"    {_paint(message, '1;32', stream)}", file=stream, flush=True)


def warn(message: str, stream: Optional[IO[str]] = None) -> None:
    stream = stream or sys.stderr
    print(f"{_paint('warning:', '1;33', stream)} {message}", file=stream, flush=True)


def error(message: str, stream: Optional[IO[str]] = None) -> None:
    stream = stream or sys.stderr
    print(f"{_paint('error:', '1;31', stream)} {message}", file=stream, flush=True)


def progress(current: int, total: int, label: str = "",
             stream: Optional[IO[str]] = None, width: int = 30) -> None:
    """Single-line progress bar, redrawn in place on TTYs."""
    stream = stream or sys.stdout
    total = max(total, 1)
    filled = int(width * min(current, total) / total)
    bar = "#" * filled + "-" * (width - filled)
    line = f"[{bar}] {current}/{total} {label}".rstrip()
    if _use_color(stream):
        print(f"\r{line}\x1b[K", end="" if current < total else "\n",
              file=stream, flush=True)
    else:
        print(line, file=stream, flush=True)


def format_table(headers: Sequence[str], rows: Iterable[Sequence[object]]) -> str:
    """Fixed-width table (display.rs' prettytable equivalent)."""
    str_rows: List[List[str]] = [[str(c) for c in row] for row in rows]
    widths = [len(h) for h in headers]
    for row in str_rows:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(cell))
    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    out = [sep, "| " + " | ".join(h.ljust(w) for h, w in zip(headers, widths)) + " |", sep]
    for row in str_rows:
        out.append("| " + " | ".join(c.ljust(w) for c, w in zip(row, widths)) + " |")
    out.append(sep)
    return "\n".join(out)


def print_table(headers: Sequence[str], rows: Iterable[Sequence[object]],
                stream: Optional[IO[str]] = None) -> None:
    print(format_table(headers, rows), file=stream or sys.stdout, flush=True)


def terminal_width(default: int = 80) -> int:
    return shutil.get_terminal_size((default, 24)).columns
