"""Benchmark orchestration: deploy validators, drive load, scrape, summarize.

Capability parity with the reference's ``orchestrator/`` crate, re-targeted:
the reference provisions AWS/Vultr over SSH (client/aws.rs, client/vultr.rs,
ssh.rs); this framework ships a provider-agnostic ``Runner`` seam with a fully
supported local multiprocess runner (the dry-run/testbed scale) and an
ssh-CLI-based runner for real fleets — no cloud SDK dependency.

Modules:
  measurement — prometheus scrape parsing + tps/latency aggregation
                (orchestrator/src/measurement.rs)
  benchmark   — benchmark parameters, fixed-load and max-load binary search
                (orchestrator/src/benchmark.rs)
  faults      — permanent / crash-recovery fault schedules
                (orchestrator/src/faults.rs)
  runner      — LocalProcessRunner + SshRunner (orchestrator.rs + ssh.rs)
  orchestrator— the benchmark lifecycle loop (orchestrator.rs:523-727)
"""
from .benchmark import BenchmarkParameters, LoadType, ParametersGenerator
from .faults import CrashRecoverySchedule, FaultsType
from .measurement import Measurement, MeasurementsCollection

__all__ = [
    "BenchmarkParameters",
    "LoadType",
    "ParametersGenerator",
    "FaultsType",
    "CrashRecoverySchedule",
    "Measurement",
    "MeasurementsCollection",
]
