"""Benchmark orchestration: deploy validators, drive load, scrape, summarize.

Capability parity with the reference's ``orchestrator/`` crate, re-targeted:
the reference provisions AWS/Vultr over SSH (client/aws.rs, client/vultr.rs,
ssh.rs); this framework ships a provider-agnostic ``Runner`` seam with a fully
supported local multiprocess runner (the dry-run/testbed scale) and an
ssh-CLI-based runner for real fleets — no cloud SDK dependency.

Modules:
  measurement — prometheus scrape parsing + tps/latency aggregation
                (orchestrator/src/measurement.rs)
  benchmark   — benchmark parameters, fixed-load and max-load binary search
                (orchestrator/src/benchmark.rs)
  faults      — permanent / crash-recovery fault schedules
                (orchestrator/src/faults.rs)
  runner      — LocalProcessRunner + SshRunner (orchestrator.rs + ssh.rs)
  orchestrator— the benchmark lifecycle loop (orchestrator.rs:523-727)
  ssh         — retried/parallel remote execution manager (ssh.rs:83-446)
  testbed     — deploy/start/stop/destroy/status lifecycle + provider seam
                (testbed.rs:21-210, client/mod.rs:68)
  display     — colored progress/status/table console output (display.rs)
  settings    — persisted settings.json model (settings.rs:53-96)
  monitor     — prometheus/grafana monitoring stack deploy (monitor.rs)
  logs        — node/client log analyzer (logs.rs:10-56)
  plot        — latency-throughput plots (assets/plot.py)
"""
from .benchmark import BenchmarkParameters, LoadType, ParametersGenerator
from .faults import CrashRecoverySchedule, FaultsType
from .measurement import Measurement, MeasurementsCollection
from .ssh import CommandContext, SshManager
from .testbed import Instance, ServerProvider, StaticProvider, Testbed

__all__ = [
    "BenchmarkParameters",
    "LoadType",
    "ParametersGenerator",
    "FaultsType",
    "CrashRecoverySchedule",
    "Measurement",
    "MeasurementsCollection",
    "CommandContext",
    "SshManager",
    "Instance",
    "ServerProvider",
    "StaticProvider",
    "Testbed",
]
