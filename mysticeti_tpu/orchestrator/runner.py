"""Deployment runners: local multiprocess fleet and ssh-CLI remote fleet.

Capability parity with ``orchestrator/src/orchestrator.rs`` (boot_nodes :215,
run_nodes :476, kill/cleanup) + ``ssh.rs`` — re-targeted: the reference shells
into cloud instances over libssh2 and runs binaries under tmux; here the
``Runner`` seam abstracts "start validator i / kill validator i / scrape i":

* ``LocalProcessRunner`` — subprocess per validator on localhost (the dry-run
  scale, fully tested in CI);
* ``SshRunner`` — same operations through the system ``ssh`` binary with
  ``nohup`` (no cloud SDK / libssh dependency; provisioning is out of scope —
  point it at any fleet of reachable hosts).
"""
from __future__ import annotations

import asyncio
import os
import signal
import sys
from typing import Dict, List, Optional

from ..cli import benchmark_genesis
from ..config import Parameters


class Runner:
    async def configure(self, committee_size: int, load_tx_s: int = 0) -> None:
        raise NotImplementedError

    async def boot_node(self, authority: int) -> None:
        raise NotImplementedError

    async def kill_node(self, authority: int) -> None:
        raise NotImplementedError

    async def scrape(self, authority: int) -> Optional[str]:
        """Fetch the node's /metrics text, or None when unreachable."""
        raise NotImplementedError

    async def host_sample(self) -> Optional[dict]:
        """One host-metrics sample covering the fleet (node_exporter
        equivalent — hostmon.py); None when the runner cannot observe its
        hosts."""
        return None

    async def cleanup(self) -> None:
        raise NotImplementedError


async def _http_get_metrics(host: str, port: int, timeout: float = 5.0,
                            path: str = "/metrics") -> Optional[str]:
    try:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), timeout=timeout
        )
        writer.write(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
        await writer.drain()
        data = await asyncio.wait_for(reader.read(-1), timeout=timeout)
        writer.close()
        body = data.split(b"\r\n\r\n", 1)
        return body[1].decode() if len(body) == 2 else None
    except (OSError, asyncio.TimeoutError):
        return None


# One orchestration coroutine drives start/run/stop sequentially; the
# lifecycle fields never see a concurrent writer, so read-await-write
# spans in these methods cannot interleave.
# lint: single-owner[orchestrator]
class LocalProcessRunner(Runner):
    def __init__(
        self,
        working_dir: str,
        tps_per_node: int = 100,
        transaction_size: int = 512,
        verifier: str = "cpu",
    ) -> None:
        self.working_dir = working_dir
        self.tps_per_node = tps_per_node
        self.transaction_size = transaction_size
        self.verifier = verifier
        self.committee_size = 0
        self.processes: Dict[int, asyncio.subprocess.Process] = {}
        self.parameters: Optional[Parameters] = None
        self._host_sampler = None
        self._verifier_service: Optional[asyncio.subprocess.Process] = None
        self._service_socket: Optional[str] = None

    async def configure(self, committee_size: int, load_tx_s: int = 0) -> None:
        self.committee_size = committee_size
        if load_tx_s > 0:
            # The sweep's offered load for THIS run, split across the committee
            # (protocol/mysticeti.rs:116 passes TPS the same way).
            self.tps_per_node = max(1, load_tx_s // committee_size)
        # Wipe per-validator state from any previous run (orchestrator.rs
        # cleanup step): genesis regenerates keys, so a stale WAL replayed
        # into the fresh committee fails verification wholesale — every block
        # suspends and the run drowns in sync traffic instead of committing.
        import glob
        import shutil

        for path in glob.glob(os.path.join(self.working_dir, "validator-*")):
            shutil.rmtree(path, ignore_errors=True)
        benchmark_genesis(["127.0.0.1"] * committee_size, self.working_dir)
        self.parameters = Parameters.load(
            os.path.join(self.working_dir, "parameters.yaml")
        )
        self._assert_ports_free()
        if (
            self.verifier.startswith("tpu")
            and not os.environ.get("MYSTICETI_NO_VERIFIER_SERVICE")
        ):
            await self._start_verifier_service()

    async def _start_verifier_service(self) -> None:
        """One warmed accelerator runtime for the whole fleet
        (verifier_service.py): started before the nodes so its trace/compile
        overlaps their boot; nodes find it via MYSTICETI_VERIFIER_SOCKET and
        never build a JAX runtime of their own."""
        if self._verifier_service is not None:
            return
        self._service_socket = os.path.join(
            os.path.abspath(self.working_dir), "verifier.sock"
        )
        # A previous run's cleanup SIGKILLs the service, skipping its own
        # unlink — a stale socket file would satisfy the exists() wait below
        # before the fresh process has bound it.
        if os.path.exists(self._service_socket):
            os.unlink(self._service_socket)
        log = open(os.path.join(self.working_dir, "verifier-service.log"), "ab")
        env = dict(os.environ)
        env.pop("MYSTICETI_VERIFIER_SOCKET", None)  # the service IS the backend
        self._verifier_service = await asyncio.create_subprocess_exec(
            sys.executable,
            "-m",
            "mysticeti_tpu",
            "verifier-service",
            "--socket",
            self._service_socket,
            "--committee-path",
            os.path.join(self.working_dir, "committee.yaml"),
            env=env,
            stdout=log,
            stderr=log,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
        )
        try:
            await self._await_service_warm()
        except BaseException:
            # A failed boot must not leak the child: an orphaned service
            # would hold the accelerator and contend with the next run's
            # service for the chip.
            service, self._verifier_service = self._verifier_service, None
            self._service_socket = None
            if service is not None and service.returncode is None:
                service.send_signal(signal.SIGKILL)
                await service.wait()
            raise

    async def _await_service_warm(self) -> None:
        # The socket appears as soon as the listener is up.
        for _ in range(600):
            if os.path.exists(self._service_socket):
                break
            if self._verifier_service.returncode is not None:
                raise RuntimeError(
                    "verifier service died at boot — see verifier-service.log"
                )
            await asyncio.sleep(0.1)
        else:
            raise RuntimeError("verifier service socket never appeared")
        # Block until the service is WARM (HELLO round-trip), not merely
        # listening: booting validators early makes them contend for the
        # host's cores exactly while the service is paying its one-time
        # trace/compile — on a small host that contention can starve the
        # warmup for the whole measurement window.  A host daemon being warm
        # before validators start is also the deployment shape.
        from ..committee import Committee
        from ..verifier_service import RemoteSignatureVerifier

        committee = Committee.load(
            os.path.join(self.working_dir, "committee.yaml")
        )
        probe = RemoteSignatureVerifier(
            socket_path=self._service_socket,
            committee_keys=committee.public_key_bytes(),
            timeout_s=900.0,
        )
        loop = asyncio.get_running_loop()
        for _ in range(50):
            try:
                await loop.run_in_executor(None, probe.warmup)
                return
            except (ConnectionError, OSError):
                # Bound but briefly unready, or unlink/bind race: retry
                # while the subprocess is alive.
                if self._verifier_service.returncode is not None:
                    raise RuntimeError(
                        "verifier service died during warmup — see "
                        "verifier-service.log"
                    )
                await asyncio.sleep(0.2)
        raise RuntimeError("verifier service never became warm")

    def _assert_ports_free(self) -> None:
        """Fail fast when another fleet holds our ports: a node that cannot
        bind crashes AFTER genesis, and the scraper would then silently read
        metrics from the stale process that owns the port — poisoning every
        measurement with another run's counters."""
        import socket

        busy = []
        for authority in range(self.committee_size):
            for _, port in (
                self.parameters.address(authority),
                self.parameters.metrics_address(authority),
            ):
                with socket.socket() as s:
                    # REUSEADDR matches the servers' bind semantics: sockets
                    # in TIME_WAIT from the previous fleet are fine, only a
                    # live listener must fail the check.
                    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                    try:
                        s.bind(("127.0.0.1", port))
                    except OSError:
                        busy.append(port)
        if busy:
            raise RuntimeError(
                f"ports already in use (stale fleet?): {sorted(set(busy))}"
            )

    async def boot_node(self, authority: int) -> None:
        env = dict(os.environ)
        env["TPS"] = str(self.tps_per_node)
        env["TRANSACTION_SIZE"] = str(self.transaction_size)
        env.setdefault("INITIAL_DELAY", "1")
        if self._service_socket is not None:
            env["MYSTICETI_VERIFIER_SOCKET"] = self._service_socket
        log = open(os.path.join(self.working_dir, f"node-{authority}.log"), "ab")
        proc = await asyncio.create_subprocess_exec(
            sys.executable,
            "-m",
            "mysticeti_tpu",
            "run",
            "--authority",
            str(authority),
            "--committee-path",
            os.path.join(self.working_dir, "committee.yaml"),
            "--parameters-path",
            os.path.join(self.working_dir, "parameters.yaml"),
            "--private-config-path",
            os.path.join(self.working_dir, f"validator-{authority}"),
            "--verifier",
            self.verifier,
            env=env,
            stdout=log,
            stderr=log,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
        )
        self.processes[authority] = proc

    async def kill_node(self, authority: int) -> None:
        proc = self.processes.pop(authority, None)
        if proc is not None and proc.returncode is None:
            proc.send_signal(signal.SIGKILL)
            await proc.wait()

    async def scrape(self, authority: int) -> Optional[str]:
        host, port = self.parameters.metrics_address(authority)
        return await _http_get_metrics("127.0.0.1", port)

    async def host_sample(self) -> Optional[dict]:
        if self._host_sampler is None:
            try:
                from .hostmon import HostSampler

                self._host_sampler = HostSampler()
            except ImportError:  # no psutil on this host: no host series
                return None
        pids = {
            f"node-{a}": proc.pid
            for a, proc in self.processes.items()
            if proc.returncode is None
        }
        return self._host_sampler.sample(pids)

    async def cleanup(self) -> None:
        for authority in list(self.processes):
            await self.kill_node(authority)
        service, self._verifier_service = self._verifier_service, None
        if service is not None and service.returncode is None:
            service.send_signal(signal.SIGKILL)
            await service.wait()


class SshRunner(Runner):
    """Remote fleet over :class:`~.ssh.SshManager` (ssh.rs re-imagined):
    retried/timed-out remote execution, scp config upload, background node
    sessions with pidfiles.

    ``hosts``: one reachable address per validator.  Assumes the repo is
    deployed at ``remote_repo`` on every host (``fleet install``/``update``
    handle that, or a one-line ``git clone`` per host).
    """

    def __init__(
        self,
        hosts: List[str],
        remote_repo: str,
        working_dir: str = "/tmp/mysticeti-bench",
        python: str = "python3",
        tps_per_node: int = 100,
        verifier: str = "tpu",
        ssh_args: Optional[List[str]] = None,
        ssh: Optional["SshManager"] = None,
    ) -> None:
        from .ssh import SshManager

        self.hosts = hosts
        self.remote_repo = remote_repo
        self.working_dir = working_dir
        self.python = python
        self.tps_per_node = tps_per_node
        self.verifier = verifier
        self.ssh = ssh or SshManager(hosts, ssh_args=ssh_args)
        self.parameters: Optional[Parameters] = None

    def _session(self, authority: int) -> str:
        return f"mysticeti-node-{authority}"

    async def configure(self, committee_size: int, load_tx_s: int = 0) -> None:
        assert committee_size <= len(self.hosts)
        if load_tx_s > 0:
            self.tps_per_node = max(1, load_tx_s // committee_size)
        import tempfile

        local = tempfile.mkdtemp(prefix="mysticeti-genesis-")
        benchmark_genesis(self.hosts[:committee_size], local)
        self.parameters = Parameters.load(os.path.join(local, "parameters.yaml"))
        for i, host in enumerate(self.hosts[:committee_size]):
            await self.ssh.execute(host, f"rm -rf {self.working_dir}/validator-{i}")
            await self.ssh.upload(
                host,
                [
                    os.path.join(local, "committee.yaml"),
                    os.path.join(local, "parameters.yaml"),
                    os.path.join(local, f"validator-{i}"),
                ],
                self.working_dir,
            )

    async def boot_node(self, authority: int) -> None:
        from .ssh import CommandContext

        host = self.hosts[authority]
        context = CommandContext(
            path=self.remote_repo,
            env={"TPS": str(self.tps_per_node)},
            background=self._session(authority),
            log_file=f"{self.working_dir}/node-{authority}.log",
        )
        await self.ssh.execute(
            host,
            f"{self.python} -m mysticeti_tpu run --authority {authority}"
            f" --committee-path {self.working_dir}/committee.yaml"
            f" --parameters-path {self.working_dir}/parameters.yaml"
            f" --private-config-path {self.working_dir}/validator-{authority}"
            f" --verifier {self.verifier}",
            context,
        )

    async def kill_node(self, authority: int) -> None:
        await self.ssh.kill_session(self.hosts[authority], self._session(authority))

    async def scrape(self, authority: int) -> Optional[str]:
        host, port = self.parameters.metrics_address(authority)
        return await _http_get_metrics(self.hosts[authority].split("@")[-1], port)

    async def host_sample(self) -> Optional[dict]:
        from .hostmon import REMOTE_SAMPLE_CMD, parse_remote_sample
        from .ssh import SshError

        hosts = {}
        for i, host in enumerate(self.hosts):
            try:
                out = await self.ssh.execute(host, REMOTE_SAMPLE_CMD)
            except SshError:
                continue
            parsed = parse_remote_sample(out)
            if parsed is not None:
                hosts[f"host-{i}"] = parsed
        if not hosts:
            return None
        import time as _time

        return {"timestamp_s": _time.time(), "hosts": hosts}

    async def download_logs(self, dest_dir: str) -> List[str]:
        """Pull every node's log (orchestrator.rs log-download step)."""
        paths = []
        for i, host in enumerate(self.hosts):
            local = os.path.join(dest_dir, f"node-{i}.log")
            await self.ssh.download(
                host, f"{self.working_dir}/node-{i}.log", local
            )
            paths.append(local)
        return paths

    async def cleanup(self) -> None:
        for i in range(len(self.hosts)):
            await self.kill_node(i)
