"""Host-level metrics sampling — the node_exporter equivalent.

The reference deploys a node_exporter container per instance and scrapes it
through the monitoring stack (``orchestrator/assets/install_node_exporter.sh``,
``orchestrator/src/monitor.rs:105-148``) so benchmark runs can attribute
saturation to the host, not just the node process.  Here the same capability
is a psutil sampler driven by the orchestrator's scrape loop:

* ``HostSampler.sample(pids)`` — system cpu%, 1-minute load, available
  memory, cumulative net bytes, plus per-node-process cpu%/rss/threads.
* Samples ride in the ``MeasurementsCollection`` (``host_samples``) and are
  summarized by ``MeasurementsCollection.host_summary()``, so max-load
  artifacts can tell verification cost from engine cost from load-generator
  core-steal on a shared box.

cpu_percent readings are interval-based: the sampler keeps one
``psutil.Process`` handle per pid so each call measures utilization since the
previous scrape; the first sample for a pid reports ``None`` (no interval yet)
rather than a misleading 0.0.
"""
from __future__ import annotations

import os
import sys
import time
from typing import Dict, Optional


class HostSampler:
    def __init__(self) -> None:
        import psutil

        self._psutil = psutil
        self._procs: Dict[int, "psutil.Process"] = {}
        psutil.cpu_percent(None)  # seed the system-wide interval counter

    def sample(self, pids: Optional[Dict[str, int]] = None) -> dict:
        psutil = self._psutil
        per: Dict[str, dict] = {}
        for name, pid in (pids or {}).items():
            try:
                proc = self._procs.get(pid)
                if proc is None:
                    proc = psutil.Process(pid)
                    proc.cpu_percent(None)  # seed; no interval to report yet
                    self._procs[pid] = proc
                    cpu = None
                else:
                    cpu = proc.cpu_percent(None)
                with proc.oneshot():
                    per[name] = {
                        "cpu_pct": cpu,
                        "rss_mb": round(proc.memory_info().rss / 2**20, 1),
                        "threads": proc.num_threads(),
                    }
            except psutil.Error:
                self._procs.pop(pid, None)
        vm = psutil.virtual_memory()
        net = psutil.net_io_counters()
        load_1m, load_5m, load_15m = os.getloadavg()
        # CPU steal: time another guest on the hypervisor took from us —
        # on a shared cloud box it explains loop-lag spikes no in-process
        # attribution can (the GIL/host conditions a PERF_ATTR artifact
        # was measured under).
        steal = getattr(psutil.cpu_times_percent(None), "steal", None)
        return {
            "timestamp_s": time.time(),
            "cpu_pct": psutil.cpu_percent(None),
            "load_1m": load_1m,
            "load_5m": load_5m,
            "load_15m": load_15m,
            "cpu_steal_pct": steal,
            # The GIL release cadence the run was measured under: a tuned
            # sys.setswitchinterval changes every convoy/blocking number.
            "switch_interval_s": sys.getswitchinterval(),
            "mem_available_mb": round(vm.available / 2**20, 1),
            "net_bytes_sent": net.bytes_sent,
            "net_bytes_recv": net.bytes_recv,
            "per_process": per,
        }


REMOTE_SAMPLE_CMD = (
    "cat /proc/loadavg && grep -E 'MemTotal|MemAvailable' /proc/meminfo"
)


def parse_remote_sample(text: str) -> Optional[dict]:
    """Parse the ``REMOTE_SAMPLE_CMD`` output from an SshRunner host into the
    same shape as ``HostSampler.sample`` (fields that need interval state are
    absent — one ssh round-trip per scrape keeps the remote side stateless)."""
    try:
        lines = [ln for ln in text.splitlines() if ln.strip()]
        loads = lines[0].split()
        mem = {}
        for ln in lines[1:]:
            key, _, rest = ln.partition(":")
            mem[key.strip()] = float(rest.split()[0]) / 1024.0  # kB -> MB
        return {
            "timestamp_s": time.time(),
            "load_1m": float(loads[0]),
            "load_5m": float(loads[1]),
            "load_15m": float(loads[2]),
            "mem_available_mb": round(mem.get("MemAvailable", 0.0), 1),
            "mem_total_mb": round(mem.get("MemTotal", 0.0), 1),
        }
    except (IndexError, ValueError):
        return None
