"""Benchmark parameters and the max-load search state machine.

Capability parity with ``orchestrator/src/benchmark.rs``:

* ``BenchmarkParameters`` {nodes, faults, load, duration} (:33-45)
* ``LoadType``: fixed list of loads, or binary ``Search`` for the maximum
  sustainable load (:99-135)
* out-of-capacity rule: avg latency > 5x the previous run's, or tps < 2/3 of
  the offered load (:202-220)
* ``register_result`` driving the search: double until breaking point, then
  binary search between the last good and first bad load (:224-271)
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from .faults import FaultsType
from .measurement import MeasurementsCollection

MAX_LATENCY_RATIO = 5.0  # benchmark.rs:205
MIN_TPS_RATIO = 2.0 / 3.0  # benchmark.rs:212


@dataclass
class BenchmarkParameters:
    nodes: int
    load: int  # offered tx/s across the committee
    duration_s: float
    faults: FaultsType = field(default_factory=FaultsType.none)

    def describe(self) -> str:
        return (
            f"{self.nodes} nodes ({self.faults.describe()}) - "
            f"{self.load} tx/s for {self.duration_s:.0f}s"
        )

    def to_dict(self) -> dict:
        return {
            "nodes": self.nodes,
            "load": self.load,
            "duration_s": self.duration_s,
            "faults": self.faults.to_dict(),
        }


class LoadType:
    FIXED = "fixed"
    SEARCH = "search"

    def __init__(self, kind: str, loads: Optional[List[int]] = None,
                 starting_load: int = 0, latency_increase_tolerance: float = MAX_LATENCY_RATIO,
                 max_iterations: int = 5) -> None:
        self.kind = kind
        self.loads = loads or []
        self.starting_load = starting_load
        self.latency_increase_tolerance = latency_increase_tolerance
        self.max_iterations = max_iterations

    @classmethod
    def fixed(cls, loads: List[int]) -> "LoadType":
        return cls(cls.FIXED, loads=loads)

    @classmethod
    def search(cls, starting_load: int, max_iterations: int = 5) -> "LoadType":
        return cls(cls.SEARCH, starting_load=starting_load, max_iterations=max_iterations)


class ParametersGenerator:
    """Yields the next BenchmarkParameters given past results (benchmark.rs:137-271)."""

    def __init__(
        self,
        nodes: int,
        load_type: LoadType,
        duration_s: float = 180.0,
        faults: Optional[FaultsType] = None,
    ) -> None:
        self.nodes = nodes
        self.load_type = load_type
        self.duration_s = duration_s
        self.faults = faults or FaultsType.none()
        self._fixed_index = 0
        self._search_lower = 0
        self._search_upper: Optional[int] = None
        self._search_current = load_type.starting_load
        self._iterations = 0
        self._previous_latency: Optional[float] = None
        self._done = False

    def _params(self, load: int) -> BenchmarkParameters:
        return BenchmarkParameters(
            nodes=self.nodes,
            load=load,
            duration_s=self.duration_s,
            faults=self.faults,
        )

    def next_parameters(self) -> Optional[BenchmarkParameters]:
        if self._done:
            return None
        if self.load_type.kind == LoadType.FIXED:
            if self._fixed_index >= len(self.load_type.loads):
                return None
            return self._params(self.load_type.loads[self._fixed_index])
        return self._params(self._search_current)

    def out_of_capacity(
        self, parameters: BenchmarkParameters, collection: MeasurementsCollection
    ) -> bool:
        """benchmark.rs:202-220."""
        avg_latency = collection.aggregate_average_latency_s()
        if (
            self._previous_latency is not None
            and self._previous_latency > 0
            and avg_latency > self.load_type.latency_increase_tolerance * self._previous_latency
        ):
            return True
        if collection.aggregate_tps() < MIN_TPS_RATIO * parameters.load:
            return True
        return False

    def register_result(
        self, parameters: BenchmarkParameters, collection: MeasurementsCollection
    ) -> None:
        """Advance the state machine (benchmark.rs:224-271)."""
        if self.load_type.kind == LoadType.FIXED:
            self._fixed_index += 1
            return
        over = self.out_of_capacity(parameters, collection)
        if not over:
            self._previous_latency = collection.aggregate_average_latency_s()
        self._iterations += 1
        # Record this probe's bound BEFORE the iteration cutoff: a run whose
        # final probe sustains must count toward max_sustainable_load.
        if over:
            self._search_upper = parameters.load
        else:
            self._search_lower = parameters.load
        if self._iterations >= self.load_type.max_iterations:
            self._done = True
            return
        if self._search_upper is None:
            self._search_current = parameters.load * 2  # still probing upward
        else:
            if self._search_upper - self._search_lower <= max(
                1, self._search_lower // 10
            ):
                self._done = True
                return
            self._search_current = (self._search_lower + self._search_upper) // 2

    def max_sustainable_load(self) -> int:
        return self._search_lower
