"""Log analyzer: count errors/crashes in downloaded node and client logs.

Capability parity with ``orchestrator/src/logs.rs`` (:10-56): after a
benchmark run, sweep the per-node log files and report how many log lines
look like errors and how many nodes crashed with a traceback — the quick
"did anything go wrong that the metrics won't show" check.
"""
from __future__ import annotations

import glob
import os
from dataclasses import dataclass, field
from typing import Dict, List

# Python-node equivalents of the reference's panic/error greps.
_ERROR_MARKERS = ("] error", "ERROR", " error ")
_CRASH_MARKERS = ("Traceback (most recent call last)",)


@dataclass
class LogsAnalysis:
    node_errors: Dict[str, int] = field(default_factory=dict)
    node_crashes: Dict[str, int] = field(default_factory=dict)

    @property
    def total_errors(self) -> int:
        return sum(self.node_errors.values())

    @property
    def total_crashes(self) -> int:
        return sum(self.node_crashes.values())

    def ok(self) -> bool:
        return self.total_errors == 0 and self.total_crashes == 0

    def display(self) -> str:
        lines = [
            f"log analysis: {self.total_errors} error lines, "
            f"{self.total_crashes} crashes across {len(self.node_errors)} logs"
        ]
        for name in sorted(self.node_errors):
            errors = self.node_errors[name]
            crashes = self.node_crashes[name]
            if errors or crashes:
                lines.append(f"  {name}: {errors} errors, {crashes} crashes")
        return "\n".join(lines)


def analyze_log_text(text: str) -> tuple:
    """(error_lines, crash_count) for one log's content."""
    errors = 0
    crashes = 0
    for line in text.splitlines():
        if any(m in line for m in _CRASH_MARKERS):
            crashes += 1
        elif any(m in line for m in _ERROR_MARKERS):
            errors += 1
    return errors, crashes


def analyze_logs(directory: str, pattern: str = "node-*.log") -> LogsAnalysis:
    """Sweep ``directory`` for log files matching ``pattern``."""
    analysis = LogsAnalysis()
    for path in sorted(glob.glob(os.path.join(directory, pattern))):
        name = os.path.basename(path)
        try:
            with open(path, "r", errors="replace") as f:
                errors, crashes = analyze_log_text(f.read())
        except OSError:
            continue
        analysis.node_errors[name] = errors
        analysis.node_crashes[name] = crashes
    return analysis
