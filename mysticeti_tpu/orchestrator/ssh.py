"""SSH connection manager: retried, timed-out, parallel remote execution.

Capability parity with ``orchestrator/src/ssh.rs``:

* ``CommandContext`` (ssh.rs:83) — working dir, env prefix, background
  session wrapping.  The reference runs background work under
  ``tmux new -d -s <id>``; here background commands run under
  ``setsid nohup`` with a pidfile per session name, which needs nothing
  installed on the target.
* ``SshManager`` (ssh.rs:99-272) — per-host retried execute with timeout,
  parallel fan-out over many hosts, upload/download (scp), reachability wait.

The process-spawn seam (``_spawn``) is the unit-test boundary: tests inject a
fake transport instead of needing a live sshd.
"""
from __future__ import annotations

import asyncio
import os
import shlex
from typing import Dict, List, Optional, Sequence, Tuple


class SshError(Exception):
    pass


class CommandContext:
    """How to run a remote command (ssh.rs:83 `CommandContext::apply`)."""

    def __init__(
        self,
        path: Optional[str] = None,
        env: Optional[Dict[str, str]] = None,
        background: Optional[str] = None,
        log_file: Optional[str] = None,
    ) -> None:
        self.path = path
        self.env = env or {}
        self.background = background
        self.log_file = log_file

    def pidfile(self) -> Optional[str]:
        if self.background is None:
            return None
        return f"/tmp/.mysticeti-session-{self.background}.pid"

    def apply(self, command: str) -> str:
        parts = []
        if self.path:
            parts.append(f"cd {shlex.quote(self.path)} &&")
        for key, value in self.env.items():
            parts.append(f"{key}={shlex.quote(value)}")
        if self.background is not None:
            log = self.log_file or "/dev/null"
            # The marker comment rides in the spawned shell's cmdline so the
            # liveness probe below can tell OUR session apart from an
            # unrelated process that recycled the pid after a crash.  The
            # trailing marker no-op keeps the shell RESIDENT: with it, the
            # command is not the tail of `sh -c`, so bash cannot exec-replace
            # the shell (which would swap the cmdline out for the command's
            # own argv and lose the marker on sh->bash hosts).
            marker = f"mysticeti-session-{self.background}"
            inner = (
                f": {marker}; " + " ".join(parts + [command]) + f"; : {marker}"
            )
            pidfile = self.pidfile()
            # Idempotent spawn: SshManager.execute retries on transient
            # failures, and a dropped connection after the remote process
            # launched would otherwise double-spawn it (and the pidfile would
            # only remember the last pid, orphaning the first).  Guard on a
            # live pidfile the way the reference's `tmux new -s <id>` fails
            # fast on a duplicate session name (ssh.rs:83).  Liveness =
            # process group alive AND the pid's cmdline carries our session
            # marker: `kill -0` alone would trust any recycled pid and
            # silently skip the respawn of a crashed node.
            spawn = (
                f"setsid nohup sh -c {shlex.quote(inner)} > {log} 2>&1 &"
                f" echo $! > {pidfile}"
            )
            return (
                f"if [ -f {pidfile} ] && kill -0 -- -$(cat {pidfile})"
                f" 2>/dev/null && grep -aq -- {shlex.quote(marker)}"
                f" /proc/$(cat {pidfile})/cmdline 2>/dev/null;"
                f" then true; else {spawn}; fi"
            )
        return " ".join(parts + [command])


class SshManager:
    """Retried/parallel command execution over the system ssh/scp binaries.

    ``hosts`` may be ``user@addr`` or bare addresses.  All operations accept
    an optional per-call timeout and retry transient failures with a linear
    backoff (ssh.rs retries :198-236).
    """

    def __init__(
        self,
        hosts: Sequence[str],
        ssh_args: Optional[List[str]] = None,
        retries: int = 3,
        timeout_s: float = 30.0,
        retry_delay_s: float = 2.0,
    ) -> None:
        self.hosts = list(hosts)
        self.ssh_args = list(
            ssh_args
            if ssh_args is not None
            else ["-o", "StrictHostKeyChecking=no", "-o", "ConnectTimeout=10"]
        )
        self.retries = retries
        self.timeout_s = timeout_s
        self.retry_delay_s = retry_delay_s

    # -- transport seam (overridden by tests) --

    async def _spawn(self, argv: List[str], timeout_s: float) -> Tuple[int, bytes]:
        proc = await asyncio.create_subprocess_exec(
            *argv,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.STDOUT,
            stdin=asyncio.subprocess.DEVNULL,
        )
        try:
            out, _ = await asyncio.wait_for(proc.communicate(), timeout=timeout_s)
        except asyncio.TimeoutError:
            proc.kill()
            await proc.wait()
            raise
        return proc.returncode or 0, out

    # -- single-host operations --

    async def execute(
        self,
        host: str,
        command: str,
        context: Optional[CommandContext] = None,
        timeout_s: Optional[float] = None,
    ) -> str:
        """Run a command, retrying transient failures; returns stdout+stderr.

        Raises :class:`SshError` after the final retry (non-zero exit or
        timeout).
        """
        full = (context or CommandContext()).apply(command)
        argv = ["ssh", *self.ssh_args, host, full]
        deadline = timeout_s if timeout_s is not None else self.timeout_s
        last: Optional[str] = None
        for attempt in range(self.retries):
            try:
                rc, out = await self._spawn(argv, deadline)
            except asyncio.TimeoutError:
                last = f"timeout after {deadline}s"
            else:
                if rc == 0:
                    return out.decode(errors="replace")
                last = f"exit {rc}: {out.decode(errors='replace')[-500:]}"
            if attempt + 1 < self.retries:
                await asyncio.sleep(self.retry_delay_s * (attempt + 1))
        raise SshError(f"ssh {host}: {command!r} failed ({last})")

    async def upload(
        self, host: str, local_paths: Sequence[str], remote_dir: str
    ) -> None:
        await self.execute(host, f"mkdir -p {shlex.quote(remote_dir)}")
        argv = ["scp", *self.ssh_args, "-r", *local_paths, f"{host}:{remote_dir}/"]
        await self._retried_copy(argv, f"upload to {host}")

    async def download(self, host: str, remote_path: str, local_path: str) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(local_path)), exist_ok=True)
        argv = ["scp", *self.ssh_args, "-r", f"{host}:{remote_path}", local_path]
        await self._retried_copy(argv, f"download from {host}")

    async def _retried_copy(self, argv: List[str], what: str) -> None:
        last: Optional[str] = None
        for attempt in range(self.retries):
            try:
                rc, out = await self._spawn(argv, self.timeout_s)
            except asyncio.TimeoutError:
                last = "timeout"
            else:
                if rc == 0:
                    return
                last = out.decode(errors="replace")[-500:]
            if attempt + 1 < self.retries:
                await asyncio.sleep(self.retry_delay_s * (attempt + 1))
        raise SshError(f"{what} failed ({last})")

    async def kill_session(self, host: str, session: str) -> None:
        """Kill a background session started with CommandContext(background=)."""
        pidfile = CommandContext(background=session).pidfile()
        await self.execute(
            host,
            f"[ -f {pidfile} ] && kill -- -$(cat {pidfile}) 2>/dev/null;"
            f" rm -f {pidfile}; true",
        )

    async def wait_reachable(self, host: str, timeout_s: float = 300.0) -> None:
        """Poll until the host accepts ssh (ssh.rs `wait_until_reachable`)."""
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout_s
        while True:
            try:
                await self.execute(host, "true", timeout_s=10.0)
                return
            except SshError:
                if loop.time() >= deadline:
                    raise
                await asyncio.sleep(5.0)

    # -- fleet fan-out --

    async def execute_all(
        self,
        command: str,
        context: Optional[CommandContext] = None,
        hosts: Optional[Sequence[str]] = None,
    ) -> List[str]:
        """Run the same command on every host in parallel; raises the first
        failure after all hosts finish (ssh.rs `execute` over instances)."""
        targets = list(hosts if hosts is not None else self.hosts)
        results = await asyncio.gather(
            *(self.execute(h, command, context) for h in targets),
            return_exceptions=True,
        )
        for res in results:
            if isinstance(res, BaseException):
                raise res
        return [r for r in results if isinstance(r, str)]

    async def execute_per_host(
        self,
        commands: Sequence[Tuple[str, str]],
        context: Optional[CommandContext] = None,
    ) -> List[str]:
        """Run a distinct command per (host, command) pair in parallel."""
        results = await asyncio.gather(
            *(self.execute(h, c, context) for h, c in commands),
            return_exceptions=True,
        )
        for res in results:
            if isinstance(res, BaseException):
                raise res
        return [r for r in results if isinstance(r, str)]
