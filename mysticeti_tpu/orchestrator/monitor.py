"""Monitoring stack: prometheus + grafana provisioning for a benchmark fleet.

Capability parity with ``orchestrator/src/monitor.rs`` (:60-184), adapted to
this environment (no package installs): the orchestrator *generates* a ready
prometheus scrape config covering every node's /metrics endpoint plus a
grafana dashboard + datasource provisioning tree, and — when the binaries
happen to exist on the host — can launch prometheus directly.  The generated
tree is also exactly what the reference's grafana/prometheus containers mount.
"""
from __future__ import annotations

import json
import os
import shutil
import subprocess
import time
from typing import List, Optional, Tuple

PROMETHEUS_PORT = 9090
GRAFANA_PORT = 3000


def prometheus_config(targets: List[str], scrape_interval_s: int = 5) -> str:
    """YAML scrape config for the node metric endpoints (monitor.rs:105-148)."""
    lines = [
        "global:",
        f"  scrape_interval: {scrape_interval_s}s",
        f"  evaluation_interval: {scrape_interval_s}s",
        "scrape_configs:",
        "  - job_name: mysticeti-nodes",
        "    static_configs:",
        "      - targets:",
    ]
    for t in targets:
        lines.append(f"          - {t}")
    return "\n".join(lines) + "\n"


def grafana_dashboard() -> dict:
    """The benchmark dashboard: tps, latency percentiles, verifier series
    (orchestrator/assets/grafana-dashboard.json equivalent, built for this
    framework's metric names)."""

    def panel(panel_id, title, expr, y):
        return {
            "id": panel_id,
            "title": title,
            "type": "timeseries",
            "datasource": "mysticeti-prometheus",
            "gridPos": {"h": 8, "w": 12, "x": (panel_id % 2) * 12, "y": y},
            "targets": [{"expr": expr, "refId": "A"}],
        }

    return {
        "title": "mysticeti-tpu benchmark",
        "uid": "mysticeti-tpu",
        "timezone": "utc",
        "refresh": "5s",
        "panels": [
            panel(0, "committed tx/s", "rate(latency_s_count[30s])", 0),
            panel(1, "avg latency (s)",
                  "rate(latency_s_sum[30s]) / rate(latency_s_count[30s])", 0),
            panel(2, "committed leaders/s", "rate(committed_leaders_total[30s])", 8),
            panel(3, "verified signatures/s",
                  "rate(verified_signatures_total[30s])", 8),
            panel(4, "verify batch size p90",
                  "histogram_quantile(0.9, rate(verify_batch_size_bucket[1m]))", 16),
            panel(5, "peer RTT p90",
                  "histogram_quantile(0.9, rate(connection_latency_bucket[1m]))", 16),
            # Fleet health plane (health.py): the "why was it slow" row.
            panel(6, "health: commit rate / round advance",
                  "mysticeti_health_commit_rate", 24),
            panel(7, "health: per-authority frontier lag",
                  "mysticeti_health_authority_lag_rounds", 24),
            panel(8, "health: SLO alerts by kind",
                  "rate(mysticeti_health_slo_alerts_total[1m])", 32),
            panel(9, "commit critical path p90 by stage",
                  "histogram_quantile(0.9, "
                  "rate(commit_critical_path_seconds_bucket[1m]))", 32),
        ],
    }


def grafana_provisioning(out_dir: str) -> None:
    """Write the grafana provisioning tree (datasource + dashboard provider)."""
    ds_dir = os.path.join(out_dir, "grafana", "provisioning", "datasources")
    db_dir = os.path.join(out_dir, "grafana", "provisioning", "dashboards")
    dash_dir = os.path.join(out_dir, "grafana", "dashboards")
    for d in (ds_dir, db_dir, dash_dir):
        os.makedirs(d, exist_ok=True)
    with open(os.path.join(ds_dir, "prometheus.yaml"), "w") as f:
        f.write(
            "apiVersion: 1\n"
            "datasources:\n"
            "  - name: mysticeti-prometheus\n"
            "    type: prometheus\n"
            f"    url: http://127.0.0.1:{PROMETHEUS_PORT}\n"
            "    isDefault: true\n"
        )
    with open(os.path.join(db_dir, "provider.yaml"), "w") as f:
        f.write(
            "apiVersion: 1\n"
            "providers:\n"
            "  - name: mysticeti\n"
            "    folder: ''\n"
            "    type: file\n"
            "    options:\n"
            "      path: /etc/grafana/dashboards\n"
        )
    with open(os.path.join(dash_dir, "mysticeti.json"), "w") as f:
        json.dump(grafana_dashboard(), f, indent=2)


class MonitoringStack:
    """Generate the monitoring tree; start prometheus when available."""

    GRAFANA_STARTUP_GRACE_S = 0.5

    def __init__(self, out_dir: str) -> None:
        self.out_dir = out_dir
        self.prometheus_proc: Optional[subprocess.Popen] = None
        self.grafana_proc: Optional[subprocess.Popen] = None

    def deploy(self, metric_targets: List[str]) -> str:
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(self.out_dir, "prometheus.yaml")
        with open(path, "w") as f:
            f.write(prometheus_config(metric_targets))
        grafana_provisioning(self.out_dir)
        return path

    def start_prometheus(self) -> bool:
        """Launch a local prometheus against the generated config when the
        binary exists; returns False (config-only mode) otherwise."""
        binary = shutil.which("prometheus")
        if binary is None:
            return False
        self.prometheus_proc = subprocess.Popen(
            [
                binary,
                f"--config.file={os.path.join(self.out_dir, 'prometheus.yaml')}",
                f"--storage.tsdb.path={os.path.join(self.out_dir, 'tsdb')}",
                f"--web.listen-address=127.0.0.1:{PROMETHEUS_PORT}",
            ],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        return True

    def start_grafana(self) -> bool:
        """Launch a local grafana against the generated provisioning tree when
        the binary exists (monitor.rs:86-104 ``start_grafana`` parity); returns
        False (config-only mode) otherwise.

        The reference runs the official container with the provisioning dir
        mounted; here the same tree is handed over through grafana's
        ``GF_PATHS_PROVISIONING`` environment override, and the dashboard
        provider path is rewritten to the generated ``grafana/dashboards``
        directory rather than the container's ``/etc/grafana/dashboards``.
        """
        binary = shutil.which("grafana-server") or shutil.which("grafana")
        if binary is None:
            return False
        grafana_dir = os.path.join(self.out_dir, "grafana")
        provider = os.path.join(
            grafana_dir, "provisioning", "dashboards", "provider.yaml")
        if os.path.exists(provider):
            text = open(provider).read().replace(
                "/etc/grafana/dashboards", os.path.join(grafana_dir, "dashboards"))
            with open(provider, "w") as f:
                f.write(text)
        env = dict(os.environ)
        env.update({
            "GF_PATHS_PROVISIONING": os.path.join(grafana_dir, "provisioning"),
            "GF_PATHS_DATA": os.path.join(grafana_dir, "data"),
            "GF_PATHS_LOGS": os.path.join(grafana_dir, "logs"),
            "GF_SERVER_HTTP_PORT": str(GRAFANA_PORT),
            "GF_AUTH_ANONYMOUS_ENABLED": "true",
        })
        # Grafana refuses to start without its homepath (conf/defaults.ini);
        # point it at the conventional install location when present.
        for home in ("/usr/share/grafana", "/opt/grafana"):
            if os.path.isdir(home):
                env["GF_PATHS_HOME"] = home
                break
        args = [binary] if binary.endswith("grafana-server") else [binary, "server"]
        self.grafana_proc = subprocess.Popen(
            args,
            env=env,
            cwd=env.get("GF_PATHS_HOME", grafana_dir),
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        # Liveness check: a misconfigured grafana exits within a moment, and
        # with stderr discarded a bare `return True` would report dashboards
        # up at :3000 with nothing listening.
        time.sleep(self.GRAFANA_STARTUP_GRACE_S)
        if self.grafana_proc.poll() is not None:
            self.grafana_proc = None
            return False
        return True

    # -- remote deployment (monitor.rs:60-105,184) --

    PROM_SESSION = "mysticeti-prometheus"
    GRAFANA_SESSION = "mysticeti-grafana"

    async def deploy_remote(
        self,
        ssh,
        host: str,
        metric_targets: List[str],
        remote_dir: str = "/tmp/mysticeti-monitoring",
    ) -> str:
        """Deploy the stack onto a DEDICATED monitoring instance over ssh —
        the reference configures and (re)starts prometheus + grafana on its
        monitoring instance through the ssh manager (monitor.rs:60-105; the
        grafana address accessor :184).  The locally generated tree (scrape
        config + dashboard provisioning) is uploaded verbatim; both services
        run as background sessions so `kill_session` tears them down.
        Returns the grafana URL on the monitoring host.
        """
        from .ssh import CommandContext

        self.deploy(metric_targets)
        await ssh.upload(
            host,
            [
                os.path.join(self.out_dir, "prometheus.yaml"),
                os.path.join(self.out_dir, "grafana"),
            ],
            remote_dir,
        )
        # The generated dashboard provider points at the container path
        # (/etc/grafana/dashboards); retarget it to the uploaded tree the
        # same way the local launcher does.
        provider = f"{remote_dir}/grafana/provisioning/dashboards/provider.yaml"
        await ssh.execute(
            host,
            f"sed -i 's#/etc/grafana/dashboards#{remote_dir}/grafana/"
            f"dashboards#' {provider}",
        )
        # Restart semantics: kill any previous sessions, then start fresh
        # against the uploaded config (monitor.rs re-runs its setup command
        # list on every deploy).
        await ssh.kill_session(host, self.PROM_SESSION)
        await ssh.execute(
            host,
            f"prometheus --config.file={remote_dir}/prometheus.yaml"
            f" --storage.tsdb.path={remote_dir}/tsdb"
            f" --web.listen-address=0.0.0.0:{PROMETHEUS_PORT}",
            CommandContext(
                background=self.PROM_SESSION,
                log_file=f"{remote_dir}/prometheus.log",
            ),
        )
        await ssh.kill_session(host, self.GRAFANA_SESSION)
        # Same binary-name and homepath tolerance as the local launcher
        # (grafana-server on older installs; GF_PATHS_HOME when a
        # conventional install dir exists).
        grafana_cmd = (
            f"GF_PATHS_PROVISIONING={remote_dir}/grafana/provisioning"
            f" GF_PATHS_DATA={remote_dir}/grafana/data"
            f" GF_SERVER_HTTP_PORT={GRAFANA_PORT}"
            f" GF_AUTH_ANONYMOUS_ENABLED=true"
            ' GF_PATHS_HOME="$([ -d /usr/share/grafana ] &&'
            " echo /usr/share/grafana)\""
            " sh -c 'command -v grafana-server >/dev/null 2>&1 &&"
            " exec grafana-server || exec grafana server'"
        )
        await ssh.execute(
            host,
            grafana_cmd,
            CommandContext(
                background=self.GRAFANA_SESSION,
                log_file=f"{remote_dir}/grafana.log",
            ),
        )
        # Liveness: a background spawn returns 0 whether or not the service
        # survived its first moment — verify both session pidgroups are
        # still alive (the remote analogue of start_grafana's local check).
        for session in (self.PROM_SESSION, self.GRAFANA_SESSION):
            pidfile = CommandContext(background=session).pidfile()
            await ssh.execute(
                host,
                f"sleep 1; kill -0 -$(cat {pidfile})",
            )
        return f"http://{host.split('@')[-1]}:{GRAFANA_PORT}"

    async def stop_remote(self, ssh, host: str) -> None:
        await ssh.kill_session(host, self.PROM_SESSION)
        await ssh.kill_session(host, self.GRAFANA_SESSION)

    def stop(self) -> None:
        for attr in ("prometheus_proc", "grafana_proc"):
            proc = getattr(self, attr)
            setattr(self, attr, None)
            if proc is None:
                continue
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
