"""A real ``ServerProvider``: JSON-REST cloud provisioning client.

Capability parity with the reference's cloud clients
(``orchestrator/src/client/vultr.rs:72-299`` — list/create/start/stop/
terminate over a bearer-token JSON API; ``client/aws.rs:37-393`` is the
same surface against EC2).  This environment has no cloud credentials and
zero egress, so the client is built the way the reference TESTS its
providers (``client/mod.rs:111-160`` ``TestClient``): all HTTP goes
through an injectable :class:`Transport`, and the test suite drives the
full testbed lifecycle against :class:`FixtureTransport` — recorded
request/response pairs — while :class:`UrllibTransport` serves real
deployments.

API shape (Vultr-flavored):

  GET    {base}/instances                 -> {"instances": [...]}
  POST   {base}/instances                 {"region", "plan", "label", "os_id"}
  POST   {base}/instances/{id}/start
  POST   {base}/instances/{id}/halt
  DELETE {base}/instances/{id}

Instances map to the orchestrator's :class:`~.testbed.Instance` via
``id`` / ``main_ip`` / ``region`` / ``power_status``.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

from .testbed import Instance, ServerProvider


class ProviderError(Exception):
    """A provider API call failed (client/mod.rs CloudProviderError)."""


class Transport:
    """One HTTP exchange: (method, url, body|None) -> (status, json-dict)."""

    async def request(self, method: str, url: str,
                      body: Optional[dict] = None,
                      headers: Optional[Dict[str, str]] = None
                      ) -> Tuple[int, dict]:
        raise NotImplementedError


class UrllibTransport(Transport):
    """Real HTTP via urllib in a worker thread (no extra dependencies).
    Only used with real credentials outside this zero-egress environment."""

    def __init__(self, timeout_s: float = 30.0) -> None:
        self.timeout_s = timeout_s

    async def request(self, method, url, body=None, headers=None):
        import asyncio
        import urllib.error
        import urllib.request

        def call():
            data = json.dumps(body).encode() if body is not None else None
            req = urllib.request.Request(
                url, data=data, method=method, headers=headers or {}
            )
            if data is not None:
                req.add_header("Content-Type", "application/json")
            try:
                with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                    raw = r.read()
                    return r.status, json.loads(raw) if raw else {}
            except urllib.error.HTTPError as e:
                raw = e.read()
                try:
                    payload = json.loads(raw) if raw else {}
                except json.JSONDecodeError:
                    payload = {"error": raw.decode(errors="replace")}
                return e.code, payload

        return await asyncio.get_running_loop().run_in_executor(None, call)


class FixtureTransport(Transport):
    """Recorded request->response pairs (client/mod.rs:111-160 TestClient
    posture: the provider logic is tested end-to-end with no network).

    Fixtures: list of {"method", "url", "status", "response"} records;
    each is consumed at most ``repeat`` times (default: unlimited), matched
    on (method, url).  Every exchange is appended to ``calls`` so tests can
    assert the wire conversation — including request bodies.
    """

    def __init__(self, fixtures: Sequence[dict]) -> None:
        self.fixtures = list(fixtures)
        self.calls: List[dict] = []

    async def request(self, method, url, body=None, headers=None):
        self.calls.append(
            {"method": method, "url": url, "body": body}
        )
        for fx in self.fixtures:
            if fx["method"] == method and fx["url"] == url:
                remaining = fx.get("repeat")
                if remaining is not None:
                    if remaining <= 0:
                        continue
                    fx["repeat"] = remaining - 1
                return fx.get("status", 200), fx.get("response", {})
        raise AssertionError(f"no fixture for {method} {url}")


class RestCloudProvider(ServerProvider):
    """Cloud provisioning behind the ``ServerProvider`` seam
    (client/vultr.rs:72-299 capability)."""

    def __init__(
        self,
        base_url: str,
        token: str,
        region: str = "ewr",
        plan: str = "vc2-16c-64gb",
        os_id: int = 1743,
        label: str = "mysticeti-tpu",
        transport: Optional[Transport] = None,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.default_region = region
        self.plan = plan
        self.os_id = os_id
        self.label = label
        self.transport = transport or UrllibTransport()

    def _headers(self) -> Dict[str, str]:
        return {"Authorization": f"Bearer {self.token}"}

    async def _call(self, method: str, path: str,
                    body: Optional[dict] = None) -> dict:
        status, payload = await self.transport.request(
            method, f"{self.base_url}{path}", body, self._headers()
        )
        if status >= 300:
            raise ProviderError(
                f"provider {method} {path} failed ({status}): {payload}"
            )
        return payload

    @staticmethod
    def _to_instance(raw: dict) -> Instance:
        return Instance(
            id=str(raw["id"]),
            host=raw.get("main_ip", ""),
            region=raw.get("region", ""),
            active=raw.get("power_status", "running") == "running",
        )

    # -- ServerProvider --

    async def list_instances(self) -> List[Instance]:
        payload = await self._call("GET", "/instances")
        return [
            self._to_instance(raw)
            for raw in payload.get("instances", [])
            if raw.get("label", self.label) == self.label
        ]

    async def create_instances(self, count: int, region: str) -> List[Instance]:
        created = []
        for _ in range(count):
            payload = await self._call(
                "POST",
                "/instances",
                {
                    "region": region or self.default_region,
                    "plan": self.plan,
                    "label": self.label,
                    "os_id": self.os_id,
                },
            )
            created.append(self._to_instance(payload["instance"]))
        return created

    async def start_instances(self, ids: Sequence[str]) -> None:
        for iid in ids:
            await self._call("POST", f"/instances/{iid}/start")

    async def stop_instances(self, ids: Sequence[str]) -> None:
        for iid in ids:
            await self._call("POST", f"/instances/{iid}/halt")

    async def terminate_instances(self, ids: Sequence[str]) -> None:
        for iid in ids:
            await self._call("DELETE", f"/instances/{iid}")
