"""A real ``ServerProvider``: JSON-REST cloud provisioning client.

Capability parity with the reference's cloud clients
(``orchestrator/src/client/vultr.rs:72-299`` — list/create/start/stop/
terminate over a bearer-token JSON API; ``client/aws.rs:37-393`` is the
same surface against EC2).  This environment has no cloud credentials and
zero egress, so the client is built the way the reference TESTS its
providers (``client/mod.rs:111-160`` ``TestClient``): all HTTP goes
through an injectable :class:`Transport`, and the test suite drives the
full testbed lifecycle against :class:`FixtureTransport` — recorded
request/response pairs — while :class:`UrllibTransport` serves real
deployments.

API shape (Vultr-flavored):

  GET    {base}/instances                 -> {"instances": [...]}
  POST   {base}/instances                 {"region", "plan", "label", "os_id"}
  POST   {base}/instances/{id}/start
  POST   {base}/instances/{id}/halt
  DELETE {base}/instances/{id}

Instances map to the orchestrator's :class:`~.testbed.Instance` via
``id`` / ``main_ip`` / ``region`` / ``power_status``.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

from .testbed import Instance, ServerProvider


class ProviderError(Exception):
    """A provider API call failed (client/mod.rs CloudProviderError)."""


class Transport:
    """One HTTP exchange: (method, url, body|None) -> (status, json-dict)."""

    async def request(self, method: str, url: str,
                      body: Optional[dict] = None,
                      headers: Optional[Dict[str, str]] = None
                      ) -> Tuple[int, dict]:
        raise NotImplementedError


class UrllibTransport(Transport):
    """Real HTTP via urllib in a worker thread (no extra dependencies).
    Only used with real credentials outside this zero-egress environment."""

    def __init__(self, timeout_s: float = 30.0) -> None:
        self.timeout_s = timeout_s

    async def request(self, method, url, body=None, headers=None):
        import asyncio
        import urllib.error
        import urllib.request

        def call():
            data = json.dumps(body).encode() if body is not None else None
            req = urllib.request.Request(
                url, data=data, method=method, headers=headers or {}
            )
            if data is not None:
                req.add_header("Content-Type", "application/json")
            try:
                with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                    raw = r.read()
                    return r.status, json.loads(raw) if raw else {}
            except urllib.error.HTTPError as e:
                raw = e.read()
                try:
                    payload = json.loads(raw) if raw else {}
                except json.JSONDecodeError:
                    payload = {"error": raw.decode(errors="replace")}
                return e.code, payload

        return await asyncio.get_running_loop().run_in_executor(None, call)


class FixtureTransport(Transport):
    """Recorded request->response pairs (client/mod.rs:111-160 TestClient
    posture: the provider logic is tested end-to-end with no network).

    Fixtures: list of {"method", "url", "status", "response"} records;
    each is consumed at most ``repeat`` times (default: unlimited), matched
    on (method, url).  Every exchange is appended to ``calls`` so tests can
    assert the wire conversation — including request bodies.
    """

    def __init__(self, fixtures: Sequence[dict]) -> None:
        self.fixtures = list(fixtures)
        self.calls: List[dict] = []

    async def request(self, method, url, body=None, headers=None):
        self.calls.append(
            {"method": method, "url": url, "body": body}
        )
        for fx in self.fixtures:
            if fx["method"] == method and fx["url"] == url:
                remaining = fx.get("repeat")
                if remaining is not None:
                    if remaining <= 0:
                        continue
                    fx["repeat"] = remaining - 1
                return fx.get("status", 200), fx.get("response", {})
        raise AssertionError(f"no fixture for {method} {url}")


class RestCloudProvider(ServerProvider):
    """Cloud provisioning behind the ``ServerProvider`` seam
    (client/vultr.rs:72-299 capability)."""

    def __init__(
        self,
        base_url: str,
        token: str,
        region: str = "ewr",
        plan: str = "vc2-16c-64gb",
        os_id: int = 1743,
        label: str = "mysticeti-tpu",
        transport: Optional[Transport] = None,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.default_region = region
        self.plan = plan
        self.os_id = os_id
        self.label = label
        self.transport = transport or UrllibTransport()

    def _headers(self) -> Dict[str, str]:
        return {"Authorization": f"Bearer {self.token}"}

    async def _call(self, method: str, path: str,
                    body: Optional[dict] = None) -> dict:
        status, payload = await self.transport.request(
            method, f"{self.base_url}{path}", body, self._headers()
        )
        if status >= 300:
            raise ProviderError(
                f"provider {method} {path} failed ({status}): {payload}"
            )
        return payload

    @staticmethod
    def _to_instance(raw: dict) -> Instance:
        return Instance(
            id=str(raw["id"]),
            host=raw.get("main_ip", ""),
            region=raw.get("region", ""),
            active=raw.get("power_status", "running") == "running",
        )

    # -- ServerProvider --

    async def list_instances(self) -> List[Instance]:
        payload = await self._call("GET", "/instances")
        return [
            self._to_instance(raw)
            for raw in payload.get("instances", [])
            if raw.get("label", self.label) == self.label
        ]

    async def create_instances(self, count: int, region: str) -> List[Instance]:
        created = []
        for _ in range(count):
            payload = await self._call(
                "POST",
                "/instances",
                {
                    "region": region or self.default_region,
                    "plan": self.plan,
                    "label": self.label,
                    "os_id": self.os_id,
                },
            )
            created.append(self._to_instance(payload["instance"]))
        return created

    async def start_instances(self, ids: Sequence[str]) -> None:
        for iid in ids:
            await self._call("POST", f"/instances/{iid}/start")

    async def stop_instances(self, ids: Sequence[str]) -> None:
        for iid in ids:
            await self._call("POST", f"/instances/{iid}/halt")

    async def terminate_instances(self, ids: Sequence[str]) -> None:
        for iid in ids:
            await self._call("DELETE", f"/instances/{iid}")


# EC2 instance lifecycle states (client/aws.rs:37-393 drives the same set):
# pending/running count as active inventory; shutting-down/terminated
# instances are on their way out and never listed as claimable.
EC2_ACTIVE_STATES = frozenset({"pending", "running"})
EC2_GONE_STATES = frozenset({"shutting-down", "terminated"})


class Ec2Provider(ServerProvider):
    """AWS/EC2-surface provisioning behind the ``ServerProvider`` seam
    (``client/aws.rs:37-393`` capability): region-scoped inventory with a
    per-region AMI map, an ensured security group, and the EC2 instance
    lifecycle state machine (pending -> running -> stopping -> stopped,
    shutting-down -> terminated), all through the same injectable
    :class:`Transport` the REST provider uses — tested end-to-end against
    recorded fixtures, exactly like the reference's TestClient.

    API shape (EC2-flavored JSON surface; region scopes every path the way
    the EC2 endpoint hostname does):

      GET    {base}/{region}/instances            -> {"reservations": [
                                                       {"instances": [...]}]}
      POST   {base}/{region}/instances            (RunInstances)
      POST   {base}/{region}/instances/{id}/start
      POST   {base}/{region}/instances/{id}/stop
      DELETE {base}/{region}/instances/{id}       (TerminateInstances)
      GET    {base}/{region}/security-groups      -> {"security_groups": [...]}
      POST   {base}/{region}/security-groups      (create + authorize ingress)

    Instances map via ``instance_id`` / ``public_ip`` / ``state.name`` /
    ``placement.availability_zone``; ownership is claimed through the
    ``Name`` tag (aws.rs filters on the same tag).
    """

    def __init__(
        self,
        base_url: str,
        token: str,
        amis: Dict[str, str],
        instance_type: str = "m5d.8xlarge",
        security_group: str = "mysticeti-tpu",
        label: str = "mysticeti-tpu",
        default_region: Optional[str] = None,
        transport: Optional[Transport] = None,
    ) -> None:
        if not amis:
            raise ValueError("Ec2Provider needs a region -> AMI map")
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.amis = dict(amis)
        self.default_region = default_region or self.regions[0]
        self.instance_type = instance_type
        self.security_group = security_group
        self.label = label
        self.transport = transport or UrllibTransport()
        # id -> region: EC2 lifecycle calls are region-scoped, so the
        # provider remembers where each instance lives (refreshed by every
        # list/create; unknown ids trigger one inventory refresh).
        self._region_of: Dict[str, str] = {}
        self._sg_ready: Dict[str, bool] = {}

    @property
    def regions(self) -> List[str]:
        return sorted(self.amis)

    def _headers(self) -> Dict[str, str]:
        return {"Authorization": f"Bearer {self.token}"}

    async def _call(self, method: str, path: str,
                    body: Optional[dict] = None) -> dict:
        status, payload = await self.transport.request(
            method, f"{self.base_url}{path}", body, self._headers()
        )
        if status >= 300:
            raise ProviderError(
                f"provider {method} {path} failed ({status}): {payload}"
            )
        return payload

    def _to_instance(self, raw: dict, region: str) -> Instance:
        iid = str(raw["instance_id"])
        self._region_of[iid] = region
        az = (raw.get("placement") or {}).get("availability_zone", "")
        return Instance(
            id=iid,
            host=raw.get("public_ip", ""),
            region=az or region,
            active=(raw.get("state") or {}).get("name") in EC2_ACTIVE_STATES,
        )

    def _owned(self, raw: dict) -> bool:
        """Ownership is the Name tag being PRESENT and equal (aws.rs filters
        the same way); an untagged foreign instance must never be claimed —
        a later ``destroy`` would terminate someone else's machine."""
        tags = {
            t.get("key"): t.get("value") for t in (raw.get("tags") or [])
        }
        return tags.get("Name") == self.label

    async def _ensure_security_group(self, region: str) -> None:
        """Describe-then-create (aws.rs creates its ``mysticeti`` group with
        the node/metrics ingress rules before the first RunInstances)."""
        if self._sg_ready.get(region):
            return
        payload = await self._call("GET", f"/{region}/security-groups")
        names = {
            g.get("group_name")
            for g in payload.get("security_groups", [])
        }
        if self.security_group not in names:
            await self._call(
                "POST",
                f"/{region}/security-groups",
                {
                    "group_name": self.security_group,
                    "description": "mysticeti-tpu benchmark fleet",
                    "ingress": [
                        {"protocol": "tcp", "port_range": "22"},
                        {"protocol": "tcp", "port_range": "1500-2000"},
                    ],
                },
            )
        self._sg_ready[region] = True

    # -- ServerProvider --

    async def list_instances(self) -> List[Instance]:
        out: List[Instance] = []
        for region in self.regions:
            payload = await self._call("GET", f"/{region}/instances")
            for reservation in payload.get("reservations", []):
                for raw in reservation.get("instances", []):
                    if not self._owned(raw):
                        continue
                    state = (raw.get("state") or {}).get("name")
                    if state in EC2_GONE_STATES:
                        continue
                    out.append(self._to_instance(raw, region))
        return out

    async def create_instances(self, count: int, region: str) -> List[Instance]:
        # "local" is the fleet CLI's placeholder default, not an EC2 region:
        # fall back to the configured default so `fleet deploy` works
        # without an explicit --region.  A genuinely unknown region still
        # errors loudly below.
        if region in (None, "", "local"):
            region = self.default_region
        ami = self.amis.get(region)
        if ami is None:
            raise ProviderError(
                f"no AMI configured for region {region!r} "
                f"(known: {self.regions})"
            )
        await self._ensure_security_group(region)
        payload = await self._call(
            "POST",
            f"/{region}/instances",
            {
                "image_id": ami,
                "instance_type": self.instance_type,
                "min_count": count,
                "max_count": count,
                "security_groups": [self.security_group],
                "tags": [{"key": "Name", "value": self.label}],
            },
        )
        return [
            self._to_instance(raw, region)
            for raw in payload.get("instances", [])
        ]

    async def _region_for(self, iid: str) -> str:
        region = self._region_of.get(iid)
        if region is None:
            await self.list_instances()  # refresh the id -> region map
            region = self._region_of.get(iid)
        if region is None:
            raise ProviderError(f"unknown instance id {iid!r}")
        return region

    async def start_instances(self, ids: Sequence[str]) -> None:
        for iid in ids:
            region = await self._region_for(iid)
            await self._call("POST", f"/{region}/instances/{iid}/start")

    async def stop_instances(self, ids: Sequence[str]) -> None:
        for iid in ids:
            region = await self._region_for(iid)
            await self._call("POST", f"/{region}/instances/{iid}/stop")

    async def terminate_instances(self, ids: Sequence[str]) -> None:
        for iid in ids:
            region = await self._region_for(iid)
            await self._call("DELETE", f"/{region}/instances/{iid}")
            self._region_of.pop(iid, None)
