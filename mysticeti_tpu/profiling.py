"""Sampling profiler + per-subsystem CPU accountant + flamegraph rendering.

Capability parity with the reference's flamegraph pipeline
(``orchestrator/assets/mkflamegraph.sh``: perf record -F 99 -g → stackcollapse
→ flamegraph.pl), re-imagined for a Python/JAX node: an in-process sampling
profiler reads every thread's stack via ``sys._current_frames()`` at a fixed
rate and aggregates *folded stacks* (the stackcollapse format), and
:func:`flamegraph_svg` renders folded stacks straight to a self-contained
SVG — no perf, no external scripts.

Host attribution plane (docs/observability.md): the same per-tick stack walk
also feeds a :class:`SubsystemAccountant` — every sampled stack resolves to
exactly one entry of the declarative :data:`SUBSYSTEMS` registry (the
totality of the mapping over the package is pinned by a lint-style test), so
the node continuously exports ``mysticeti_cpu_seconds_total{subsystem,
thread_class}`` and per-committed-leader normalized costs instead of one
whole-process flame dump.  The census walk additionally estimates the GIL
convoy (ticks where ≥2 threads were runnable at once) — with one interpreter
lock, two runnable threads means one of them is waiting for the GIL.

Wire-up: ``MYSTICETI_PROFILE=/path/out.folded`` makes the node CLI sample
for its whole lifetime and write the folded file at shutdown;
``python tools/mkflamegraph.py out.folded > flame.svg`` renders it and
``--diff base.folded new.folded`` renders an A/B flame diff.
``MYSTICETI_PERF_REPORT=/path/report.json`` writes the deterministic
attribution report at shutdown (tools/perf_attr.py consumes it).
"""
from __future__ import annotations

import json
import os
import sys
import threading
from collections import Counter
from html import escape
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

DEFAULT_HZ = 99.0  # the classic perf sampling rate (mkflamegraph.sh -F 99)

# ---------------------------------------------------------------------------
# The subsystem registry
# ---------------------------------------------------------------------------
#
# Declarative module-basename -> subsystem map.  Every module under
# ``mysticeti_tpu/`` must resolve through this table (totality is enforced by
# tests/test_hostattr.py the same way the span-names lint pins STAGES), so a
# new module cannot silently land its CPU time in "other".  Frames from
# outside the package (jax, numpy, stdlib) never match here — attribution
# walks leaf→root and charges the first *in-package* frame, so a numpy core
# routine called from serde.py is charged to mesh-parse, not to "other".

SUBSYSTEMS: Dict[str, str] = {
    # Consensus core: DAG state machine + the single-owner core task.
    "core": "core", "core_task": "core", "syncer": "core",
    "block_manager": "core", "block_handler": "core",
    "threshold_clock": "core", "state": "core", "committee": "core",
    "config": "core", "types": "core", "range_map": "core",
    "dag": "core", "lock": "core", "tasks": "core", "epoch_close": "core",
    # Epoch reconfiguration: the fold runs inline on the core commit path.
    "reconfig": "core",
    # Execution state machine: folded inline on the core commit path.
    "execution": "core",
    # Commit linearization + interpretation.
    "linearizer": "linearizer", "base_committer": "linearizer",
    "universal_committer": "linearizer", "commit_observer": "linearizer",
    "finalization_interpreter": "linearizer",
    # Decision ledger: recorded inline from try_commit on the core path.
    "decisions": "linearizer",
    # Host-side digest/signature oracles.
    "crypto": "digest", "_ed25519_py": "digest",
    # Verifier hot path: batch collection, packing, kernels.
    "block_validator": "verifier-pack", "verify_pipeline": "verifier-pack",
    "verifier_service": "verifier-pack", "ed25519": "verifier-pack",
    "ed25519_pallas": "verifier-pack", "field": "verifier-pack",
    "scalar": "verifier-pack", "sha512": "verifier-pack",
    "mesh": "verifier-pack",
    # Durability plane.
    "wal": "wal", "storage": "wal", "block_store": "wal",
    # Client ingress (finality tracks submit→finality over ingress keys).
    "ingress": "ingress", "transactions_generator": "ingress",
    "finality": "ingress",
    # Mesh data plane: frame encode/fan-out vs receive/decode.
    "net_sync": "mesh-parse", "synchronizer": "mesh-encode",
    "network": "mesh-encode", "simulated_network": "mesh-encode",
    "serde": "mesh-parse",
    # Observability plane itself (metrics sweeps, tracing, this module).
    "metrics": "obs", "health": "obs", "spans": "obs", "tracing": "obs",
    "profiling": "obs", "flight_recorder": "obs", "hostattr": "obs",
    "log": "obs",
    # Tooling / harness code that can appear inside a node process.
    "cli": "tooling", "__main__": "tooling", "adversary": "tooling",
    "chaos": "tooling", "scenarios": "tooling", "checker": "tooling",
    "detflow": "tooling", "races": "tooling", "lockgraph": "tooling",
    "detsan": "tooling",
    "benchmark": "tooling", "display": "tooling", "faults": "tooling",
    "hostmon": "tooling", "logs": "tooling", "measurement": "tooling",
    "monitor": "tooling", "orchestrator": "tooling", "plot": "tooling",
    "providers": "tooling", "runner": "tooling", "settings": "tooling",
    "ssh": "tooling", "testbed": "tooling", "validator": "tooling",
    # Runtime facade + the deterministic loop.
    "__init__": "runtime", "simulated": "runtime",
}

# Exact (module, function) overrides checked before the module map: GC work
# lives inside wal/storage/core modules but is its own budget line (ISSUE 14
# names it a subsystem).  Leaf-most match wins, whole stack is scanned — a
# wal append *inside* retire_below is GC cost, not steady-state WAL cost.
FRAME_SUBSYSTEMS: Dict[Tuple[str, str], str] = {
    ("syncer", "cleanup"): "gc",
    ("storage", "cleanup"): "gc",
    ("storage", "retire_below"): "gc",
    ("storage", "gc_target"): "gc",
    ("block_store", "cleanup"): "gc",
    ("block_store", "retire_below_round"): "gc",
    # Wire-block decode is mesh-parse cost wherever it bottoms out — the
    # leaf-most in-package frame would otherwise charge it to "core"
    # (types.py's module row).  Covers both the inline receive path and
    # the dataplane-offload worker; WAL-reload decode rides along (decode
    # is decode).
    ("types", "from_bytes"): "mesh-parse",
    ("types", "from_bytes_many"): "mesh-parse",
}

# Leaf frames that mean "this thread is parked, not burning CPU": the event
# loop in select, executor/WAL threads waiting on queues and locks.  A tick
# whose stack bottoms out here charges event-loop-idle and does not count as
# runnable for the convoy estimate.
WAITING_LEAVES = frozenset([
    ("selectors", "select"),
    ("selectors", "_select"),
    ("threading", "wait"),
    ("threading", "_wait_for_tstate_lock"),
    ("queue", "get"),
    ("socket", "accept"),
    ("thread", "_worker"),
])

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))

# The full set of subsystem names (tests + budget rows iterate it).
SUBSYSTEM_NAMES: Tuple[str, ...] = tuple(sorted(
    set(SUBSYSTEMS.values())
    | set(FRAME_SUBSYSTEMS.values())
    | {"event-loop-idle", "other"}
))


def attribute(frames: Sequence[Tuple[str, str, bool]]) -> str:
    """Resolve one sampled stack to a subsystem.

    ``frames`` is leaf-first ``(module, function, in_package)`` triples.
    Order of precedence: a parked leaf is idle; any frame matching an exact
    :data:`FRAME_SUBSYSTEMS` override (leaf-most first) wins next — GC work
    is GC wherever it bottoms out; otherwise the leaf-most *in-package*
    frame's module decides — third-party frames (jax, numpy, stdlib) are
    charged to whichever package module called into them.
    """
    if not frames:
        return "other"
    leaf_mod, leaf_fn, _ = frames[0]
    if (leaf_mod, leaf_fn) in WAITING_LEAVES:
        return "event-loop-idle"
    for module, func, _in_pkg in frames:
        sub = FRAME_SUBSYSTEMS.get((module, func))
        if sub is not None:
            return sub
    for module, _func, in_pkg in frames:
        if in_pkg:
            sub = SUBSYSTEMS.get(module)
            if sub is not None:
                return sub
    return "other"


def thread_class_of(name: str) -> str:
    """Coarse thread taxonomy for the cpu-seconds label: the event-loop
    owner, the data-plane offload worker, verifier executor/JAX dispatch
    threads, the WAL writer, rest."""
    if name == "MainThread":
        return "loop"
    low = name.lower()
    # Before the generic "threadpool" catch: the offload pool's threads are
    # named dataplane-offload_N (core_task.DataPlaneOffload) and carry
    # decode/digest burn, not signature verification.
    if "offload" in low:
        return "offload"
    if "verif" in low or "jax" in low or "threadpool" in low:
        return "verifier"
    if "wal" in low or "fsync" in low:
        return "wal"
    return "aux"


class SubsystemAccountant:
    """Per-subsystem CPU-time accumulator fed by the sampler's census.

    ``ingest_census`` is the synthetic-census seam: tests (and the
    determinism pin) feed hand-built censuses and get byte-identical
    reports; in production the sampler thread feeds one census per tick.
    The shared counters are mutated from the sampler thread and read by
    ``publish``/``report`` from the metrics/health side, so every mutation
    holds ``_acct_lock`` (GUARDED_FIELDS, docs/static-analysis.md).
    """

    def __init__(self) -> None:
        self._acct_lock = threading.Lock()
        self._cpu_seconds: Dict[Tuple[str, str], float] = {}
        self._census_ticks = 0
        self._convoy_ticks = 0
        self._runnable_sum = 0
        self._published: Dict[Tuple[str, str], float] = {}
        self._metrics = None
        self._leaders_fn = None

    def bind(self, metrics, leaders_fn=None) -> None:
        """Late-bind the metrics registry (+ committed-leader source for the
        normalized gauges): the sampler starts from the env before the
        validator has built its Metrics."""
        self._metrics = metrics
        self._leaders_fn = leaders_fn

    # -- ingestion (sampler thread; or tests, synthetically) --

    def ingest_census(
        self,
        samples: Sequence[Tuple[str, Sequence[Tuple[str, str, bool]]]],
        dt: float,
    ) -> None:
        """One census tick: ``samples`` is ``(thread_class, frames)`` per
        live thread (frames leaf-first, as :func:`attribute` takes them);
        each thread is charged ``dt`` seconds against its subsystem."""
        attributed: List[Tuple[str, str]] = []
        runnable = 0
        for thread_class, frames in samples:
            sub = attribute(frames)
            attributed.append((sub, thread_class))
            if sub != "event-loop-idle":
                runnable += 1
        with self._acct_lock:
            self._census_ticks += 1
            self._runnable_sum += runnable
            if runnable >= 2:
                # With one GIL, two simultaneously-runnable threads mean one
                # of them is waiting on the interpreter lock this tick.
                self._convoy_ticks += 1
            for key in attributed:
                self._cpu_seconds[key] = self._cpu_seconds.get(key, 0.0) + dt

    # -- export --

    def publish(self) -> None:
        """Sync accumulated deltas into the prometheus series (counter incs
        + the per-leader and convoy gauges).  Called on the sampler's flush
        cadence and at stop; cheap, idempotent, no-op until bound."""
        metrics = self._metrics
        if metrics is None:
            return
        with self._acct_lock:
            totals = dict(self._cpu_seconds)
            census = self._census_ticks
            convoy = self._convoy_ticks
        for key in sorted(totals):
            delta = totals[key] - self._published.get(key, 0.0)
            if delta > 0:
                subsystem, thread_class = key
                metrics.mysticeti_cpu_seconds_total.labels(
                    subsystem, thread_class
                ).inc(delta)
                self._published[key] = totals[key]
        if census:
            metrics.mysticeti_gil_convoy_ratio.set(convoy / census)
        leaders = self._leaders_fn() if self._leaders_fn is not None else 0
        if leaders:
            per_sub: Dict[str, float] = {}
            for (subsystem, _tc), seconds in totals.items():
                if subsystem != "event-loop-idle":
                    per_sub[subsystem] = per_sub.get(subsystem, 0.0) + seconds
            for subsystem in sorted(per_sub):
                metrics.mysticeti_cpu_us_per_leader.labels(subsystem).set(
                    per_sub[subsystem] * 1e6 / leaders
                )

    def report(self) -> dict:
        """The deterministic attribution report: plain rounded numbers,
        sorted keys — a seeded synthetic census reproduces it byte-for-byte
        (pinned by tests/test_hostattr.py)."""
        with self._acct_lock:
            totals = dict(self._cpu_seconds)
            census = self._census_ticks
            convoy = self._convoy_ticks
            runnable = self._runnable_sum
        per_sub: Dict[str, float] = {}
        for (subsystem, _tc), seconds in totals.items():
            per_sub[subsystem] = per_sub.get(subsystem, 0.0) + seconds
        busy = sum(s for k, s in per_sub.items() if k != "event-loop-idle")
        other = per_sub.get("other", 0.0)
        return {
            "census_ticks": census,
            "convoy_ticks": convoy,
            "gil_convoy_ratio": round(convoy / census, 6) if census else 0.0,
            "mean_runnable": round(runnable / census, 6) if census else 0.0,
            "cpu_seconds": {
                f"{sub}/{tc}": round(seconds, 6)
                for (sub, tc), seconds in sorted(totals.items())
            },
            "subsystem_seconds": {
                sub: round(seconds, 6) for sub, seconds in sorted(per_sub.items())
            },
            "attributed_ratio": (
                round((busy - other) / busy, 6) if busy else 1.0
            ),
        }

    def report_bytes(self) -> bytes:
        return (
            json.dumps(self.report(), sort_keys=True, separators=(",", ":"))
            + "\n"
        ).encode()


class SamplingProfiler:
    """Samples all Python threads' stacks into folded-stack counts.

    The sampler thread is a daemon and costs one ``_current_frames`` walk per
    tick (~10 µs per thread) — cheap enough to run for a whole benchmark.
    The same walk feeds the accountant's census when one is attached (one
    stack walk serves both the flamegraph and the attribution plane).
    """

    def __init__(
        self,
        hz: float = DEFAULT_HZ,
        flush_path: Optional[str] = None,
        flush_every_s: float = 10.0,
        accountant: Optional[SubsystemAccountant] = None,
    ) -> None:
        self.interval_s = 1.0 / hz
        self.counts: Counter = Counter()
        # Periodic flush: benchmark fleets kill nodes with SIGKILL (no
        # shutdown path runs), so a profile that only writes at stop() would
        # never land on disk — flush the folded file from the sampler thread.
        self.flush_path = flush_path
        self.flush_every_s = flush_every_s
        self.accountant = accountant
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --

    def start(self) -> "SamplingProfiler":
        if self._thread is not None:
            return self
        # Under the deterministic simulator the node lives in virtual time:
        # a wall-clocked sampler thread would charge arbitrary real time
        # against virtual work and make seeded runs nondeterministic.  Tests
        # exercise the attribution plane through the synthetic-census seam.
        from .runtime import is_simulated

        if is_simulated():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="mysticeti-profiler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join()
        self._thread = None
        if self.accountant is not None:
            self.accountant.publish()

    def __enter__(self) -> "SamplingProfiler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- sampling --

    def _run(self) -> None:
        me = threading.get_ident()
        import time as _time

        next_flush = _time.monotonic() + self.flush_every_s
        while not self._stop.wait(self.interval_s):
            names = {t.ident: t.name for t in threading.enumerate()}
            census: List[Tuple[str, List[Tuple[str, str, bool]]]] = []
            for ident, top in sys._current_frames().items():
                if ident == me:
                    continue
                frames: List[str] = []
                triples: List[Tuple[str, str, bool]] = []
                frame = top
                while frame is not None:
                    code = frame.f_code
                    module = os.path.splitext(
                        os.path.basename(code.co_filename)
                    )[0]
                    frames.append(f"{module}:{code.co_name}")
                    triples.append((
                        module,
                        code.co_name,
                        code.co_filename.startswith(_PKG_DIR),
                    ))
                    frame = frame.f_back
                if frames:
                    self.counts[";".join(reversed(frames))] += 1
                    census.append(
                        (thread_class_of(names.get(ident, "")), triples)
                    )
            if self.accountant is not None and census:
                self.accountant.ingest_census(census, self.interval_s)
            # Sampler-thread body: the profiler never starts under the sim
            # (health.py gates it), so this cadence is real-mode-only.
            if self.flush_path and _time.monotonic() >= next_flush:  # lint: ignore[sim-taint]
                next_flush = _time.monotonic() + self.flush_every_s
                try:
                    self.write_folded(self.flush_path)
                except OSError:
                    pass
                if self.accountant is not None:
                    self.accountant.publish()

    # -- output --

    def folded(self) -> List[str]:
        """Folded-stack lines, most frequent first: ``a;b;c 42``."""
        return [f"{stack} {n}" for stack, n in self.counts.most_common()]

    def write_folded(self, path: str) -> None:
        # Atomic swap: the periodic flush exists to survive SIGKILL, so a
        # kill landing mid-write must not destroy the previous complete
        # flush with a truncated file.
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            for line in self.folded():
                f.write(line + "\n")
        os.replace(tmp, path)


def load_folded(path: str) -> List[str]:
    """Read a folded file, salvaging the torn-profile cases the way
    ``trace_report`` salvages traces: a node SIGKILL'd before its first
    complete flush leaves only ``<path>.tmp`` (possibly with a torn last
    line — the trie builder skips malformed lines), so fall back to it
    rather than dying on the missing main file."""
    for candidate in (path, f"{path}.tmp"):
        try:
            with open(candidate) as f:
                return f.read().splitlines()
        except OSError:
            continue
    raise FileNotFoundError(path)


# ---------------------------------------------------------------------------
# Flamegraph rendering (flamegraph.pl equivalent)
# ---------------------------------------------------------------------------

_FRAME_H = 16
_FONT_SIZE = 11
_PALETTE = ("#e4572e", "#e8864a", "#f0a868", "#f6c28b", "#c96e3b", "#d88c51")


class _Node:
    __slots__ = ("name", "value", "children")

    def __init__(self, name: str) -> None:
        self.name = name
        self.value = 0
        self.children: Dict[str, "_Node"] = {}


def _build_trie(folded_lines: Iterable[str]) -> _Node:
    root = _Node("all")
    for line in folded_lines:
        line = line.strip()
        if not line:
            continue
        stack, _, count_s = line.rpartition(" ")
        try:
            count = int(count_s)
        except ValueError:
            continue
        root.value += count
        node = root
        for frame in stack.split(";"):
            child = node.children.get(frame)
            if child is None:
                child = node.children[frame] = _Node(frame)
            child.value += count
            node = child
    return root


def _depth(node: _Node) -> int:
    return 1 + max((_depth(c) for c in node.children.values()), default=0)


def flamegraph_svg(
    folded_lines: Iterable[str],
    title: str = "mysticeti-tpu flamegraph",
    width: int = 1200,
) -> str:
    """Render folded stacks to a self-contained SVG string.

    Layout matches flamegraph.pl: x = fraction of total samples, one row per
    stack depth, alpha-ordered siblings; every rect carries a ``<title>``
    tooltip with the frame name, sample count, and percentage.
    """
    root = _build_trie(folded_lines)
    if root.value == 0:
        root.value = 1
    height = (_depth(root) + 1) * _FRAME_H + 40
    parts = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}"'
        f' height="{height}" font-family="monospace" font-size="{_FONT_SIZE}">',
        f'<text x="{width // 2}" y="20" text-anchor="middle"'
        f' font-size="14">{escape(title)}</text>',
    ]
    total = root.value

    def emit(node: _Node, x: float, level: int, color_idx: int) -> None:
        w = width * node.value / total
        if w < 0.4:
            return
        y = height - (level + 1) * _FRAME_H - 8
        color = _PALETTE[color_idx % len(_PALETTE)]
        pct = 100.0 * node.value / total
        label = escape(node.name)
        parts.append(
            f'<g><title>{label} ({node.value} samples, {pct:.1f}%)</title>'
            f'<rect x="{x:.1f}" y="{y}" width="{w:.1f}" height="{_FRAME_H - 1}"'
            f' fill="{color}" rx="1"/>'
        )
        if w > 40:
            chars = max(1, int(w / (_FONT_SIZE * 0.62)) - 1)
            parts.append(
                f'<text x="{x + 3:.1f}" y="{y + _FRAME_H - 5}"'
                f' fill="#1a1a1a">{label[:chars]}</text>'
            )
        parts.append("</g>")
        child_x = x
        for i, name in enumerate(sorted(node.children)):
            child = node.children[name]
            emit(child, child_x, level + 1, color_idx + i + 1)
            child_x += width * child.value / total

    emit(root, 0.0, 0, 0)
    parts.append("</svg>")
    return "\n".join(parts)


def _diff_color(delta_pct: float) -> str:
    """flamegraph.pl --negate palette: red = grew vs base, blue = shrank,
    grey = within noise; intensity scales with the delta."""
    if abs(delta_pct) < 0.05:
        return "#c9c9c9"
    mag = min(1.0, abs(delta_pct) / 5.0)  # saturate at a 5-point swing
    fade = int(220 - 150 * mag)
    if delta_pct > 0:
        return f"#ff{fade:02x}{fade:02x}"
    return f"#{fade:02x}{fade:02x}ff"


def flamegraph_diff_svg(
    base_lines: Iterable[str],
    new_lines: Iterable[str],
    title: str = "mysticeti-tpu flame diff",
    width: int = 1200,
) -> str:
    """A/B flame diff: layout follows the NEW profile (x = fraction of new
    samples) and color encodes the per-frame share delta vs the base —
    red frames grew, blue shrank, grey held.  Frames present only in the
    base vanish from the layout (they have zero new width); the summary
    row in the tooltip carries both shares for every surviving frame.
    """
    base_root = _build_trie(base_lines)
    new_root = _build_trie(new_lines)
    if new_root.value == 0:
        new_root.value = 1
    base_total = base_root.value or 1
    total = new_root.value
    height = (_depth(new_root) + 1) * _FRAME_H + 40
    parts = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}"'
        f' height="{height}" font-family="monospace" font-size="{_FONT_SIZE}">',
        f'<text x="{width // 2}" y="20" text-anchor="middle"'
        f' font-size="14">{escape(title)} (red grew / blue shrank)</text>',
    ]

    def emit(node: _Node, base: Optional[_Node], x: float, level: int) -> None:
        w = width * node.value / total
        if w < 0.4:
            return
        y = height - (level + 1) * _FRAME_H - 8
        new_pct = 100.0 * node.value / total
        base_pct = 100.0 * (base.value if base is not None else 0) / base_total
        delta = new_pct - base_pct
        label = escape(node.name)
        parts.append(
            f'<g><title>{label} ({new_pct:.1f}% vs {base_pct:.1f}% base, '
            f'{delta:+.1f} pts)</title>'
            f'<rect x="{x:.1f}" y="{y}" width="{w:.1f}" height="{_FRAME_H - 1}"'
            f' fill="{_diff_color(delta)}" rx="1"/>'
        )
        if w > 40:
            chars = max(1, int(w / (_FONT_SIZE * 0.62)) - 1)
            parts.append(
                f'<text x="{x + 3:.1f}" y="{y + _FRAME_H - 5}"'
                f' fill="#1a1a1a">{label[:chars]}</text>'
            )
        parts.append("</g>")
        child_x = x
        for name in sorted(node.children):
            child = node.children[name]
            base_child = base.children.get(name) if base is not None else None
            emit(child, base_child, child_x, level + 1)
            child_x += width * child.value / total

    emit(new_root, base_root, 0.0, 0)
    parts.append("</svg>")
    return "\n".join(parts)


def render_file(folded_path: str, svg_path: Optional[str] = None) -> str:
    """Render a folded file to SVG; returns the SVG path."""
    svg = flamegraph_svg(
        load_folded(folded_path), title=os.path.basename(folded_path)
    )
    out = svg_path or folded_path.rsplit(".", 1)[0] + ".svg"
    with open(out, "w") as f:
        f.write(svg)
    return out


def render_diff(
    base_path: str, new_path: str, svg_path: Optional[str] = None
) -> str:
    """Render an A/B flame diff of two folded files; returns the SVG path."""
    svg = flamegraph_diff_svg(
        load_folded(base_path),
        load_folded(new_path),
        title=f"{os.path.basename(base_path)} → {os.path.basename(new_path)}",
    )
    out = svg_path or new_path.rsplit(".", 1)[0] + ".diff.svg"
    with open(out, "w") as f:
        f.write(svg)
    return out


_active: Optional[SamplingProfiler] = None


def start_from_env() -> Optional[SamplingProfiler]:
    """Start lifetime profiling when ``MYSTICETI_PROFILE`` is set; the node
    CLI calls this at boot and :func:`stop_from_env` at shutdown."""
    global _active
    path = os.environ.get("MYSTICETI_PROFILE")
    if not path or _active is not None:
        return None
    # "%p" -> pid so one env var serves a whole local fleet without the
    # nodes clobbering each other's profiles.
    path = path.replace("%p", str(os.getpid()))
    _active = SamplingProfiler(
        flush_path=path, accountant=SubsystemAccountant()
    ).start()
    return _active


def bind_active(metrics, leaders_fn=None) -> None:
    """Bind the env-started sampler's accountant to a node's metrics (and
    committed-leader source).  No-op when profiling is off — the validator
    calls this unconditionally at health-plane boot."""
    if _active is not None and _active.accountant is not None:
        _active.accountant.bind(metrics, leaders_fn=leaders_fn)


def active_accountant() -> Optional[SubsystemAccountant]:
    return _active.accountant if _active is not None else None


def write_report_from_env() -> Optional[str]:
    """Write the attribution report when ``MYSTICETI_PERF_REPORT`` is set
    (atomic, %p-expanded); returns the path written."""
    path = os.environ.get("MYSTICETI_PERF_REPORT")
    if not path or _active is None or _active.accountant is None:
        return None
    path = path.replace("%p", str(os.getpid()))
    # The written file carries the native data-plane inventory alongside
    # the attribution numbers (A/B harnesses record which path the node
    # ran); report_bytes() itself stays environment-independent — the
    # seeded census pin in tests/test_hostattr.py covers it, not this.
    doc = json.loads(_active.accountant.report_bytes())
    try:
        from .native import active_functions

        doc["native_active"] = list(active_functions())
    except Exception:  # noqa: BLE001 - inventory is best-effort evidence
        pass
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True, separators=(",", ":"))
        f.write("\n")
    os.replace(tmp, path)
    return path


def stop_from_env() -> None:
    global _active
    path = os.environ.get("MYSTICETI_PROFILE")
    if _active is None or not path:
        return
    path = path.replace("%p", str(os.getpid()))
    _active.stop()
    _active.write_folded(path)
    render_file(path)
    write_report_from_env()
    _active = None
