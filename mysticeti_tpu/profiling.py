"""Sampling profiler + flamegraph rendering for running nodes.

Capability parity with the reference's flamegraph pipeline
(``orchestrator/assets/mkflamegraph.sh``: perf record -F 99 -g → stackcollapse
→ flamegraph.pl), re-imagined for a Python/JAX node: an in-process sampling
profiler reads every thread's stack via ``sys._current_frames()`` at a fixed
rate and aggregates *folded stacks* (the stackcollapse format), and
:func:`flamegraph_svg` renders folded stacks straight to a self-contained
SVG — no perf, no external scripts.

Wire-up: ``MYSTICETI_PROFILE=/path/out.folded`` makes the node CLI sample
for its whole lifetime and write the folded file at shutdown;
``python -m tools.mkflamegraph out.folded > flame.svg`` renders it.
"""
from __future__ import annotations

import os
import sys
import threading
from collections import Counter
from html import escape
from typing import Dict, Iterable, List, Optional, Tuple

DEFAULT_HZ = 99.0  # the classic perf sampling rate (mkflamegraph.sh -F 99)


class SamplingProfiler:
    """Samples all Python threads' stacks into folded-stack counts.

    The sampler thread is a daemon and costs one ``_current_frames`` walk per
    tick (~10 µs per thread) — cheap enough to run for a whole benchmark.
    """

    def __init__(
        self,
        hz: float = DEFAULT_HZ,
        flush_path: Optional[str] = None,
        flush_every_s: float = 10.0,
    ) -> None:
        self.interval_s = 1.0 / hz
        self.counts: Counter = Counter()
        # Periodic flush: benchmark fleets kill nodes with SIGKILL (no
        # shutdown path runs), so a profile that only writes at stop() would
        # never land on disk — flush the folded file from the sampler thread.
        self.flush_path = flush_path
        self.flush_every_s = flush_every_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --

    def start(self) -> "SamplingProfiler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="mysticeti-profiler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join()
        self._thread = None

    def __enter__(self) -> "SamplingProfiler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- sampling --

    def _run(self) -> None:
        me = threading.get_ident()
        import time as _time

        next_flush = _time.monotonic() + self.flush_every_s
        while not self._stop.wait(self.interval_s):
            for ident, top in sys._current_frames().items():
                if ident == me:
                    continue
                frames: List[str] = []
                frame = top
                while frame is not None:
                    code = frame.f_code
                    module = os.path.splitext(os.path.basename(code.co_filename))[0]
                    frames.append(f"{module}:{code.co_name}")
                    frame = frame.f_back
                if frames:
                    self.counts[";".join(reversed(frames))] += 1
            if self.flush_path and _time.monotonic() >= next_flush:
                next_flush = _time.monotonic() + self.flush_every_s
                try:
                    self.write_folded(self.flush_path)
                except OSError:
                    pass

    # -- output --

    def folded(self) -> List[str]:
        """Folded-stack lines, most frequent first: ``a;b;c 42``."""
        return [f"{stack} {n}" for stack, n in self.counts.most_common()]

    def write_folded(self, path: str) -> None:
        # Atomic swap: the periodic flush exists to survive SIGKILL, so a
        # kill landing mid-write must not destroy the previous complete
        # flush with a truncated file.
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            for line in self.folded():
                f.write(line + "\n")
        os.replace(tmp, path)


# ---------------------------------------------------------------------------
# Flamegraph rendering (flamegraph.pl equivalent)
# ---------------------------------------------------------------------------

_FRAME_H = 16
_FONT_SIZE = 11
_PALETTE = ("#e4572e", "#e8864a", "#f0a868", "#f6c28b", "#c96e3b", "#d88c51")


class _Node:
    __slots__ = ("name", "value", "children")

    def __init__(self, name: str) -> None:
        self.name = name
        self.value = 0
        self.children: Dict[str, "_Node"] = {}


def _build_trie(folded_lines: Iterable[str]) -> _Node:
    root = _Node("all")
    for line in folded_lines:
        line = line.strip()
        if not line:
            continue
        stack, _, count_s = line.rpartition(" ")
        try:
            count = int(count_s)
        except ValueError:
            continue
        root.value += count
        node = root
        for frame in stack.split(";"):
            child = node.children.get(frame)
            if child is None:
                child = node.children[frame] = _Node(frame)
            child.value += count
            node = child
    return root


def _depth(node: _Node) -> int:
    return 1 + max((_depth(c) for c in node.children.values()), default=0)


def flamegraph_svg(
    folded_lines: Iterable[str],
    title: str = "mysticeti-tpu flamegraph",
    width: int = 1200,
) -> str:
    """Render folded stacks to a self-contained SVG string.

    Layout matches flamegraph.pl: x = fraction of total samples, one row per
    stack depth, alpha-ordered siblings; every rect carries a ``<title>``
    tooltip with the frame name, sample count, and percentage.
    """
    root = _build_trie(folded_lines)
    if root.value == 0:
        root.value = 1
    height = (_depth(root) + 1) * _FRAME_H + 40
    parts = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}"'
        f' height="{height}" font-family="monospace" font-size="{_FONT_SIZE}">',
        f'<text x="{width // 2}" y="20" text-anchor="middle"'
        f' font-size="14">{escape(title)}</text>',
    ]
    total = root.value

    def emit(node: _Node, x: float, level: int, color_idx: int) -> None:
        w = width * node.value / total
        if w < 0.4:
            return
        y = height - (level + 1) * _FRAME_H - 8
        color = _PALETTE[color_idx % len(_PALETTE)]
        pct = 100.0 * node.value / total
        label = escape(node.name)
        parts.append(
            f'<g><title>{label} ({node.value} samples, {pct:.1f}%)</title>'
            f'<rect x="{x:.1f}" y="{y}" width="{w:.1f}" height="{_FRAME_H - 1}"'
            f' fill="{color}" rx="1"/>'
        )
        if w > 40:
            chars = max(1, int(w / (_FONT_SIZE * 0.62)) - 1)
            parts.append(
                f'<text x="{x + 3:.1f}" y="{y + _FRAME_H - 5}"'
                f' fill="#1a1a1a">{label[:chars]}</text>'
            )
        parts.append("</g>")
        child_x = x
        for i, name in enumerate(sorted(node.children)):
            child = node.children[name]
            emit(child, child_x, level + 1, color_idx + i + 1)
            child_x += width * child.value / total

    emit(root, 0.0, 0, 0)
    parts.append("</svg>")
    return "\n".join(parts)


def render_file(folded_path: str, svg_path: Optional[str] = None) -> str:
    """Render a folded file to SVG; returns the SVG path."""
    with open(folded_path) as f:
        svg = flamegraph_svg(f, title=os.path.basename(folded_path))
    out = svg_path or folded_path.rsplit(".", 1)[0] + ".svg"
    with open(out, "w") as f:
        f.write(svg)
    return out


_active: Optional[SamplingProfiler] = None


def start_from_env() -> Optional[SamplingProfiler]:
    """Start lifetime profiling when ``MYSTICETI_PROFILE`` is set; the node
    CLI calls this at boot and :func:`stop_from_env` at shutdown."""
    global _active
    path = os.environ.get("MYSTICETI_PROFILE")
    if not path or _active is not None:
        return None
    # "%p" -> pid so one env var serves a whole local fleet without the
    # nodes clobbering each other's profiles.
    path = path.replace("%p", str(os.getpid()))
    _active = SamplingProfiler(flush_path=path).start()
    return _active


def stop_from_env() -> None:
    global _active
    path = os.environ.get("MYSTICETI_PROFILE")
    if _active is None or not path:
        return
    path = path.replace("%p", str(os.getpid()))
    _active.stop()
    _active.write_folded(path)
    render_file(path)
    _active = None
