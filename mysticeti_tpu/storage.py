"""Storage lifecycle plane: segmented WAL, commit-anchored checkpoints, DAG GC,
and snapshot catch-up.

The reference prototype (mysticeti-core) runs benchmarks measured in minutes
and leaves storage lifecycle open: one append-only WAL file, recovery replays
from byte zero, and a fresh/long-crashed validator pulls all history
block-by-block.  At sustained load an unbounded log fills a disk in hours and
bootstrap cost is O(history).  This module closes that gap with four pieces:

* **Segmented WAL** — :class:`SegmentedWalWriter` rolls to a new
  ``wal.NNNNNN`` segment when the active one would exceed
  ``StorageParameters.segment_bytes``, under an atomically-rewritten
  ``MANIFEST.json`` (tmp + rename + dir fsync).  A :data:`WalPosition` stays
  one u64 — a *logical* byte offset, contiguous across segments — so every
  downstream consumer (``OwnBlockData.next_entry``, index entries, pending
  cursors) is untouched; the manifest maps offsets to (segment, local
  offset).  The torn-tail truncation contract is preserved on the active
  segment; a tear discovered in a sealed segment drops every later segment
  (the entries after it were never replayable anyway) and reopens the torn
  segment as active.
* **Commit-anchored checkpoints** — every ``checkpoint_interval`` committed
  leaders, :class:`StorageLifecycle` writes a crc-framed
  ``checkpoint.HHHHHHHHHHHH`` file: the WAL replay position, the commit
  height + committed-leader digest chain, the serialized recovery state
  above the GC floor (pending queue, last own block, handler state, observer
  aggregator state, committed refs, block index).  ``open_store`` boots from
  the newest *valid* checkpoint and replays only WAL entries after it,
  falling back to the previous checkpoint (we keep :data:`CHECKPOINT_KEEP`)
  on a torn/corrupt one, and to full replay when none survives.
* **DAG garbage collection** — ``gc_depth`` rounds behind the last committed
  leader becomes the *retired floor*: index entries below it leave the block
  store, sealed segments whose every block is below it (and which no kept
  checkpoint still needs for replay) are deleted, reclaiming disk.  The
  linearizer and block manager treat references below the floor as settled
  (the standard Mysticeti GC semantic: commits never reach below gc_round).
* **Snapshot catch-up** — a :class:`SnapshotManifest` (commit height, last
  committed leader, digest chain, retired floor, committed refs above it)
  served over wire tags 9/10/11 (docs/wire-format.md §5) lets a far-behind peer
  adopt the fleet's commit baseline and fetch only the O(recent) block
  window above the floor instead of replaying history.

Single-file logs remain first-class: ``open_wal`` with
``segment_bytes <= 0`` returns the plain ``walf`` pair (no rolling, no
checkpoints, no GC) and an existing single-file log is migrated into a
segment directory on first segmented open.
"""
from __future__ import annotations

import json
import os
import threading
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .config import StorageParameters
from .serde import Reader, SerdeError, Writer
from .tracing import logger
from .types import BlockReference
from .wal import (
    HEADER_SIZE,
    WalError,
    WalPosition,
    WalReader,
    WalWriter,
    walf,
)

log = logger(__name__)

MANIFEST_NAME = "MANIFEST.json"
SEGMENT_PREFIX = "wal."
CHECKPOINT_PREFIX = "checkpoint."
CHECKPOINT_KEEP = 2  # newest N checkpoint files survive pruning

CHECKPOINT_MAGIC = 0x31504B43  # b"CKP1" little-endian
SNAPSHOT_MAGIC = 0x31504E53  # b"SNP1" little-endian

ZERO_DIGEST = b"\x00" * 32


def fold_leader_digest(digest: bytes, leader: BlockReference) -> bytes:
    """One step of the committed-leader digest chain:
    ``d_h = BLAKE2b-256(d_{h-1} || leader_ref_bytes)``.

    A 32-byte rolling commitment to the whole committed-leader sequence —
    two nodes agreeing on the chain digest at height ``h`` agree on every
    anchor up to ``h`` (the snapshot catch-up prefix-consistency handle)."""
    import hashlib

    w = Writer()
    leader.encode(w)
    h = hashlib.blake2b(digest_size=32)
    h.update(digest)
    h.update(w.finish())
    return h.digest()


def _atomic_write(path: str, data: bytes) -> None:
    """tmp + fsync + rename + dir fsync: the file is either the old content
    or the complete new content, never a tear."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


# ---------------------------------------------------------------------------
# Segmented WAL


class _Segment:
    """Bookkeeping for one ``wal.NNNNNN`` file."""

    __slots__ = ("name", "base", "size", "max_round", "path", "reader")

    def __init__(self, name: str, base: int, size: int, max_round: int,
                 path: str) -> None:
        self.name = name
        self.base = base
        self.size = size  # sealed size; the active segment's live size is
        self.max_round = max_round  # tracked by its writer
        self.path = path
        self.reader: Optional[WalReader] = None

    def to_manifest(self) -> dict:
        return {"name": self.name, "base": self.base,
                "max_round": self.max_round}


class SegmentedWalWriter:
    """Single-owner appender over a directory of size-bounded segments.

    Drop-in for :class:`~mysticeti_tpu.wal.WalWriter`: same append surface
    (``write``/``writev``/``position``/``flush``/``pending``/``sync``/
    ``truncate_to``/``syncer``/``close``), positions are global logical
    offsets.  Adds the lifecycle surface: ``note_round`` (per-segment max
    block round, the GC predicate), ``retire_below`` (delete retired
    segments), ``size_bytes``/``segment_count``/``first_base``.

    Thread shape: appends come from the consensus owner only (like the plain
    writer); the segment table is read by the paired reader, the metrics
    thread, and the fsync thread, so every table access holds ``_seg_lock``.
    """

    def __init__(self, directory: str, params: StorageParameters,
                 async_writes: Optional[bool] = None) -> None:
        self._dir = directory
        self._params = params
        self._async = async_writes
        self._seg_lock = threading.Lock()
        self._segments: List[_Segment] = []
        self._next_seq = 0
        self._active_writer: Optional[WalWriter] = None
        os.makedirs(directory, exist_ok=True)
        self._recover_manifest()

    # -- recovery --

    def _manifest_path(self) -> str:
        return os.path.join(self._dir, MANIFEST_NAME)

    def _recover_manifest(self) -> None:
        manifest_path = self._manifest_path()
        tmp = manifest_path + ".tmp"
        if os.path.exists(tmp):
            # A crash mid-rewrite: the rename never happened, so the real
            # manifest (if any) is the authoritative old one.
            log.warning("discarding torn manifest rewrite %s", tmp)
            os.unlink(tmp)
        segments: List[_Segment] = []
        if os.path.exists(manifest_path):
            try:
                with open(manifest_path, "r", encoding="utf-8") as f:
                    raw = json.load(f)
                entries = raw["segments"]
                self._next_seq = int(raw.get("next_seq", len(entries)))
            except (ValueError, KeyError, TypeError) as exc:
                raise WalError(f"corrupt WAL manifest {manifest_path}: {exc}")
            for entry in entries:
                path = os.path.join(self._dir, entry["name"])
                if not os.path.exists(path):
                    raise WalError(
                        f"WAL manifest lists missing segment {entry['name']}"
                    )
                segments.append(
                    _Segment(
                        entry["name"], int(entry["base"]),
                        os.path.getsize(path),
                        int(entry.get("max_round", 0)), path,
                    )
                )
            # Base contiguity: a sealed segment's recorded base must equal the
            # previous base + its on-disk size.  A mismatch means a tear
            # landed between a truncation and its manifest rewrite — every
            # segment past the inconsistency is unreachable; drop them.
            kept: List[_Segment] = []
            for seg in segments:
                if kept and seg.base != kept[-1].base + kept[-1].size:
                    log.warning(
                        "WAL segment %s base %d disagrees with predecessor "
                        "end %d; dropping it and %d later segment(s)",
                        seg.name, seg.base, kept[-1].base + kept[-1].size,
                        len(segments) - len(kept) - 1,
                    )
                    break
                kept.append(seg)
            for seg in segments[len(kept):]:
                os.unlink(seg.path)
            segments = kept
            if not segments:
                raise WalError(f"WAL manifest {manifest_path} lists no usable segments")
        else:
            listed = sorted(
                n for n in os.listdir(self._dir)
                if n.startswith(SEGMENT_PREFIX)
            )
            first = f"{SEGMENT_PREFIX}{0:06d}"
            if listed and listed != [first]:
                raise WalError(
                    f"WAL directory {self._dir} has segments but no manifest"
                )
            path = os.path.join(self._dir, first)
            size = os.path.getsize(path) if os.path.exists(path) else 0
            if not os.path.exists(path):
                open(path, "ab").close()
            segments = [_Segment(first, 0, size, 0, path)]
            self._next_seq = 1
        # Orphan segment files (a crash between creating the next segment and
        # the manifest rewrite, or between a GC unlink batch and its rewrite):
        # not addressable, safe to delete — the roll recreates its file.
        known = {seg.name for seg in segments}
        for name in os.listdir(self._dir):
            if name.startswith(SEGMENT_PREFIX) and name not in known:
                log.warning("removing orphan WAL segment %s", name)
                os.unlink(os.path.join(self._dir, name))
        with self._seg_lock:
            self._segments = segments
        self._open_active(segments[-1])
        self._write_manifest()

    def _open_active(self, seg: _Segment) -> None:
        fd = os.open(seg.path, os.O_RDWR | os.O_CREAT, 0o644)
        writer = WalWriter(fd, os.fstat(fd).st_size, seg.path,
                           async_writes=self._async)
        reader = WalReader(seg.path)
        reader._inflight = writer.inflight_get
        reader._writer_flush = writer.flush
        seg.reader = reader
        self._active_writer = writer

    def _write_manifest(self) -> None:
        with self._seg_lock:
            segs = list(self._segments)
            active = segs[-1]
        active.size = self._active_writer.position()
        data = json.dumps(
            {
                "version": 1,
                "next_seq": self._next_seq,
                "segments": [seg.to_manifest() for seg in segs],
            },
            sort_keys=True,
        ).encode()
        _atomic_write(self._manifest_path(), data)

    # -- the append surface (WalWriter parity) --

    def write(self, tag: int, payload: bytes) -> WalPosition:
        return self.writev(tag, (payload,))

    def writev(self, tag: int, parts: Sequence[bytes]) -> WalPosition:
        framed = HEADER_SIZE + sum(len(p) for p in parts)
        active = self._active()
        if (
            self._active_writer.position() + framed > self._params.segment_bytes
            and self._active_writer.position() > 0
        ):
            self._roll()
            active = self._active()
        local = self._active_writer.writev(tag, parts)
        return active.base + local

    def _active(self) -> _Segment:
        with self._seg_lock:
            return self._segments[-1]

    def _roll(self) -> None:
        """Seal the active segment and open the next one.

        Seal order is the crash-safety argument: (1) drain + fsync the
        active segment so its recorded size is durable, (2) create the new
        segment file, (3) rewrite the manifest.  A crash after (2) leaves an
        orphan file recovery deletes; a crash before (2) changes nothing."""
        old = self._active()
        self._active_writer.sync()
        sealed_size = self._active_writer.position()
        self._active_writer.close()
        old.size = sealed_size
        name = f"{SEGMENT_PREFIX}{self._next_seq:06d}"
        self._next_seq += 1
        path = os.path.join(self._dir, name)
        open(path, "wb").close()
        seg = _Segment(name, old.base + sealed_size, 0, 0, path)
        self._open_active(seg)
        with self._seg_lock:
            self._segments = self._segments + [seg]
        self._write_manifest()
        log.debug("rolled WAL to segment %s at base %d", name, seg.base)

    def note_round(self, round_: int, position: Optional[WalPosition] = None) -> None:
        """Record that a block of ``round_`` lives at ``position`` (default:
        the active segment).  The per-segment running max is the GC
        predicate; recovery replay re-feeds it so a segment sealed without a
        manifest rewrite (crash mid-roll) still reports its true max."""
        seg = self._segment_at(position) if position is not None else self._active()
        if seg is not None and round_ > seg.max_round:
            seg.max_round = round_

    def _segment_at(self, position: WalPosition) -> Optional[_Segment]:
        with self._seg_lock:
            candidate = None
            for seg in self._segments:
                if seg.base <= position:
                    candidate = seg
                else:
                    break
            return candidate

    def position(self) -> WalPosition:
        return self._active().base + self._active_writer.position()

    def pending(self) -> bool:
        return self._active_writer.pending()

    def flush(self) -> None:
        self._active_writer.flush()

    def sync(self) -> None:
        self._active_writer.sync()

    def inflight_get(self, position: WalPosition) -> Optional[bytes]:
        active = self._active()
        if position >= active.base:
            return self._active_writer.inflight_get(position - active.base)
        return None

    def truncate_to(self, position: WalPosition) -> None:
        """Discard a torn tail found during recovery.

        Within the active segment this is the plain single-file contract.  A
        tear in a *sealed* segment (an OS crash that outran the seal fsync)
        makes every later segment unreachable on replay: they are deleted and
        the torn segment is reopened as the active one, truncated at the
        tear, so appends resume exactly where replay stops."""
        assert position <= self.position()
        with self._seg_lock:
            segs = list(self._segments)
        idx = 0
        for i, seg in enumerate(segs):
            if seg.base <= position:
                idx = i
        if idx == len(segs) - 1:
            self._active_writer.truncate_to(position - segs[idx].base)
            self._write_manifest()
            return
        log.warning(
            "torn WAL tail inside sealed segment %s: dropping %d later "
            "segment(s)", segs[idx].name, len(segs) - idx - 1,
        )
        self._active_writer.close()
        torn = segs[idx]
        if torn.reader is not None:
            torn.reader.close()
            torn.reader = None
        with self._seg_lock:
            self._segments = segs[: idx + 1]
        self._open_active(torn)
        self._active_writer.truncate_to(position - torn.base)
        torn.size = position - torn.base
        # Manifest BEFORE unlinking the dropped segments: a crash in between
        # leaves orphan files recovery deletes — never a manifest naming
        # files that no longer exist.  (A crash before the rewrite changes
        # nothing: all files still exist and replay re-detects the tear.)
        self._write_manifest()
        for seg in segs[idx + 1:]:
            if seg.reader is not None:
                seg.reader.close()
                seg.reader = None
            os.unlink(seg.path)

    # -- lifecycle surface --

    def retire_below(self, gc_round: int, keep_from_position: WalPosition
                     ) -> Tuple[int, int]:
        """Delete sealed segments whose every block round is ``< gc_round``
        and which end at or before ``keep_from_position`` (the oldest kept
        checkpoint's replay start — replay never reaches below it).  Returns
        ``(bytes_reclaimed, segments_removed)``.

        Only a PREFIX of the segment list is eligible: stopping at the first
        non-retirable segment keeps the surviving bases contiguous, which
        the recovery contiguity check relies on to tell a GC'd head from a
        mid-log tear.  Crash-safety order: the manifest is rewritten WITHOUT
        the victims FIRST, then the files are unlinked — a crash in between
        leaves orphan files recovery already deletes, never a manifest
        naming files that no longer exist."""
        with self._seg_lock:
            segs = list(self._segments)
        victims = []
        for seg in segs[:-1]:
            if (
                seg.max_round < gc_round
                and seg.base + seg.size <= keep_from_position
            ):
                victims.append(seg)
            else:
                break
        if not victims:
            return 0, 0
        gone = set(id(seg) for seg in victims)
        with self._seg_lock:
            self._segments = [s for s in self._segments if id(s) not in gone]
        self._write_manifest()
        reclaimed = 0
        for seg in victims:
            if seg.reader is not None:
                seg.reader.close()
                seg.reader = None
            os.unlink(seg.path)
            reclaimed += seg.size
        log.info(
            "WAL GC below round %d: removed %d segment(s), %d bytes",
            gc_round, len(victims), reclaimed,
        )
        return reclaimed, len(victims)

    def size_bytes(self) -> int:
        with self._seg_lock:
            sealed = sum(seg.size for seg in self._segments[:-1])
        return sealed + self._active_writer.position()

    def segment_count(self) -> int:
        with self._seg_lock:
            return len(self._segments)

    def first_base(self) -> WalPosition:
        with self._seg_lock:
            return self._segments[0].base

    def segments_snapshot(self) -> List[Tuple[str, int, int, int]]:
        """(name, base, size, max_round) per live segment (active last)."""
        with self._seg_lock:
            segs = list(self._segments)
        out = []
        for seg in segs:
            size = seg.size
            if seg is segs[-1]:
                size = self._active_writer.position()
            out.append((seg.name, seg.base, size, seg.max_round))
        return out

    def syncer(self) -> "SegmentedWalSyncer":
        return SegmentedWalSyncer(self)

    def close(self) -> None:
        self._active_writer.close()


class SegmentedWalSyncer:
    """Fsync handle that follows the active segment across rolls (the
    1 s wal-sync thread holds one of these; a plain per-file descriptor
    would keep fsyncing a sealed file forever after the first roll)."""

    __slots__ = ("_writer", "_fd", "_path")

    def __init__(self, writer: SegmentedWalWriter) -> None:
        self._writer = writer
        self._fd: Optional[int] = None
        self._path: Optional[str] = None

    def sync(self) -> None:
        try:
            self._writer.flush()
        except (WalError, OSError):
            pass  # append-path failures surface on the append path
        path = self._writer._active().path
        if path != self._path:
            if self._fd is not None:
                os.close(self._fd)
            self._fd = os.open(path, os.O_RDWR)
            self._path = path
        os.fsync(self._fd)

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None


class SegmentedWalReader:
    """Random-access reader over the segment table; thread-safe.

    Positions are global logical offsets; the reader resolves them through
    the writer's segment table (shared, under its lock) and delegates to a
    per-segment :class:`~mysticeti_tpu.wal.WalReader` (lazily opened).  The
    active segment's reader is pre-wired to the writer's in-flight queue so
    read-after-write holds exactly as in the single-file log."""

    def __init__(self, writer: SegmentedWalWriter) -> None:
        self._writer = writer

    def _resolve(self, position: WalPosition) -> Tuple[_Segment, int]:
        seg = self._writer._segment_at(position)
        if seg is None:
            raise WalError(
                f"wal position {position} is below the GC-retired floor"
            )
        return seg, position - seg.base

    def _reader_for(self, seg: _Segment) -> WalReader:
        with self._writer._seg_lock:
            if seg.reader is None:
                seg.reader = WalReader(seg.path)
            return seg.reader

    def read(self, position: WalPosition) -> Tuple[int, bytes]:
        seg, local = self._resolve(position)
        return self._reader_for(seg).read(local)

    def iter_until(self, end: Optional[WalPosition] = None):
        yield from self.iter_from(0, end)

    def iter_from(self, start: WalPosition,
                  end: Optional[WalPosition] = None):
        """Replay from ``start`` to ``end`` across segments.

        A torn entry terminates iteration for the WHOLE log, not just its
        segment: entries in later segments were appended after the torn one
        and are exactly the unreachable tail ``truncate_to`` discards."""
        if end is None:
            end = self._writer.position()
        with self._writer._seg_lock:
            segs = list(self._writer._segments)
        for seg in segs:
            size = seg.size
            if seg is segs[-1]:
                size = self._writer._active_writer.position()
            seg_end = seg.base + size
            if seg_end <= start or size == 0:
                continue
            if seg.base >= end:
                break
            local_start = max(0, start - seg.base)
            local_end = min(size, end - seg.base)
            consumed = local_start
            reader = self._reader_for(seg)
            for pos, tag, payload in reader.iter_from(local_start, local_end):
                consumed = pos + HEADER_SIZE + len(payload)
                yield seg.base + pos, tag, payload
            if consumed < local_end:
                return  # torn entry: everything after is unreachable

    def cleanup(self) -> int:
        with self._writer._seg_lock:
            segs = list(self._writer._segments)
        for seg in segs:
            if seg.reader is not None:
                seg.reader.cleanup()
        return 0

    def close(self) -> None:
        with self._writer._seg_lock:
            segs = list(self._writer._segments)
        for seg in segs:
            if seg.reader is not None:
                seg.reader.close()
                seg.reader = None


# ---------------------------------------------------------------------------
# Opening


def open_wal(path: str, params: Optional[StorageParameters] = None):
    """Open the node's WAL at ``path``: segmented (directory) when
    ``params.segment_bytes > 0``, the legacy single file otherwise.  An
    existing single-file log is migrated into segment 0 of a fresh directory
    (rename-only; the bytes never move)."""
    if params is None or params.segment_bytes <= 0:
        return walf(path)
    stash = path + ".migrate"
    if os.path.exists(stash):
        # A crash interrupted a previous migration after the log moved to
        # the stash: resume it — the stash IS the node's entire WAL, and
        # booting without it would re-propose already-broadcast rounds.
        log.warning("resuming interrupted WAL migration from %s", stash)
        os.makedirs(path, exist_ok=True)
        os.replace(stash, os.path.join(path, f"{SEGMENT_PREFIX}{0:06d}"))
    elif os.path.isfile(path):
        os.replace(path, stash)
        os.makedirs(path, exist_ok=True)
        os.replace(stash, os.path.join(path, f"{SEGMENT_PREFIX}{0:06d}"))
        log.info("migrated single-file WAL %s into a segment directory", path)
    writer = SegmentedWalWriter(path, params)
    reader = SegmentedWalReader(writer)
    return writer, reader


def active_wal_file(path: str) -> str:
    """The file new appends land in: the path itself for a single-file log,
    the manifest's last segment for a directory (fault injectors tear this
    one)."""
    if os.path.isfile(path):
        return path
    with open(os.path.join(path, MANIFEST_NAME), "r", encoding="utf-8") as f:
        manifest = json.load(f)
    return os.path.join(path, manifest["segments"][-1]["name"])


# ---------------------------------------------------------------------------
# Checkpoints


def _write_opt_bytes(w: Writer, data: Optional[bytes]) -> None:
    if data is None:
        w.u8(0)
    else:
        w.u8(1)
        w.bytes(data)


def _read_opt_bytes(r: Reader) -> Optional[bytes]:
    return r.bytes() if r.u8() else None


def _write_opt_ref(w: Writer, ref: Optional[BlockReference]) -> None:
    if ref is None:
        w.u8(0)
    else:
        w.u8(1)
        ref.encode(w)


def _read_opt_ref(r: Reader) -> Optional[BlockReference]:
    return BlockReference.decode(r) if r.u8() else None


@dataclass
class Checkpoint:
    """One durable recovery anchor (see the module docstring for framing)."""

    wal_position: WalPosition
    commit_height: int
    gc_round: int
    last_committed_leader: Optional[BlockReference]
    chain_digest: bytes
    committed_state: Optional[bytes]
    handler_state: Optional[bytes]
    last_own_block: Optional[object]  # OwnBlockData (lazy import, no cycle)
    pending: List[Tuple[WalPosition, object]]  # (position, Include|Payload)
    committed_refs: List[BlockReference]
    index: List[Tuple[BlockReference, WalPosition, bool]]
    path: str = ""
    # Reconfiguration (reconfig.py): the serialized epoch chain as of this
    # checkpoint.  Soft serialization tail — absent on pre-reconfig files
    # (they decode as "still epoch 0") and omitted when empty, so frozen-
    # committee deployments keep byte-identical checkpoints.
    epoch_chain: bytes = b""
    # Execution plane (execution.py): the serialized account state as of
    # this checkpoint.  Second soft tail — writing it forces the epoch
    # chain to be written explicitly (possibly empty) so the tail order is
    # unambiguous; with both planes off the file stays byte-identical.
    exec_state: bytes = b""

    def to_bytes(self) -> bytes:
        from .state import Include, encode_payload

        w = Writer()
        w.u32(CHECKPOINT_MAGIC).u32(1)
        w.u64(self.wal_position).u64(self.commit_height).u64(self.gc_round)
        _write_opt_ref(w, self.last_committed_leader)
        w.fixed(self.chain_digest)
        _write_opt_bytes(w, self.committed_state)
        _write_opt_bytes(w, self.handler_state)
        _write_opt_bytes(
            w,
            self.last_own_block.to_bytes()
            if self.last_own_block is not None
            else None,
        )
        w.u32(len(self.pending))
        for position, meta in self.pending:
            w.u64(position)
            if isinstance(meta, Include):
                w.u8(0)
                meta.reference.encode(w)
            else:
                w.u8(1)
                w.bytes(encode_payload(meta.statements))
        w.u32(len(self.committed_refs))
        for ref in self.committed_refs:
            ref.encode(w)
        w.u32(len(self.index))
        for ref, position, proposed in self.index:
            w.u64(position)
            w.u8(1 if proposed else 0)
            ref.encode(w)
        if self.exec_state:
            w.bytes(self.epoch_chain)
            w.bytes(self.exec_state)
        elif self.epoch_chain:
            w.bytes(self.epoch_chain)
        body = w.finish()
        return zlib.crc32(body).to_bytes(4, "little") + body

    @staticmethod
    def from_bytes(data: bytes) -> "Checkpoint":
        from .block_store import OwnBlockData
        from .state import Include, Payload, decode_payload

        if len(data) < 4 + 8:
            raise WalError("checkpoint file truncated")
        crc = int.from_bytes(data[:4], "little")
        body = data[4:]
        if zlib.crc32(body) != crc:
            raise WalError("checkpoint crc mismatch (torn or corrupt)")
        r = Reader(body)
        if r.u32() != CHECKPOINT_MAGIC:
            raise WalError("bad checkpoint magic")
        version = r.u32()
        if version != 1:
            raise WalError(f"unsupported checkpoint version {version}")
        wal_position = r.u64()
        commit_height = r.u64()
        gc_round = r.u64()
        leader = _read_opt_ref(r)
        chain_digest = r.fixed(32)
        committed_state = _read_opt_bytes(r)
        handler_state = _read_opt_bytes(r)
        own_raw = _read_opt_bytes(r)
        own = OwnBlockData.from_bytes(own_raw) if own_raw is not None else None
        pending: List[Tuple[WalPosition, object]] = []
        for _ in range(r.u32()):
            position = r.u64()
            kind = r.u8()
            if kind == 0:
                pending.append((position, Include(BlockReference.decode(r))))
            elif kind == 1:
                pending.append((position, Payload(decode_payload(r.bytes()))))
            else:
                raise WalError(f"unknown pending kind {kind} in checkpoint")
        committed_refs = [BlockReference.decode(r) for _ in range(r.u32())]
        index = []
        for _ in range(r.u32()):
            position = r.u64()
            proposed = bool(r.u8())
            index.append((BlockReference.decode(r), position, proposed))
        epoch_chain = r.bytes() if not r.done() else b""
        exec_state = r.bytes() if not r.done() else b""
        r.expect_done()
        return Checkpoint(
            wal_position=wal_position,
            commit_height=commit_height,
            gc_round=gc_round,
            last_committed_leader=leader,
            chain_digest=chain_digest,
            committed_state=committed_state,
            handler_state=handler_state,
            last_own_block=own,
            pending=pending,
            committed_refs=committed_refs,
            index=index,
            epoch_chain=epoch_chain,
            exec_state=exec_state,
        )


def checkpoint_brief(path: str) -> Optional[Tuple[int, WalPosition]]:
    """(commit_height, wal_position) from a checkpoint file's fixed-offset
    header — 28 bytes, no full decode.  The values are bookkeeping only
    (checkpoint cadence, the segment-GC keep floor); boot-time validation
    still runs the full crc-checked parse.  None on a file too short or
    with the wrong magic."""
    try:
        with open(path, "rb") as f:
            head = f.read(28)
    except OSError:
        return None
    # Layout: u32 crc ‖ u32 magic ‖ u32 version ‖ u64 wal_position ‖
    # u64 commit_height ...
    if len(head) < 28 or int.from_bytes(head[4:8], "little") != CHECKPOINT_MAGIC:
        return None
    position = int.from_bytes(head[12:20], "little")
    height = int.from_bytes(head[20:28], "little")
    return height, position


def checkpoint_files(directory: str) -> List[str]:
    """Checkpoint file paths, newest (highest commit height) first."""
    if not os.path.isdir(directory):
        return []
    names = sorted(
        (n for n in os.listdir(directory) if n.startswith(CHECKPOINT_PREFIX)),
        reverse=True,
    )
    return [os.path.join(directory, n) for n in names]


def load_latest_checkpoint(
    directory: str, wal_end: WalPosition, first_base: WalPosition = 0
) -> Tuple[Optional[Checkpoint], int]:
    """Newest checkpoint that parses, crc-verifies, and whose replay
    position lies inside the live WAL; returns ``(checkpoint, skipped)``
    where ``skipped`` counts torn/corrupt/stale files that were passed over
    (the fallback the chaos tier exercises)."""
    skipped = 0
    for path in checkpoint_files(directory):
        try:
            with open(path, "rb") as f:
                ckpt = Checkpoint.from_bytes(f.read())
        except (WalError, SerdeError, OSError) as exc:
            log.warning("skipping unusable checkpoint %s: %s", path, exc)
            skipped += 1
            continue
        if ckpt.wal_position > wal_end or ckpt.wal_position < first_base:
            log.warning(
                "skipping checkpoint %s: replay position %d outside live "
                "WAL [%d, %d]", path, ckpt.wal_position, first_base, wal_end,
            )
            skipped += 1
            continue
        ckpt.path = path
        return ckpt, skipped
    return None, skipped


# ---------------------------------------------------------------------------
# Snapshot catch-up manifest (wire payload, tags 9/10/11)


@dataclass
class SnapshotManifest:
    """The commit baseline a far-behind peer adopts: everything needed to
    resume committing at ``commit_height + 1`` once the block window above
    ``gc_round`` has been streamed in."""

    commit_height: int
    last_committed_leader: Optional[BlockReference]
    gc_round: int
    chain_digest: bytes
    committed_refs: List[BlockReference] = field(default_factory=list)
    # Reconfiguration: the serving node's epoch chain — a rejoiner absent
    # across one or more boundaries re-derives the CURRENT committee from
    # this before processing the post-baseline block stream.  Soft tail
    # (omitted when empty), so pre-reconfig manifests stay byte-identical
    # and decode fine both ways.
    epoch_chain: bytes = b""
    # Execution plane: the serving node's account state at the baseline —
    # the rejoiner lands on the fleet's exact root.  Second soft tail with
    # the same ordering rule as Checkpoint.exec_state.
    exec_state: bytes = b""

    def to_bytes(self) -> bytes:
        w = Writer()
        w.u32(SNAPSHOT_MAGIC).u32(1)
        w.u64(self.commit_height).u64(self.gc_round)
        _write_opt_ref(w, self.last_committed_leader)
        w.fixed(self.chain_digest)
        w.u32(len(self.committed_refs))
        for ref in self.committed_refs:
            ref.encode(w)
        if self.exec_state:
            w.bytes(self.epoch_chain)
            w.bytes(self.exec_state)
        elif self.epoch_chain:
            w.bytes(self.epoch_chain)
        return w.finish()

    @staticmethod
    def from_bytes(data: bytes) -> "SnapshotManifest":
        r = Reader(data)
        if r.u32() != SNAPSHOT_MAGIC:
            raise SerdeError("bad snapshot manifest magic")
        version = r.u32()
        if version != 1:
            raise SerdeError(f"unsupported snapshot manifest version {version}")
        commit_height = r.u64()
        gc_round = r.u64()
        leader = _read_opt_ref(r)
        chain_digest = r.fixed(32)
        refs = [BlockReference.decode(r) for _ in range(r.u32())]
        epoch_chain = r.bytes() if not r.done() else b""
        exec_state = r.bytes() if not r.done() else b""
        r.expect_done()
        return SnapshotManifest(
            commit_height=commit_height,
            last_committed_leader=leader,
            gc_round=gc_round,
            chain_digest=chain_digest,
            committed_refs=refs,
            epoch_chain=epoch_chain,
            exec_state=exec_state,
        )


# ---------------------------------------------------------------------------
# The lifecycle manager


def _ref_sort_key(ref: BlockReference):
    return (ref.round, ref.authority, ref.digest)


class StorageLifecycle:
    """Owns the node's storage lifecycle policy: the committed-leader digest
    chain, checkpoint cadence, the GC floor, and the snapshot manifest.

    Single-writer like the :class:`~mysticeti_tpu.core.Core` that owns it —
    every mutation comes from the consensus owner task; other tasks on the
    same event loop may read."""

    def __init__(
        self,
        directory: Optional[str],
        params: StorageParameters,
        wal_writer,
        recovered,
        observer_recovered,
        metrics=None,
        boot_checkpoint=None,
    ) -> None:
        self.directory = directory
        self.params = params
        self.wal_writer = wal_writer
        self.metrics = metrics
        self.commit_height: int = recovered.commit_height
        self.chain_digest: bytes = recovered.chain_digest or ZERO_DIGEST
        self.last_committed_leader = recovered.last_committed_leader
        # The floor already applied to this store (checkpoint/adoption
        # baseline + own GC passes): references below it are gone here.
        self.retired_round: int = recovered.gc_round
        # The committed-ref set feeds checkpoints and snapshot manifests and
        # is pruned below the GC floor.  On configurations where neither
        # consumer can ever run AND no floor ever rises (legacy single-file
        # log, or gc_depth=0 without catch-up) it would be a new unbounded
        # set duplicating the linearizer's — skip tracking entirely there.
        segmented = isinstance(wal_writer, SegmentedWalWriter)
        self._track_committed = (
            segmented and params.checkpoint_interval > 0
        ) or params.snapshot_catchup
        self._committed: Set[BlockReference] = set()
        if self._track_committed:
            self._committed.update(observer_recovered.base_committed)
            for commit in observer_recovered.sub_dags:
                self._committed.update(commit.sub_dag)
        self.checkpoints_written = 0
        self.snapshots_adopted = 0
        # Live snapshot streams currently serving this node's retained
        # window (net_sync/synchronizer increment around each stream, on the
        # event loop): GC must not advance the floor under a window a
        # manifest already promised.
        self.gc_holds = 0
        # Flight recorder (flight_recorder.py), wired post-construction by
        # the node assembly: GC passes and checkpoint writes are incident-
        # ring events.
        self.recorder = None
        # Boot-cost evidence (the acceptance criterion "replay bytes <<
        # lifetime WAL bytes"): how much replay this boot actually paid.
        self.replay_start = recovered.replay_start
        self.replayed_bytes = recovered.replayed_bytes
        self.recovered_checkpoint_height = recovered.checkpoint_height
        # (commit_height, wal_position) of kept on-disk checkpoints, newest
        # last; the OLDEST kept position is the segment-GC keep floor (a
        # fallback boot from the older checkpoint must still find every
        # segment it replays).
        self._kept_checkpoints: List[Tuple[int, WalPosition]] = []
        if directory is not None:
            # Files NEWER than the checkpoint boot actually recovered from
            # were examined and rejected (torn body, replay position outside
            # the live WAL): they must not drive the checkpoint cadence or
            # occupy a keep slot — delete them so the keep set only ever
            # holds files a future boot could use.  With no usable boot
            # checkpoint at all (full replay), every file on disk is junk.
            used_height = (
                boot_checkpoint.commit_height
                if boot_checkpoint is not None
                else -1
            )
            for path in reversed(checkpoint_files(directory)):
                brief = checkpoint_brief(path)
                if brief is not None and brief[0] <= used_height:
                    self._kept_checkpoints.append(brief)
                else:
                    log.warning(
                        "removing unusable checkpoint %s (rejected at boot)",
                        path,
                    )
                    os.unlink(path)
        if metrics is not None:
            if self._kept_checkpoints:
                metrics.checkpoint_last_commit_index.set(
                    self._kept_checkpoints[-1][0]
                )
            metrics.wal_segments.set(self._segment_count())

    # -- helpers --

    def _segmented(self) -> bool:
        return self.directory is not None and isinstance(
            self.wal_writer, SegmentedWalWriter
        )

    def _segment_count(self) -> int:
        try:
            return self.wal_writer.segment_count()
        except AttributeError:
            return 1

    # -- commit tracking --

    def note_commits(self, commit_data: Sequence) -> None:
        """Fold freshly persisted commits (List[CommitData]) into the chain:
        height, leader digest chain, committed-ref set."""
        for commit in commit_data:
            self.commit_height = commit.height
            self.last_committed_leader = commit.leader
            self.chain_digest = fold_leader_digest(
                self.chain_digest, commit.leader
            )
            if self._track_committed:
                self._committed.update(commit.sub_dag)

    # -- checkpoints --

    def should_checkpoint(self) -> bool:
        if not self._segmented() or self.params.checkpoint_interval <= 0:
            return False
        last = self._kept_checkpoints[-1][0] if self._kept_checkpoints else 0
        return self.commit_height - last >= self.params.checkpoint_interval

    def write_checkpoint(self, core, committed_state: bytes) -> str:
        """One durable recovery anchor.  The WAL is fsynced FIRST: a
        checkpoint must never reference bytes that could be lost behind it
        (replay starts at its recorded position)."""
        self.wal_writer.sync()
        ckpt = Checkpoint(
            wal_position=self.wal_writer.position(),
            commit_height=self.commit_height,
            gc_round=self.retired_round,
            last_committed_leader=self.last_committed_leader,
            chain_digest=self.chain_digest,
            committed_state=committed_state,
            handler_state=core.block_handler.state(),
            last_own_block=core.last_own_block,
            pending=list(core.pending),
            committed_refs=sorted(self._committed, key=_ref_sort_key),
            index=core.block_store.index_entries_snapshot(self.retired_round),
            epoch_chain=(
                core.reconfig.chain.to_bytes()
                if getattr(core, "reconfig", None) is not None
                else b""
            ),
            exec_state=(
                core.execution.to_bytes()
                if getattr(core, "execution", None) is not None
                else b""
            ),
        )
        name = f"{CHECKPOINT_PREFIX}{self.commit_height:012d}"
        path = os.path.join(self.directory, name)
        _atomic_write(path, ckpt.to_bytes())
        self._kept_checkpoints.append((self.commit_height, ckpt.wal_position))
        while len(self._kept_checkpoints) > CHECKPOINT_KEEP:
            height, _ = self._kept_checkpoints.pop(0)
            stale = os.path.join(
                self.directory, f"{CHECKPOINT_PREFIX}{height:012d}"
            )
            if os.path.exists(stale):
                os.unlink(stale)
        self.checkpoints_written += 1
        if self.metrics is not None:
            self.metrics.checkpoint_last_commit_index.set(self.commit_height)
        if self.recorder is not None:
            self.recorder.record(
                "checkpoint", height=self.commit_height,
                wal_position=ckpt.wal_position,
            )
        log.info(
            "checkpoint at commit height %d (wal position %d, %d index "
            "entries)", self.commit_height, ckpt.wal_position, len(ckpt.index),
        )
        return path

    # -- garbage collection --

    def gc_target(self) -> int:
        """The round strictly below which the DAG may be retired."""
        if self.params.gc_depth <= 0 or self.last_committed_leader is None:
            return 0
        return max(0, self.last_committed_leader.round - self.params.gc_depth)

    def collect(self, block_store) -> int:
        """One GC pass: raise the retired floor, drop index entries below
        it, delete fully-retired sealed segments.  Returns bytes reclaimed.

        A no-op on the legacy single-file log: the documented contract for
        ``segment_bytes <= 0`` is "no rolling, no checkpoints, no GC" —
        retiring index entries there would make the node forget history
        that is still on disk (and resurrect it on the next full replay)."""
        if not self._segmented():
            return 0
        if self.gc_holds > 0:
            return 0  # a snapshot stream is serving the promised window
        target = self.gc_target()
        if target <= self.retired_round:
            return 0
        block_store.retire_below_round(target)
        self._committed = {
            ref for ref in self._committed if ref.round >= target
        }
        self.retired_round = target
        keep = (
            min(pos for _h, pos in self._kept_checkpoints)
            if self._kept_checkpoints
            else 0
        )
        reclaimed, _removed = self.wal_writer.retire_below(target, keep)
        if self.metrics is not None:
            if reclaimed:
                self.metrics.wal_reclaimed_bytes_total.inc(reclaimed)
            self.metrics.wal_segments.set(self._segment_count())
        if self.recorder is not None:
            self.recorder.record(
                "gc", floor=target, reclaimed_bytes=reclaimed
            )
        return reclaimed

    # -- snapshot catch-up --

    def build_manifest(self) -> SnapshotManifest:
        return SnapshotManifest(
            commit_height=self.commit_height,
            last_committed_leader=self.last_committed_leader,
            gc_round=self.retired_round,
            chain_digest=self.chain_digest,
            committed_refs=sorted(self._committed, key=_ref_sort_key),
        )

    def serves_snapshot_for(self, peer_height: int) -> bool:
        """Server-side gate: only a peer genuinely far behind gets a
        snapshot; anything closer catches up over the ordinary streams."""
        if not self.params.snapshot_catchup or self.commit_height <= 0:
            return False
        gap = self.commit_height - peer_height
        return gap >= max(1, self.params.catchup_threshold_commits)

    def wants_snapshot(self, manifest: SnapshotManifest) -> bool:
        """Client-side gate (also the duplicate-manifest dedup): adopt only
        a baseline meaningfully ahead of where we already are."""
        gap = manifest.commit_height - self.commit_height
        return gap >= max(1, self.params.catchup_threshold_commits // 2)

    def adopt(self, manifest: SnapshotManifest) -> None:
        """Adopt a remote commit baseline (the caller has already persisted
        the manifest as a WAL entry so a crash re-adopts it on replay)."""
        self.commit_height = manifest.commit_height
        self.last_committed_leader = manifest.last_committed_leader
        self.chain_digest = manifest.chain_digest
        floor = max(self.retired_round, manifest.gc_round)
        self._committed = {
            ref for ref in self._committed if ref.round >= floor
        } | set(manifest.committed_refs)
        self.retired_round = floor
        self.snapshots_adopted += 1


# ---------------------------------------------------------------------------
# One-call node storage boot


def open_store(authority, wal_path, committee, parameters=None, metrics=None):
    """The node's storage boot: open (segmented) WAL, find the newest valid
    checkpoint, replay only what follows it.  Returns
    ``(core_recovered, observer_recovered, wal_writer, lifecycle)``.

    Raises :class:`~mysticeti_tpu.wal.WalError` when the log is genuinely
    unreplayable: history below the first live segment was garbage-collected
    and no surviving checkpoint covers it (``tools/wal_inspect.py``
    diagnoses the same states offline)."""
    from .block_store import BlockStore

    params = parameters.storage if parameters is not None else StorageParameters()
    wal_writer, wal_reader = open_wal(wal_path, params)
    checkpoint = None
    if isinstance(wal_writer, SegmentedWalWriter):
        first_base = wal_writer.first_base()
        checkpoint, _skipped = load_latest_checkpoint(
            wal_path, wal_writer.position(), first_base
        )
        if checkpoint is None and first_base > 0:
            raise WalError(
                f"WAL at {wal_path} starts at offset {first_base} (history "
                "garbage-collected) but no valid checkpoint covers it"
            )
    recovered, observer_recovered = BlockStore.open(
        authority, wal_reader, wal_writer, committee, metrics,
        checkpoint=checkpoint,
    )
    directory = wal_path if isinstance(wal_writer, SegmentedWalWriter) else None
    lifecycle = StorageLifecycle(
        directory, params, wal_writer, recovered, observer_recovered, metrics,
        boot_checkpoint=checkpoint,
    )
    return recovered, observer_recovered, wal_writer, lifecycle
