"""The commit rule: wave-based direct/indirect decisions over the DAG.

Capability parity with ``mysticeti-core/src/consensus/mod.rs``:

* a wave = leader round + voting round(s) + decision round; minimum length 3
  (consensus/mod.rs:19-24)
* ``LeaderStatus``: Commit(block) | Skip(authority_round) | Undecided(authority_round)
  (consensus/mod.rs:30-34) with helpers ``round`` / ``authority`` / ``is_decided``.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Union

from ..types import AuthorityIndex, RoundNumber, StatementBlock

DEFAULT_WAVE_LENGTH = 3
MINIMUM_WAVE_LENGTH = 3

DIRECT = "direct"
INDIRECT = "indirect"


@dataclass(frozen=True, order=True)
class AuthorityRound:
    """(authority, round) pair naming a leader slot (types.rs AuthorityRound)."""

    authority: AuthorityIndex
    round: RoundNumber

    def __repr__(self) -> str:
        return f"{chr(ord('A') + self.authority % 26)}{self.round}"


class LeaderStatus:
    """Decision state of one leader slot (consensus/mod.rs:30-34)."""

    __slots__ = ("kind", "block", "authority_round")

    COMMIT = "commit"
    SKIP = "skip"
    UNDECIDED = "undecided"

    def __init__(self, kind: str, block: Optional[StatementBlock], ar: AuthorityRound):
        self.kind = kind
        self.block = block
        self.authority_round = ar

    @classmethod
    def commit(cls, block: StatementBlock) -> "LeaderStatus":
        return cls(cls.COMMIT, block, AuthorityRound(block.author(), block.round()))

    @classmethod
    def skip(cls, ar: AuthorityRound) -> "LeaderStatus":
        return cls(cls.SKIP, None, ar)

    @classmethod
    def undecided(cls, ar: AuthorityRound) -> "LeaderStatus":
        return cls(cls.UNDECIDED, None, ar)

    @property
    def round(self) -> RoundNumber:
        return self.authority_round.round

    @property
    def authority(self) -> AuthorityIndex:
        return self.authority_round.authority

    def is_decided(self) -> bool:
        return self.kind != self.UNDECIDED

    def into_decided_author_round(self) -> AuthorityRound:
        assert self.is_decided()
        return self.authority_round

    def committed_block(self) -> Optional[StatementBlock]:
        return self.block

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, LeaderStatus)
            and self.kind == other.kind
            and self.authority_round == other.authority_round
            and (
                self.block.reference if self.block else None
            ) == (other.block.reference if other.block else None)
        )

    def __repr__(self) -> str:
        return f"{self.kind.capitalize()}({self.authority_round!r})"
