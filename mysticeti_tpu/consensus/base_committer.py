"""The bare-bones decision rules for one (leader_offset, round_offset) view of the DAG.

Capability parity with ``mysticeti-core/src/consensus/base_committer.rs``:

* ``BaseCommitterOptions`` {wave_length, leader_offset, round_offset} (:22-31)
* wave/leader-round/decision-round arithmetic (:71-86)
* ``elect_leader`` (:91-102)
* support/vote/certificate predicates via DAG traversal with a memoized vote
  cache (:109-180)
* ``decide_leader_from_anchor`` (:184-224) — commit iff a certified link to the
  anchor exists, else skip; panics if >1 certified leader block (BFT break)
* direct rule ``try_direct_decide`` (:323-357) — skip on 2f+1 blames in the voting
  round, commit on 2f+1 certificates in the decision round
* indirect rule ``try_indirect_decide`` (:294-318) — decide from the first
  committed anchor >= one wave later; stop at the first undecided anchor.

All methods are idempotent, read-only queries over the block store.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional

from . import AuthorityRound, DEFAULT_WAVE_LENGTH, LeaderStatus, MINIMUM_WAVE_LENGTH
from ..block_store import BlockStore
from ..committee import Committee, QUORUM, StakeAggregator
from ..types import AuthorityIndex, BlockReference, RoundNumber, StatementBlock


@dataclass
class BaseCommitterOptions:
    wave_length: int = DEFAULT_WAVE_LENGTH
    leader_offset: int = 0
    round_offset: int = 0


class BaseCommitter:
    def __init__(
        self,
        committee: Committee,
        block_store: BlockStore,
        options: Optional[BaseCommitterOptions] = None,
    ) -> None:
        self.committee = committee
        self.block_store = block_store
        self.options = options or BaseCommitterOptions()
        assert self.options.wave_length >= MINIMUM_WAVE_LENGTH

    # -- wave arithmetic (base_committer.rs:71-86) --

    def wave_number(self, round_: RoundNumber) -> int:
        return max(0, round_ - self.options.round_offset) // self.options.wave_length

    def leader_round(self, wave: int) -> RoundNumber:
        return wave * self.options.wave_length + self.options.round_offset

    def decision_round(self, wave: int) -> RoundNumber:
        wl = self.options.wave_length
        return wave * wl + wl - 1 + self.options.round_offset

    def elect_leader(self, round_: RoundNumber) -> Optional[AuthorityRound]:
        wave = self.wave_number(round_)
        if self.leader_round(wave) != round_:
            return None
        return AuthorityRound(
            self.committee.elect_leader(round_, self.options.leader_offset), round_
        )

    # -- DAG predicates (base_committer.rs:109-180) --

    def find_support(
        self, author_round: AuthorityRound, from_block: StatementBlock
    ) -> Optional[BlockReference]:
        """Which block at (author, round) does ``from_block`` support?

        The *first* include matching (author, round) wins — ordered includes define
        support, and any descendant including ``from_block`` inherits its choice.
        """
        if from_block.round() < author_round.round:
            return None
        target = (author_round.authority, author_round.round)
        for include in from_block.includes:
            if include.author_round() == target:
                return include
            # Weak links may point below the target round; skip them.
            if include.round <= author_round.round:
                continue
            inner = self.block_store.get_block(include)
            assert inner is not None, "whole sub-dag must be stored by now"
            support = self.find_support(author_round, inner)
            if support is not None:
                return support
        return None

    def is_vote(self, potential_vote: StatementBlock, leader_block: StatementBlock) -> bool:
        ar = AuthorityRound(leader_block.author(), leader_block.round())
        return self.find_support(ar, potential_vote) == leader_block.reference

    def is_certificate(
        self,
        potential_certificate: StatementBlock,
        leader_block: StatementBlock,
        all_votes: Dict[BlockReference, bool],
        trace=None,
    ) -> bool:
        """2f+1 stake of ``potential_certificate``'s includes vote for the leader.

        ``all_votes`` memoizes per-reference vote checks; it is only valid for one
        ``leader_block`` (base_committer.rs:149-151).  ``trace`` (an optional
        :class:`~mysticeti_tpu.decisions.DecisionTrace`) captures the vote
        tally — the best one seen, whether or not quorum was reached — as a
        side channel; it never affects the decision.
        """
        aggregator = StakeAggregator(QUORUM)
        for reference in potential_certificate.includes:
            vote = all_votes.get(reference)
            if vote is None:
                if reference.round <= leader_block.round():
                    # Cannot vote for the leader (includes point strictly
                    # down-round, so no path from here reaches a block that
                    # links the leader).  Also the reference may simply not
                    # be stored: a snapshot-rejoiner's first proposal
                    # carries its pre-crash pending includes — settled
                    # history the rest of the fleet long GC'd (the
                    # BlockManager admits such blocks by treating sub-floor
                    # includes as satisfied; this walk must tolerate the
                    # same shape instead of asserting).
                    all_votes[reference] = False
                    continue
                block = self.block_store.get_block(reference)
                assert block is not None, "whole sub-dag must be stored by now"
                vote = self.is_vote(block, leader_block)
                all_votes[reference] = vote
            if vote and aggregator.add(reference.authority, self.committee):
                if trace is not None:
                    trace.note_certificates(aggregator)
                return True
        if trace is not None:
            trace.note_certificates(aggregator)
        return False

    # -- decisions --

    def decide_leader_from_anchor(
        self, anchor: StatementBlock, leader: AuthorityRound, trace=None
    ) -> LeaderStatus:
        """Commit the target leader iff it has a certificate among the anchor's
        ancestors at the target's decision round (base_committer.rs:184-224)."""
        if trace is not None:
            trace.note_anchor(AuthorityRound(anchor.author(), anchor.round()))
        leader_blocks = self.block_store.get_blocks_at_authority_round(
            leader.authority, leader.round
        )
        wave = self.wave_number(leader.round)
        decision_round = self.decision_round(wave)
        potential_certificates = self.block_store.linked_to_round(anchor, decision_round)

        certified: List[StatementBlock] = []
        for leader_block in leader_blocks:
            all_votes: Dict[BlockReference, bool] = {}
            if any(
                self.is_certificate(pc, leader_block, all_votes, trace=trace)
                for pc in potential_certificates
            ):
                certified.append(leader_block)
        if len(certified) > 1:
            raise RuntimeError(
                f"More than one certified block at wave {wave} from leader {leader!r}"
            )
        if certified:
            return LeaderStatus.commit(certified[0])
        return LeaderStatus.skip(leader)

    def enough_leader_blame(
        self, voting_round: RoundNumber, leader: AuthorityIndex, trace=None
    ) -> bool:
        """2f+1 stake of voting-round blocks with no include from the leader
        (base_committer.rs:228-249)."""
        aggregator = StakeAggregator(QUORUM)
        quorum = False
        for voting_block in self.block_store.get_blocks_by_round(voting_round):
            if all(inc.authority != leader for inc in voting_block.includes):
                if aggregator.add(voting_block.author(), self.committee):
                    quorum = True
                    break
        if trace is not None:
            trace.note_blames(aggregator)
        return quorum

    def enough_leader_support(
        self, decision_round: RoundNumber, leader_block: StatementBlock, trace=None
    ) -> bool:
        """2f+1 stake of decision-round blocks that are certificates
        (base_committer.rs:253-289)."""
        decision_blocks = self.block_store.get_blocks_by_round(decision_round)
        total = self.committee.get_total_stake(b.author() for b in decision_blocks)
        if total < self.committee.quorum_threshold():
            return False
        aggregator = StakeAggregator(QUORUM)
        all_votes: Dict[BlockReference, bool] = {}
        quorum = False
        for decision_block in decision_blocks:
            # The trace tallies the outer aggregator (decision-round authors
            # whose blocks certify the leader), not the per-block vote walks.
            if self.is_certificate(decision_block, leader_block, all_votes):
                if aggregator.add(decision_block.author(), self.committee):
                    quorum = True
                    break
        if trace is not None:
            trace.note_certificates(aggregator)
        return quorum

    def try_indirect_decide(
        self, leader: AuthorityRound, leaders: Iterable[LeaderStatus], trace=None
    ) -> LeaderStatus:
        """Decide from the first committed anchor at least one wave later
        (base_committer.rs:294-318).  ``leaders`` is the (higher-round) decided
        sequence so far, in increasing round order."""
        for anchor in leaders:
            if leader.round + self.options.wave_length > anchor.round:
                continue
            if anchor.kind == LeaderStatus.COMMIT:
                return self.decide_leader_from_anchor(anchor.block, leader, trace=trace)
            if anchor.kind == LeaderStatus.UNDECIDED:
                break
        return LeaderStatus.undecided(leader)

    def try_direct_decide(self, leader: AuthorityRound, trace=None) -> LeaderStatus:
        """The fast path (base_committer.rs:323-357)."""
        voting_round = leader.round + 1
        if self.enough_leader_blame(voting_round, leader.authority, trace=trace):
            return LeaderStatus.skip(leader)

        wave = self.wave_number(leader.round)
        decision_round = self.decision_round(wave)
        supported = [
            block
            for block in self.block_store.get_blocks_at_authority_round(
                leader.authority, leader.round
            )
            if self.enough_leader_support(decision_round, block, trace=trace)
        ]
        if len(supported) > 1:
            raise RuntimeError(
                f"More than one certified block for {leader!r}"
            )
        if supported:
            return LeaderStatus.commit(supported[0])
        return LeaderStatus.undecided(leader)

    def __repr__(self) -> str:
        return (
            f"Committer-L{self.options.leader_offset}-R{self.options.round_offset}"
        )
