"""Composition of base committers: multi-leader x pipelining, longest-decided-prefix.

Capability parity with ``mysticeti-core/src/consensus/universal_committer.rs``:

* ``try_commit`` (:30-90) — scan rounds from high to low across all committers
  (reverse order), direct rule first, fall back to the indirect rule with the
  already-decided higher-round sequence; return the longest decided prefix in
  increasing round order, stopping at the first undecided leader.
* ``get_leaders`` (:95-101) — all leaders for a round (syncer proposal gating).
* ``UniversalCommitterBuilder`` (:125-184) — pipeline stages (one committer per
  round offset 0..wave_length) x number_of_leaders (leader offsets).
"""
from __future__ import annotations

from typing import List, Optional

from . import AuthorityRound, DEFAULT_WAVE_LENGTH, DIRECT, INDIRECT, LeaderStatus
from .base_committer import BaseCommitter, BaseCommitterOptions
from ..block_store import BlockStore
from ..committee import Committee
from ..decisions import DecisionLedger, DecisionTrace
from ..types import AuthorityIndex, RoundNumber


class UniversalCommitter:
    def __init__(
        self,
        block_store: BlockStore,
        committers: List[BaseCommitter],
        metrics=None,
    ) -> None:
        self.block_store = block_store
        self.committers = committers
        self._metrics = metrics
        # Why each slot decided the way it did — exports
        # mysticeti_commit_decision_total{rule,outcome} (which replaced the
        # old per-authority direct-commit/indirect-skip committed_leaders
        # labels) and serves /debug/consensus.
        self.ledger = DecisionLedger(metrics=metrics)

    def try_commit(self, last_decided: AuthorityRound) -> List[LeaderStatus]:
        """Idempotent scan for newly decidable leaders (universal_committer.rs:30-90)."""
        highest_known_round = self.block_store.highest_round()
        # Direct decision for round R needs blocks at R+2.
        # [(status, decision, trace)] in increasing round order
        leaders: List[tuple] = []
        stop = False
        for round_ in range(max(0, highest_known_round - 2), last_decided.round - 1, -1):
            if stop:
                break
            for committer in reversed(self.committers):
                leader = committer.elect_leader(round_)
                if leader is None:
                    continue
                if leader == last_decided:
                    stop = True
                    break
                trace = DecisionTrace()
                status = committer.try_direct_decide(leader, trace=trace)
                decision = DIRECT
                if not status.is_decided():
                    status = committer.try_indirect_decide(
                        leader, (s for s, _, _ in leaders), trace=trace
                    )
                    decision = INDIRECT
                leaders.insert(0, (status, decision, trace))
        # Longest decided prefix, excluding genesis.  Only the emitted prefix
        # is recorded in the ledger: the core advances its cursor past it, so
        # those slots are never rescanned (exactly one record per slot),
        # while decided slots above the first undecided WILL be rescanned on
        # a later call and must not be recorded yet.
        out: List[LeaderStatus] = []
        undecided: List[AuthorityRound] = []
        emitting = True
        for status, decision, trace in leaders:
            if status.round == 0:
                continue
            if not status.is_decided():
                emitting = False
                undecided.append(status.authority_round)
                continue
            if not emitting:
                continue
            out.append(status)
            self.ledger.record_decision(
                status, decision, trace, highest_known_round - status.round
            )
        self.ledger.note_undecided(undecided)
        return out

    def get_leaders(self, round_: RoundNumber) -> List[AuthorityIndex]:
        return [
            leader.authority
            for committer in self.committers
            if (leader := committer.elect_leader(round_)) is not None
        ]


class UniversalCommitterBuilder:
    def __init__(self, committee: Committee, block_store: BlockStore, metrics=None) -> None:
        self.committee = committee
        self.block_store = block_store
        self.metrics = metrics
        self.wave_length = DEFAULT_WAVE_LENGTH
        self.number_of_leaders = 1
        self.pipeline = False

    def with_wave_length(self, wave_length: int) -> "UniversalCommitterBuilder":
        self.wave_length = wave_length
        return self

    def with_number_of_leaders(self, n: int) -> "UniversalCommitterBuilder":
        self.number_of_leaders = n
        return self

    def with_pipeline(self, pipeline: bool) -> "UniversalCommitterBuilder":
        self.pipeline = pipeline
        return self

    def build(self) -> UniversalCommitter:
        committers = []
        pipeline_stages = self.wave_length if self.pipeline else 1
        for round_offset in range(pipeline_stages):
            for leader_offset in range(self.number_of_leaders):
                committers.append(
                    BaseCommitter(
                        self.committee,
                        self.block_store,
                        BaseCommitterOptions(
                            wave_length=self.wave_length,
                            leader_offset=leader_offset,
                            round_offset=round_offset,
                        ),
                    )
                )
        return UniversalCommitter(self.block_store, committers, self.metrics)
