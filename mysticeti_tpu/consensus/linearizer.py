"""Expand committed leaders into ordered sub-DAGs of their uncommitted causal history.

Capability parity with ``mysticeti-core/src/consensus/linearizer.rs``:

* ``CommittedSubDag`` {anchor, blocks, timestamp_ms, height} (:17-27), buildable
  from persisted ``CommitData`` (:45-65), sorted by round (:68-70).
* ``Linearizer`` (:91-166) — DFS collection of not-yet-committed causal history
  from each committed leader; monotone height counter; recovery from the commit
  observer's persisted state (:108-121).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Set

from ..block_store import BlockStore, CommitData
from ..state import CommitObserverRecoveredState
from ..types import BlockReference, StatementBlock


@dataclass
class CommittedSubDag:
    anchor: BlockReference
    blocks: List[StatementBlock]
    timestamp_ms: int
    height: int

    @staticmethod
    def new_from_commit_data(
        commit_data: CommitData, block_store: BlockStore
    ) -> "CommittedSubDag":
        blocks = []
        leader_block = None
        for ref in commit_data.sub_dag:
            block = block_store.get_block(ref)
            assert block is not None, "commit-data block must be stored"
            if ref == commit_data.leader:
                leader_block = block
            blocks.append(block)
        assert leader_block is not None, "leader block must be in the sub-dag"
        return CommittedSubDag(
            commit_data.leader,
            blocks,
            leader_block.meta_creation_time_ns // 1_000_000,
            commit_data.height,
        )

    def sort(self) -> None:
        self.blocks.sort(key=lambda b: b.round())

    def __repr__(self) -> str:
        refs = ", ".join(repr(b.reference) for b in self.blocks)
        return f"{self.anchor!r}@{self.height}({refs})"


class Linearizer:
    def __init__(self, block_store: BlockStore) -> None:
        self.block_store = block_store
        self.committed: Set[BlockReference] = set()
        self.last_height = 0
        # Storage-GC floor (storage.py): references strictly below it are
        # settled — retired from disk, guaranteed inside some committed
        # history — so the DFS treats them like already-committed blocks.
        # Also the snapshot catch-up seam: a node that adopted a remote
        # commit baseline lacks all history below the served floor.
        self.gc_round = 0

    def recover_state(self, recovered: CommitObserverRecoveredState) -> None:
        assert not self.committed and self.last_height == 0
        self.last_height = recovered.base_height
        self.committed.update(recovered.base_committed)
        self.gc_round = max(self.gc_round, recovered.gc_round)
        for commit in recovered.sub_dags:
            assert commit.height > self.last_height
            self.last_height = commit.height
            self.committed.update(commit.sub_dag)
            assert commit.leader in self.committed

    def set_gc_round(self, gc_round: int) -> None:
        """Raise the floor and prune the committed set below it (the set
        otherwise grows with the whole run — the GC'd node's memory bound)."""
        if gc_round <= self.gc_round:
            return
        self.gc_round = gc_round
        self.committed = {r for r in self.committed if r.round >= gc_round}

    def adopt_snapshot(
        self, height: int, committed_refs, gc_round: int
    ) -> None:
        """Snapshot catch-up: jump the sequencer to the remote baseline —
        heights at or below ``height`` are the adopted prefix, the committed
        set becomes the baseline's (everything below its floor is settled)."""
        self.last_height = max(self.last_height, height)
        self.committed.update(committed_refs)
        self.set_gc_round(gc_round)

    def collect_sub_dag(self, leader_block: StatementBlock) -> CommittedSubDag:
        to_commit: List[StatementBlock] = []
        timestamp_ms = leader_block.meta_creation_time_ns // 1_000_000
        leader_ref = leader_block.reference
        assert leader_ref not in self.committed
        self.committed.add(leader_ref)
        buffer = [leader_block]
        while buffer:
            block = buffer.pop()
            to_commit.append(block)
            for reference in block.includes:
                if reference in self.committed or reference.round < self.gc_round:
                    continue
                inner = self.block_store.get_block(reference)
                assert inner is not None, "whole sub-dag must be stored by now"
                self.committed.add(reference)
                buffer.append(inner)
        self.last_height += 1
        return CommittedSubDag(leader_ref, to_commit, timestamp_ms, self.last_height)

    def handle_commit(
        self, committed_leaders: List[StatementBlock]
    ) -> List[CommittedSubDag]:
        out = []
        for leader_block in committed_leaders:
            sub_dag = self.collect_sub_dag(leader_block)
            sub_dag.sort()
            out.append(sub_dag)
        return out
