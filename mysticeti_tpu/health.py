"""Fleet health plane: why a run was slow, not just that it was.

The metrics substrate (:mod:`metrics`) says *how fast* the node is and the
span tracer (:mod:`spans`) says *where* a block's latency went; this module
turns both into a diagnosis:

* :class:`HealthProbe` — per-node consensus health derived from state the
  node already has: round-advance rate and commit-rate EMAs, DAG frontier
  skew (own round vs max peer round), per-authority frontier lag, verifier
  state (circuit breaker, routing pin, pipeline in-flight), WAL append
  backlog.  Exported as ``mysticeti_health_*`` gauges and as a
  readiness/diagnosis JSON document served next to ``/healthz``.
* :class:`SLOThresholds` + the probe's watchdog — declarative thresholds
  (min commit rate, max round-stall seconds, max breaker-open fraction,
  max per-authority lag) raising structured, counted :class:`Alert` events
  that NAME the violating authority and pipeline stage.  Alerts fire on
  threshold *transitions* (degraded edge), not every tick.
* :class:`CriticalPathAnalyzer` — commit critical-path attribution from the
  span stream: per committed leader, which pipeline stage dominated the
  receive -> verify -> dag_add -> proposal_wait -> commit -> finalize chain,
  attributed to the leader's authoring authority.  Exported as the
  ``commit_critical_path_seconds{stage}`` histogram plus a top-blocking
  (stage, authority) table in the diagnosis document
  (``tools/trace_report.py --critical-path`` computes the same offline).
* :func:`cluster_snapshot` — fleet-level health from per-node ``/metrics``
  scrapes (quorum participation, per-authority straggler score, cross-node
  commit skew); consumed by ``tools/fleetmon.py`` and the orchestrator's
  scrape loop so every perf artifact ships with its own diagnosis.
* :class:`FleetHealthMonitor` — a loop-clocked central sampler over a set
  of probes (the chaos/sim harnesses): a seeded run produces a
  byte-identical health timeline and alert stream every run.

Everything is clocked by the RUNTIME clock (virtual under the deterministic
simulator), and the probe reads only already-maintained state — no new
bookkeeping on any hot path.
"""
from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

from .runtime import now as runtime_now
from .spans import PIPELINE_STAGES
from .tracing import logger
from .utils.tasks import spawn_logged

log = logger(__name__)

# Which pipeline stage an alert kind indicts.  round/authority stalls mean
# blocks are not ARRIVING (receive); commit stalls mean the decision rule is
# starved (commit); breaker trouble sits on the verify edge.
ALERT_STAGES = {
    "round-stall": "receive",
    "commit-stall": "commit",
    "commit-rate": "commit",
    "authority-lag": "receive",
    "breaker-open": "verify",
    "low-participation": "receive",
    # Host attribution plane (hostattr.py): a laggy or blocked event loop
    # starves block ingestion first, so both kinds indict the dag_add edge.
    "loop-lag": "dag_add",
    "blocking-call": "dag_add",
    # Finality SLI plane (finality.py): a breaching submit→finalized p99
    # means transactions linger between proposal and the observer, so the
    # finalize edge is where to start looking.
    "finality-p99": "finalize",
}

# Snapshot keys whose values depend on real-thread timing (the WAL drain
# thread races the sampler even under the virtual-time loop); the
# deterministic timeline strips them so seeded runs stay byte-identical.
VOLATILE_KEYS = ("wal_backlog",)

_EMA_ALPHA = 0.3


@dataclass(frozen=True)
class SLOThresholds:
    """Declarative health SLOs.  A zero/None threshold disables its check."""

    min_commit_rate: float = 0.0  # committed sub-dags per second
    max_round_stall_s: float = 10.0
    max_commit_stall_s: float = 0.0
    max_authority_lag_rounds: int = 0
    # Fraction of recent samples with the verifier breaker open (window =
    # BREAKER_WINDOW most recent samples).
    max_breaker_open_fraction: float = 0.0
    # Cluster-level: fraction of authorities that must be participating
    # (frontier lag within max_authority_lag_rounds).
    min_participation: float = 0.0
    # Host attribution plane (hostattr.py): event-loop responsiveness SLOs.
    max_loop_lag_s: float = 0.0  # loop-lag p99 ceiling
    max_blocking_call_ms: float = 0.0  # worst synchronous core-owner hold
    # Finality SLI plane (finality.py): submit→finalized p99 ceiling.
    max_finality_p99_s: float = 0.0

    def to_dict(self) -> dict:
        return {
            "min_commit_rate": self.min_commit_rate,
            "max_round_stall_s": self.max_round_stall_s,
            "max_commit_stall_s": self.max_commit_stall_s,
            "max_authority_lag_rounds": self.max_authority_lag_rounds,
            "max_breaker_open_fraction": self.max_breaker_open_fraction,
            "min_participation": self.min_participation,
            "max_loop_lag_s": self.max_loop_lag_s,
            "max_blocking_call_ms": self.max_blocking_call_ms,
            "max_finality_p99_s": self.max_finality_p99_s,
        }

    @staticmethod
    def from_dict(d: dict) -> "SLOThresholds":
        return SLOThresholds(
            min_commit_rate=float(d.get("min_commit_rate", 0.0)),
            max_round_stall_s=float(d.get("max_round_stall_s", 10.0)),
            max_commit_stall_s=float(d.get("max_commit_stall_s", 0.0)),
            max_authority_lag_rounds=int(d.get("max_authority_lag_rounds", 0)),
            max_breaker_open_fraction=float(
                d.get("max_breaker_open_fraction", 0.0)
            ),
            min_participation=float(d.get("min_participation", 0.0)),
            max_loop_lag_s=float(d.get("max_loop_lag_s", 0.0)),
            max_blocking_call_ms=float(d.get("max_blocking_call_ms", 0.0)),
            max_finality_p99_s=float(d.get("max_finality_p99_s", 0.0)),
        )


@dataclass(frozen=True)
class Alert:
    """One SLO violation, naming the violating authority and stage."""

    t: float
    kind: str
    stage: str
    authority: Optional[int]  # the INDICTED authority (None = whole node)
    observer: int  # the authority whose probe raised it
    value: float
    threshold: float
    detail: str

    def to_dict(self) -> dict:
        return {
            "t": round(self.t, 6),
            "kind": self.kind,
            "stage": self.stage,
            "authority": self.authority,
            "observer": self.observer,
            "value": round(self.value, 6),
            "threshold": self.threshold,
            "detail": self.detail,
        }


def _canonical(obj) -> bytes:
    return json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()


# ---------------------------------------------------------------------------
# Commit critical-path attribution (from the span stream)


class CriticalPathAnalyzer:
    """Per committed leader: which (stage, authority) edge blocked the commit.

    Registered as a :class:`~mysticeti_tpu.spans.SpanTracer` sink.  Pipeline
    spans for blocks on this node's track are indexed per block reference;
    the ``commit`` span for a leader closes the chain (``finalize`` and the
    ``proposal_wait`` close are recorded just before it inside the same
    commit pass), so at that moment every stage interval the leader crossed
    is known.  The longest stage is THE critical-path edge, attributed to
    the leader's authoring authority — a slow ``receive`` for leader A3R7
    means authority 3 (or the link to it) held the quorum up.
    """

    MAX_TRACKED = 20_000

    def __init__(self, metrics=None, authority: Optional[int] = None) -> None:
        self.metrics = metrics
        self.authority = authority
        self._stages: Dict[object, Dict[str, float]] = {}
        # (stage, author) -> [leaders attributed, total blocked seconds]
        self._blocking: Dict[Tuple[str, int], List[float]] = {}
        self.leaders_attributed = 0

    def on_span(self, stage, ref, authority, t0, t1) -> None:
        if self.authority is not None and authority != self.authority:
            return
        if stage not in PIPELINE_STAGES:
            return
        if stage == "commit":
            self._finish(ref, t1 - t0)
            return
        entry = self._stages.get(ref)
        if entry is None:
            if len(self._stages) >= self.MAX_TRACKED:
                # FIFO eviction: blocks that never commit must not pin memory.
                self._stages.pop(next(iter(self._stages)))
            entry = self._stages[ref] = {}
        entry[stage] = t1 - t0

    def _finish(self, ref, commit_dur: float) -> None:
        durations = self._stages.pop(ref, {})
        durations["commit"] = commit_dur
        blocking_stage = max(durations, key=lambda s: (durations[s], s))
        if self.metrics is not None:
            channel = self.metrics.commit_critical_path_seconds
            for stage, dur in durations.items():
                channel.labels(stage).observe(max(0.0, dur))
        author = getattr(ref, "authority", None)
        if author is not None:
            slot = self._blocking.setdefault((blocking_stage, author), [0, 0.0])
            slot[0] += 1
            slot[1] += max(0.0, durations[blocking_stage])
        self.leaders_attributed += 1

    def top_blocking(self, n: int = 5) -> List[dict]:
        """Top (stage, authority) pairs by total blocked seconds."""
        ranked = sorted(
            self._blocking.items(), key=lambda kv: (-kv[1][1], kv[0])
        )
        return [
            {
                "stage": stage,
                "authority": authority,
                "leaders": int(count),
                "blocked_s": round(total, 6),
            }
            for (stage, authority), (count, total) in ranked[:n]
        ]


# ---------------------------------------------------------------------------
# Per-node probe + watchdog


class HealthProbe:
    """Derives consensus-level health from state the node already maintains.

    ``attach`` binds (and re-binds, after a crash-restart rebuild) the live
    node objects; ``sample`` takes one loop-clocked reading, refreshes the
    ``mysticeti_health_*`` gauges, and runs the SLO watchdog.  ``start``
    spawns a periodic sampling task for production nodes; deterministic
    harnesses drive :meth:`sample` themselves through a
    :class:`FleetHealthMonitor`.
    """

    BREAKER_WINDOW = 20
    MAX_ALERTS = 10_000

    def __init__(
        self,
        authority: int,
        committee_size: int,
        metrics=None,
        slo: Optional[SLOThresholds] = None,
        clock: Callable[[], float] = runtime_now,
        recorder=None,
    ) -> None:
        self.authority = authority
        self.committee_size = committee_size
        self.metrics = metrics
        self.slo = slo or SLOThresholds()
        self.clock = clock
        # Flight recorder (flight_recorder.py): alert edges and verifier
        # breaker/pin transitions land in the node's event ring; an alert
        # additionally triggers a debounced on-disk dump when the recorder
        # has a path.
        self.recorder = recorder
        self._last_breaker_open: Optional[bool] = None
        self._last_pinned: Optional[bool] = None
        self.alerts: List[Alert] = []
        self.critical_path: Optional[CriticalPathAnalyzer] = None
        self._core = None
        self._net_syncer = None
        self._block_verifier = None
        self._commit_observer = None
        self._ingress = None
        self._host_monitor = None
        self._task: Optional[asyncio.Task] = None
        # Rate state.
        self._last_t: Optional[float] = None
        self._last_round = 0
        self._last_commit_height = 0
        self._round_advance_t: Optional[float] = None
        self._commit_advance_t: Optional[float] = None
        self._round_rate_ema = 0.0
        self._commit_rate_ema = 0.0
        self._breaker_samples: List[int] = []
        # Alert-kind transition state: (kind, authority) currently firing.
        self._firing: set = set()
        self.last_snapshot: Optional[dict] = None

    # -- wiring --

    def attach(
        self,
        core=None,
        net_syncer=None,
        block_verifier=None,
        commit_observer=None,
        ingress=None,
        host_monitor=None,
    ) -> "HealthProbe":
        if core is not None:
            self._core = core
        if net_syncer is not None:
            self._net_syncer = net_syncer
        if block_verifier is not None:
            self._block_verifier = block_verifier
        if commit_observer is not None:
            self._commit_observer = commit_observer
        if ingress is not None:
            self._ingress = ingress
        if host_monitor is not None:
            self._host_monitor = host_monitor
        return self

    def detach(self) -> None:
        """Drop node references (crash): the probe object survives so rate
        state and the alert stream span restarts."""
        self._core = None
        self._net_syncer = None
        self._block_verifier = None
        self._commit_observer = None
        self._ingress = None

    def attach_critical_path(self, tracer) -> "HealthProbe":
        """Subscribe a critical-path analyzer to the span stream."""
        if self.critical_path is None:
            self.critical_path = CriticalPathAnalyzer(
                metrics=self.metrics, authority=self.authority
            )
            tracer.add_sink(self.critical_path.on_span)
        return self

    @property
    def attached(self) -> bool:
        return self._core is not None

    # -- sampling --

    def sample(self) -> dict:
        """One reading: snapshot dict + gauge refresh + watchdog pass."""
        t = self.clock()
        core = self._core
        if core is None:
            return {"down": True}
        round_ = core.current_round()
        commit_height = 0
        if self._commit_observer is not None:
            interpreter = getattr(
                self._commit_observer, "commit_interpreter", None
            )
            if interpreter is not None:
                commit_height = interpreter.last_height
        if self._last_t is None:
            self._round_advance_t = t
            self._commit_advance_t = t
        else:
            dt = t - self._last_t
            if dt > 0:
                self._round_rate_ema += _EMA_ALPHA * (
                    (round_ - self._last_round) / dt - self._round_rate_ema
                )
                self._commit_rate_ema += _EMA_ALPHA * (
                    (commit_height - self._last_commit_height) / dt
                    - self._commit_rate_ema
                )
        if round_ > self._last_round:
            self._round_advance_t = t
        if commit_height > self._last_commit_height:
            self._commit_advance_t = t
        self._last_t = t
        self._last_round = round_
        self._last_commit_height = commit_height

        # Frontier: own round vs what each peer has shown us.  Under epoch
        # reconfiguration (reconfig.py) an INACTIVE authority — cleanly
        # departed, or registered-at-genesis but not yet activated — is
        # retired, not a straggler: it produces no blocks by design, so it
        # is excluded from the lag table (no participation alerts) and
        # listed separately.  With reconfig off every authority has
        # positive stake and nothing changes.
        lags: Dict[int, int] = {}
        retired: List[int] = []
        max_peer_round = round_
        store = core.block_store
        committee = getattr(core, "committee", None)
        for a in range(self.committee_size):
            if a == self.authority:
                continue
            if committee is not None and not committee.is_active(a):
                retired.append(a)
                continue
            seen = store.last_seen_by_authority(a)
            lags[a] = max(0, round_ - seen)
            max_peer_round = max(max_peer_round, seen)
        frontier_skew = max_peer_round - round_

        verifier_state = None
        state_fn = getattr(self._block_verifier, "health_state", None)
        if state_fn is not None:
            verifier_state = state_fn()
        breaker_open = bool(verifier_state and verifier_state["breaker_open"])
        if self.recorder is not None and verifier_state is not None:
            pinned = bool(verifier_state.get("pinned_backend"))
            if self._last_breaker_open is not None and (
                breaker_open != self._last_breaker_open
            ):
                self.recorder.record(
                    "breaker", open=breaker_open
                )
            if self._last_pinned is not None and pinned != self._last_pinned:
                self.recorder.record(
                    "pin", pinned=pinned,
                    backend=verifier_state.get("pinned_backend"),
                )
            self._last_breaker_open = breaker_open
            self._last_pinned = pinned
        self._breaker_samples.append(1 if breaker_open else 0)
        if len(self._breaker_samples) > self.BREAKER_WINDOW:
            self._breaker_samples.pop(0)
        breaker_fraction = sum(self._breaker_samples) / len(
            self._breaker_samples
        )

        connected = (
            len(self._net_syncer.connected_authorities)
            if self._net_syncer is not None
            else None
        )
        # Constant False in virtual time (walf() forces sync writes), so
        # the /health snapshot stays deterministic under the sim.
        wal_backlog = bool(core.wal_writer.pending())  # lint: ignore[sim-taint]

        snapshot = {
            "t": round(t, 6),
            "round": round_,
            "commit_height": commit_height,
            "round_advance_rate": round(self._round_rate_ema, 6),
            "commit_rate": round(self._commit_rate_ema, 6),
            "round_stall_s": round(t - self._round_advance_t, 6),
            "commit_stall_s": round(t - self._commit_advance_t, 6),
            "frontier_skew_rounds": frontier_skew,
            "authority_lag_rounds": {str(a): lag for a, lag in lags.items()},
            "connected_authorities": connected,
            "breaker_open_fraction": round(breaker_fraction, 6),
            "wal_backlog": wal_backlog,
        }
        if getattr(core, "reconfig", None) is not None:
            # Reconfig-only keys, so pre-reconfig timelines stay
            # byte-identical: the node's current epoch plus the retired
            # (zero-stake) authorities excluded from the lag table above.
            snapshot["epoch"] = core.committee.epoch
            if retired:
                snapshot["retired_authorities"] = retired
        if verifier_state is not None:
            snapshot["verifier"] = verifier_state
        if self._ingress is not None:
            # Admission state in the /health diagnosis: a degraded node that
            # is SHEDDING reads differently from one silently drowning —
            # the whole point of the ingress plane (ingress.py).
            snapshot["ingress"] = self._ingress.health_state()
        if self._host_monitor is not None:
            # Host attribution plane (hostattr.py): loop-lag percentiles,
            # blocking-call census, GIL convoy ratio.  All-zero under the
            # sim (the probe and sampler never start in virtual time), so
            # the deterministic timeline stays byte-identical.
            host = dict(self._host_monitor.state())
            # Which native data-plane functions resolved in this process
            # (native/__init__.py): lets an operator — and the A/B
            # harness — tell from /health alone whether a node is running
            # the C extension or the pure-Python fallback.
            from .native import active_functions

            host["native_active"] = list(active_functions())
            snapshot["host"] = host
        alerts = self._watchdog(snapshot, lags)
        snapshot["status"] = "degraded" if self._firing else "ok"
        self._export_gauges(snapshot, lags)
        self.last_snapshot = snapshot
        if alerts:
            snapshot = dict(snapshot)  # timeline entries carry their alerts
            snapshot["alerts"] = [a.to_dict() for a in alerts]
        return snapshot

    def _export_gauges(self, snapshot: dict, lags: Dict[int, int]) -> None:
        m = self.metrics
        if m is None:
            return
        m.mysticeti_health_round_advance_rate.set(
            snapshot["round_advance_rate"]
        )
        m.mysticeti_health_commit_rate.set(snapshot["commit_rate"])
        m.mysticeti_health_frontier_skew_rounds.set(
            snapshot["frontier_skew_rounds"]
        )
        for a, lag in lags.items():
            m.mysticeti_health_authority_lag_rounds.labels(str(a)).set(lag)
        verifier = snapshot.get("verifier")
        m.mysticeti_health_verifier_breaker_open.set(
            1 if (verifier and verifier["breaker_open"]) else 0
        )
        m.mysticeti_health_verifier_pinned.set(
            1 if (verifier and verifier.get("pinned_backend")) else 0
        )
        m.mysticeti_health_wal_backlog.set(1 if snapshot["wal_backlog"] else 0)
        m.mysticeti_health_status.set(1 if not self._firing else 0)

    # -- the SLO watchdog --

    def _watchdog(self, snapshot: dict, lags: Dict[int, int]) -> List[Alert]:
        slo = self.slo
        new: List[Alert] = []

        def check(kind: str, authority, value, threshold, above, detail):
            key = (kind, authority)
            violated = value > threshold if above else value < threshold
            if violated and key not in self._firing:
                self._firing.add(key)
                alert = Alert(
                    t=snapshot["t"],
                    kind=kind,
                    stage=ALERT_STAGES[kind],
                    authority=authority,
                    observer=self.authority,
                    value=float(value),
                    threshold=float(threshold),
                    detail=detail,
                )
                if len(self.alerts) < self.MAX_ALERTS:
                    self.alerts.append(alert)
                    new.append(alert)
                if self.metrics is not None:
                    self.metrics.mysticeti_health_slo_alerts_total.labels(
                        kind,
                        "" if authority is None else str(authority),
                        alert.stage,
                    ).inc()
                if self.recorder is not None:
                    self.recorder.on_alert(
                        kind, authority, alert.stage, alert.value, detail
                    )
            elif not violated:
                self._firing.discard(key)

        if slo.max_round_stall_s > 0:
            check(
                "round-stall", None, snapshot["round_stall_s"],
                slo.max_round_stall_s, True,
                f"round {snapshot['round']} stalled "
                f"{snapshot['round_stall_s']:.1f}s",
            )
        if slo.max_commit_stall_s > 0:
            check(
                "commit-stall", None, snapshot["commit_stall_s"],
                slo.max_commit_stall_s, True,
                f"no commit past height {snapshot['commit_height']} for "
                f"{snapshot['commit_stall_s']:.1f}s",
            )
        if slo.min_commit_rate > 0 and self._last_commit_height > 0:
            # Distinct kind from commit-stall: both would share the firing
            # key otherwise, and the stall check clearing it every healthy
            # tick would make the rate alert re-fire per sample.  Armed only
            # once the node has EVER committed — the EMA warms up from zero,
            # and a boot-time "rate below floor" would mark every run with
            # this threshold degraded; a node that never commits at all is
            # the commit-stall check's case.
            check(
                "commit-rate", None, snapshot["commit_rate"],
                slo.min_commit_rate, False,
                f"commit rate {snapshot['commit_rate']:.3f}/s below floor",
            )
        if slo.max_authority_lag_rounds > 0:
            for a in sorted(lags):
                check(
                    "authority-lag", a, lags[a],
                    slo.max_authority_lag_rounds, True,
                    f"authority {a} last seen "
                    f"{lags[a]} rounds behind round {snapshot['round']}",
                )
        if slo.max_breaker_open_fraction > 0:
            check(
                "breaker-open", None, snapshot["breaker_open_fraction"],
                slo.max_breaker_open_fraction, True,
                "verifier circuit breaker open fraction over threshold",
            )
        monitor = self._host_monitor
        if monitor is not None:
            host = snapshot.get("host") or monitor.state()
            if slo.max_loop_lag_s > 0 and host["loop_lag_samples"] > 0:
                check(
                    "loop-lag", None, host["loop_lag_p99_s"],
                    slo.max_loop_lag_s, True,
                    f"event-loop lag p99 {host['loop_lag_p99_s'] * 1e3:.1f}ms"
                    " over SLO",
                )
            if slo.max_blocking_call_ms > 0:
                # Worst hold SINCE THE LAST SAMPLE: draining re-arms the
                # alert after one clean interval, matching the other
                # transition-edge kinds.
                worst_ms = monitor.drain_worst_blocking_ms()
                last = host.get("last_blocking") or {}
                check(
                    "blocking-call", None, worst_ms,
                    slo.max_blocking_call_ms, True,
                    f"synchronous {last.get('site', '?')} held the core "
                    f"owner {worst_ms:.1f}ms",
                )
        if slo.max_finality_p99_s > 0:
            fin = (snapshot.get("ingress") or {}).get("finality") or {}
            # Armed only once samples exist: an idle node (or one with the
            # tracker disabled) reports p99 = 0, not a breach or an all-clear.
            if fin.get("samples", 0) > 0:
                check(
                    "finality-p99", None, fin["p99_s"],
                    slo.max_finality_p99_s, True,
                    f"submit->finalized p99 {fin['p99_s']:.3f}s over SLO "
                    f"({fin['completed']} sampled tx completed)",
                )
        return new

    # -- diagnosis document (served next to /healthz) --

    def diagnosis(self) -> dict:
        doc = {
            "authority": self.authority,
            "status": "degraded" if self._firing else "ok",
            "attached": self.attached,
            "slo": self.slo.to_dict(),
            "signals": self.last_snapshot,
            "alerts": [a.to_dict() for a in self.alerts[-20:]],
            "alerts_total": len(self.alerts),
        }
        if self.critical_path is not None:
            doc["critical_path"] = {
                "leaders_attributed": self.critical_path.leaders_attributed,
                "top_blocking": self.critical_path.top_blocking(),
            }
        return doc

    # -- periodic sampler (production nodes) --

    def start(self, interval_s: float = 5.0) -> "HealthProbe":
        if self._task is None:
            self._task = spawn_logged(
                self._run(interval_s), log, name="health-probe"
            )
        return self

    async def _run(self, interval_s: float) -> None:
        while True:
            await asyncio.sleep(interval_s)
            try:
                self.sample()
            except Exception:  # noqa: BLE001 - the probe must outlive glitches
                log.exception("health probe sample failed")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None


# ---------------------------------------------------------------------------
# Deterministic fleet monitor (sim harnesses)


class FleetHealthMonitor:
    """Central loop-clocked sampler over a fleet of probes.

    One ordered tick across all authorities per interval, so a seeded sim
    produces a byte-identical timeline (:meth:`timeline_bytes`) and alert
    stream every run.  ``probe_of(authority)`` returns the live probe or
    None when the node is down (crashed); down nodes are recorded as such.
    """

    def __init__(
        self,
        probe_of: Callable[[int], Optional[HealthProbe]],
        n: int,
        interval_s: float = 1.0,
    ) -> None:
        self.probe_of = probe_of
        self.n = n
        self.interval_s = interval_s
        self.timeline: List[dict] = []
        self._task: Optional[asyncio.Task] = None
        # Epoch reconfiguration: authorities that departed CLEANLY (or have
        # not activated yet) are "retired", not "down" — expected absence,
        # never a degraded-fleet signal.
        self.retired: Set[int] = set()

    def note_retired(self, authority: int) -> None:
        self.retired.add(authority)

    def note_joined(self, authority: int) -> None:
        self.retired.discard(authority)

    def tick(self) -> dict:
        nodes: Dict[str, dict] = {}
        for authority in range(self.n):
            probe = self.probe_of(authority)
            if probe is None or not probe.attached:
                if authority in self.retired:
                    nodes[str(authority)] = {"retired": True}
                else:
                    nodes[str(authority)] = {"down": True}
                continue
            snapshot = dict(probe.sample())
            for key in VOLATILE_KEYS:
                snapshot.pop(key, None)
            nodes[str(authority)] = snapshot
        entry = {"t": round(runtime_now(), 6), "nodes": nodes}
        self.timeline.append(entry)
        return entry

    def alert_stream(self) -> List[dict]:
        """Every alert raised by any probe, in (t, observer) order."""
        alerts: List[Alert] = []
        for authority in range(self.n):
            probe = self.probe_of(authority)
            if probe is not None:
                alerts.extend(probe.alerts)
        alerts.sort(key=lambda a: (a.t, a.observer, a.kind, str(a.authority)))
        return [a.to_dict() for a in alerts]

    def timeline_bytes(self) -> bytes:
        return _canonical(self.timeline)

    def alert_stream_bytes(self) -> bytes:
        return _canonical(self.alert_stream())

    def fleet_report(self) -> dict:
        """End-of-run verdict: green iff no alerts and every authority is
        within the participation floor at the final sample."""
        alerts = self.alert_stream()
        last = self.timeline[-1] if self.timeline else {"nodes": {}}
        lag_threshold = 0
        participating = self.n
        for authority in range(self.n):
            probe = self.probe_of(authority)
            if probe is not None and probe.slo.max_authority_lag_rounds > 0:
                lag_threshold = probe.slo.max_authority_lag_rounds
                break
        max_lag = 0
        if lag_threshold:
            behind = set()
            for snapshot in last["nodes"].values():
                for a, lag in (snapshot.get("authority_lag_rounds") or {}).items():
                    max_lag = max(max_lag, lag)
                    if lag > lag_threshold:
                        behind.add(a)
            participating = self.n - len(behind)
        down = [
            a for a, snap in last["nodes"].items() if snap.get("down")
        ]
        status = "ok"
        if alerts or down or participating < self.n:
            status = "degraded"
        return {
            "status": status,
            "alerts": alerts,
            "down": down,
            "participation": participating / self.n if self.n else 1.0,
            "max_authority_lag_rounds": max_lag,
            "samples": len(self.timeline),
        }

    # -- lifecycle --

    def start(self) -> "FleetHealthMonitor":
        if self._task is None:
            self._task = spawn_logged(self._run(), log, name="fleet-health")
        return self

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            self.tick()

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None


# ---------------------------------------------------------------------------
# Cluster-level health from /metrics scrapes (fleetmon + orchestrator)


def node_health_from_series(series) -> dict:
    """Reduce one node's parsed prometheus series (an iterable of
    ``(name, labels, value)``, e.g. from
    :func:`mysticeti_tpu.orchestrator.measurement.iter_series`) to the
    health-relevant view."""
    out: dict = {
        "round": 0,
        "commit_round": 0,
        "commit_rate": 0.0,
        "round_advance_rate": 0.0,
        "frontier_skew_rounds": 0,
        "status_ok": True,
        "committed_by_authority": {},
        "authority_lag_rounds": {},
        "slo_alerts": {},
        "loop_lag_p99_s": 0.0,
        "finality_p50_s": 0.0,
        "finality_p99_s": 0.0,
        "cpu_subsystems": {},
        "epoch": 0,
    }
    for name, labels, value in series:
        if name == "threshold_clock_round":
            out["round"] = int(value)
        elif name == "commit_round":
            out["commit_round"] = int(value)
        elif name == "mysticeti_epoch":
            out["epoch"] = int(value)
        elif name == "mysticeti_health_commit_rate":
            out["commit_rate"] = value
        elif name == "mysticeti_health_round_advance_rate":
            out["round_advance_rate"] = value
        elif name == "mysticeti_health_frontier_skew_rounds":
            out["frontier_skew_rounds"] = int(value)
        elif name == "mysticeti_health_status":
            out["status_ok"] = value >= 1.0
        elif name == "mysticeti_health_authority_lag_rounds":
            out["authority_lag_rounds"][labels.get("authority", "?")] = int(value)
        elif name == "committed_leaders_total":
            if "commit" in labels.get("status", ""):
                a = labels.get("authority", "?")
                out["committed_by_authority"][a] = (
                    out["committed_by_authority"].get(a, 0.0) + value
                )
        elif name == "mysticeti_health_slo_alerts_total":
            kind = labels.get("kind", "?")
            out["slo_alerts"][kind] = out["slo_alerts"].get(kind, 0.0) + value
        elif name == "mysticeti_loop_lag_p99_seconds":
            out["loop_lag_p99_s"] = value
        elif name == "mysticeti_e2e_finality_p50_seconds":
            out["finality_p50_s"] = value
        elif name == "mysticeti_e2e_finality_p99_seconds":
            out["finality_p99_s"] = value
        elif name == "mysticeti_cpu_seconds_total":
            # Attribution plane (profiling.py): per-subsystem CPU seconds,
            # summed over thread classes for the fleet view.
            sub = labels.get("subsystem", "?")
            out["cpu_subsystems"][sub] = (
                out["cpu_subsystems"].get(sub, 0.0) + value
            )
    return out


def cluster_snapshot(
    nodes: Dict[str, Optional[dict]],
    committee_size: int,
    slo: Optional[SLOThresholds] = None,
    retired: Optional[Set[str]] = None,
) -> dict:
    """Fleet-level health for one scrape tick.

    ``nodes`` maps node id -> :func:`node_health_from_series` output (None =
    unreachable this tick).  Quorum participation counts authorities whose
    blocks reached ANY committed sub-dag; the straggler score per authority
    is the worst frontier lag any node reports for it; cross-node commit
    skew is the spread of committed rounds across the fleet.

    ``retired`` names authorities that departed the committee CLEANLY
    (epoch reconfiguration): they are expected-absent, never counted
    unreachable, and ``committee_size`` should already be the CURRENT
    epoch's active count so quorum participation is judged against the
    committee that actually votes.
    """
    retired = retired or set()
    nodes = {k: v for k, v in nodes.items() if k not in retired}
    reachable = {k: v for k, v in nodes.items() if v is not None}
    commit_rounds = [v["commit_round"] for v in reachable.values()]
    committed_authorities = set()
    stragglers: Dict[str, int] = {}
    alert_totals: Dict[str, float] = {}
    for v in reachable.values():
        for a, count in v["committed_by_authority"].items():
            if count > 0:
                committed_authorities.add(a)
        for a, lag in v["authority_lag_rounds"].items():
            if a in retired:
                continue  # frozen gauge from before the departure
            stragglers[a] = max(stragglers.get(a, 0), lag)
        for kind, count in v["slo_alerts"].items():
            alert_totals[kind] = alert_totals.get(kind, 0.0) + count
    committed_authorities -= set(retired)
    participation = (
        len(committed_authorities) / committee_size if committee_size else 0.0
    )
    snapshot = {
        "reachable": sorted(reachable),
        "unreachable": sorted(k for k, v in nodes.items() if v is None),
        "retired": sorted(retired),
        "epochs_by_node": {
            k: int(v.get("epoch", 0)) for k, v in sorted(reachable.items())
        },
        "quorum_participation": round(participation, 4),
        "commit_skew_rounds": (
            max(commit_rounds) - min(commit_rounds) if commit_rounds else 0
        ),
        "max_commit_round": max(commit_rounds, default=0),
        "straggler_score": dict(sorted(stragglers.items())),
        "commit_rate_by_node": {
            k: round(v["commit_rate"], 4) for k, v in sorted(reachable.items())
        },
        "slo_alert_totals": dict(sorted(alert_totals.items())),
        "degraded_nodes": sorted(
            k for k, v in reachable.items() if not v["status_ok"]
        ),
        # Host attribution plane: per-node loop responsiveness and the
        # top-3 CPU consumers (busy subsystems only — idle is not a cost).
        "loop_lag_p99_by_node": {
            k: round(v.get("loop_lag_p99_s", 0.0), 6)
            for k, v in sorted(reachable.items())
        },
        # Finality SLI plane: per-node rolling submit→finalized percentiles.
        "finality_p99_by_node": {
            k: round(v.get("finality_p99_s", 0.0), 6)
            for k, v in sorted(reachable.items())
        },
        "top_cpu_subsystems": {
            k: [
                sub
                for sub, _ in sorted(
                    (v.get("cpu_subsystems") or {}).items(),
                    key=lambda kv: (-kv[1], kv[0]),
                )
                if sub != "event-loop-idle"
            ][:3]
            for k, v in sorted(reachable.items())
        },
    }
    reasons = []
    if snapshot["unreachable"]:
        reasons.append("unreachable:" + ",".join(snapshot["unreachable"]))
    if snapshot["degraded_nodes"]:
        reasons.append("degraded:" + ",".join(snapshot["degraded_nodes"]))
    # slo_alert_totals are CUMULATIVE counters — informational history, not
    # a live verdict.  Current degradation shows through degraded_nodes
    # (mysticeti_health_status re-arms on recovery); keying status on the
    # totals would leave one transient alert marking the fleet degraded
    # forever.
    if slo is not None and slo.min_participation > 0 and reachable:
        if participation < slo.min_participation:
            reasons.append("participation")
    # Loop-lag and finality-p99 SLO breaches turn the gate YELLOW, not red:
    # the node is answering and committing, but slowly — a warning state,
    # distinct from degraded (fleetmon still exits 0).
    yellow = set()
    if slo is not None and slo.max_loop_lag_s > 0:
        yellow.update(
            k
            for k, lag in snapshot["loop_lag_p99_by_node"].items()
            if lag > slo.max_loop_lag_s
        )
    if slo is not None and slo.max_finality_p99_s > 0:
        yellow.update(
            k
            for k, p99 in snapshot["finality_p99_by_node"].items()
            if p99 > slo.max_finality_p99_s
        )
    yellow = sorted(yellow)
    snapshot["yellow_nodes"] = yellow
    if reasons:
        snapshot["status"] = "degraded"
    elif yellow:
        snapshot["status"] = "yellow"
    else:
        snapshot["status"] = "ok"
    snapshot["degraded_reasons"] = reasons
    return snapshot


def cluster_snapshot_from_texts(
    texts: Dict[str, Optional[str]],
    committee_size: int,
    slo: Optional[SLOThresholds] = None,
    retired: Optional[Set[str]] = None,
) -> dict:
    """Convenience: per-node raw ``/metrics`` text (None = unreachable) ->
    :func:`cluster_snapshot`."""
    from .orchestrator.measurement import iter_series

    nodes = {
        k: None if text is None else node_health_from_series(iter_series(text))
        for k, text in texts.items()
    }
    return cluster_snapshot(nodes, committee_size, slo=slo, retired=retired)
