"""Pluggable block verification seam — where the TPU batch verifier plugs in.

Capability parity with ``mysticeti-core/src/block_validator.rs`` (the trait the
reference explicitly leaves as the application-level verification hook, :10-14)
plus the piece the reference lacks and this framework exists for: a **batching
collector** that accumulates blocks arriving across connections within a small
window and verifies their signatures as one TPU dispatch, instead of the
reference's serial per-connection ``block.verify()`` (net_sync.rs:352-372).

Split of responsibilities on the receive path:
  * consensus-rule checks (digest, epoch, author, includes, threshold clock) —
    host, cheap, per-block: ``StatementBlock.verify_structure``
  * Ed25519 signature — batched: ``BatchedSignatureVerifier`` (TPU) or
    ``CpuSignatureVerifier`` (oracle/fallback)
"""
from __future__ import annotations

import asyncio
import contextlib
import random
import threading
import time
from itertools import islice
from typing import List, Optional, Sequence, Tuple

from . import spans
from .committee import Committee
from .network import jittered_backoff
from .tracing import logger
from .runtime import is_simulated
from .types import StatementBlock, VerificationError
from .utils.tasks import spawn_logged
from .verify_pipeline import (
    STAGE_DEVICE,
    STAGE_FETCH,
    STAGE_PACK,
    CompletedDispatch,
    DeferredDispatch,
    VerifyPipeline,
)

log = logger(__name__)


class VerifierProtocolError(ConnectionError):
    """A verifier backend answered but REJECTED the request (committee
    mismatch, malformed frame).  Retrying cannot help and the circuit
    breaker must NOT treat it as an outage: a misconfigured validator fails
    fast instead of silently serving on the CPU oracle forever.  Defined
    here (not in verifier_service.py) so the breaker can exclude it without
    a circular import; the service module re-exports it."""


class BlockVerifier:
    """Application-content verification hook (block_validator.rs:10-14)."""

    async def verify(self, block: StatementBlock) -> None:
        """Raise VerificationError to reject."""
        raise NotImplementedError

    async def verify_blocks(self, blocks: Sequence[StatementBlock]) -> List[bool]:
        """Batch entry; default falls back to per-block verify."""
        out = []
        for b in blocks:
            try:
                await self.verify(b)
                out.append(True)
            except VerificationError:
                out.append(False)
        return out

    def note_committee(self, committee: "Committee") -> None:
        """Epoch reconfiguration hook (reconfig.py): the committee's stake
        table changed at a boundary commit.  Registry KEYS are stable
        (stable-index membership), so signature tables need no rebuild —
        only stake-weighted math (quorum endorsement) must follow the new
        committee.  Default: nothing stake-weighted here."""
        return None


class AcceptAllBlockVerifier(BlockVerifier):
    """block_validator.rs:18-27."""

    async def verify(self, block: StatementBlock) -> None:
        return None


class SignatureVerifier:
    """Synchronous batch signature check: (pubkeys, digests, signatures) -> bools."""

    def verify_signatures(
        self,
        public_keys: Sequence[bytes],
        digests: Sequence[bytes],
        signatures: Sequence[bytes],
    ) -> List[bool]:
        raise NotImplementedError

    def verify_signatures_async(self, public_keys, digests, signatures):
        """Staged-dispatch seam: submit without blocking, returning a handle
        whose ``result()`` blocks until the verdicts are ready.  Backends
        with a real async queue (JAX dispatch, the verifier-service socket)
        override this so the device computes while the host packs the next
        batch; the default defers the synchronous path to ``result()`` —
        host backends have no device queue to exploit, and the pipeline's
        fetch stage runs them on concurrent executor threads anyway."""
        return DeferredDispatch(
            self.verify_signatures, public_keys, digests, signatures
        )

    def warmup(self) -> None:
        """Optional: pay one-time costs (tracing, compilation) before the
        first real batch arrives.  Called from a background thread at node
        boot; default no-op."""

    def resolved_backend(self) -> str:
        """The platform this verifier's dispatches ACTUALLY land on.  Host
        oracles are "cpu"; accelerator backends override with the live
        runtime's answer so the verifier service can advertise it over
        HELLO_OK (and clients can short-circuit a service with no chip
        behind it)."""
        return "cpu"

    def padded_batch(self, n: int) -> int:
        """Device lanes an ``n``-signature dispatch actually occupies; the
        host paths pay no padding.  Telemetry only (padding waste =
        ``padded_batch(n) - n``)."""
        return n


class CpuSignatureVerifier(SignatureVerifier):
    """The CPU oracle path (cryptography/OpenSSL) — reference behavior
    (crypto.rs:174-189), also the correctness baseline for the TPU kernel."""

    def verify_signatures(self, public_keys, digests, signatures):
        from . import crypto

        out = []
        for pk, digest, sig in zip(public_keys, digests, signatures):
            out.append(crypto.PublicKey(pk).verify(sig, digest))
        return out


class TpuSignatureVerifier(SignatureVerifier):
    """The JAX kernel (ops/ed25519.py) — fused raw-bytes path.

    ``mesh="auto"`` shards the batch over all local devices via ``shard_map``
    (parallel/mesh.py) when more than one is attached; a single chip (or CPU)
    dispatches the plain bucketed kernel.  Pass an explicit
    ``jax.sharding.Mesh`` or ``None`` to override.
    """

    def __init__(self, mesh="auto", committee_keys=None) -> None:
        self._mesh = mesh
        # Known signer set -> device-resident key table: the pk rides as an
        # index (26 words/sig on the wire instead of 33), uploaded once.
        self._table = None
        if committee_keys:
            from .ops.ed25519 import KeyTable

            self._table = KeyTable(list(committee_keys))

    def _resolve_mesh(self):
        if self._mesh == "auto":
            import jax

            from .parallel.mesh import make_mesh

            # Clamp to the largest power-of-two prefix: the fused bucket
            # shapes (256/1024/4096) shard evenly only over power-of-two
            # meshes, and TPU slices are power-of-two sized anyway.
            n = len(jax.devices())
            pow2 = 1 << (n.bit_length() - 1)
            self._mesh = make_mesh(pow2) if pow2 > 1 else None
        return self._mesh

    def warmup(self) -> None:
        """Trace + compile (or load from the persistent cache) the smallest
        bucket kernel so the first real block batch is not stalled ~15-30 s
        behind JAX tracing.  Warms BOTH dispatch flavors: a single-unknown-key
        batch (groups trivially -> keyed-tile kernel) and, when a committee
        table is present, a one-sig-per-committee-key batch (grouping
        overflows the smallest bucket -> generic ladder fallback)."""
        dummy = bytes(32)
        self.verify_signatures([dummy], [dummy], [bytes(64)])
        if self._table is not None and len(self._table) > 1:
            pks = list(self._table._keys)
            self.verify_signatures(
                pks, [dummy] * len(pks), [bytes(64)] * len(pks)
            )

    def resolved_backend(self) -> str:
        """The live JAX platform ("cpu" when no accelerator is attached or
        the runtime degraded to the host) — what HELLO_OK advertises when
        this backend sits behind the verifier service."""
        import jax

        return str(jax.default_backend())

    def padded_batch(self, n: int) -> int:
        """Lanes dispatched for n signatures under the kernel's fixed bucket
        shapes (``ops.ed25519.iter_buckets`` is the single source of truth;
        imported lazily — by the time padding is worth reporting a dispatch
        has already paid the jax import)."""
        from .ops.ed25519 import iter_buckets

        return sum(bucket for _, _, bucket in iter_buckets(n))

    def verify_signatures_async(self, public_keys, digests, signatures):
        """True async dispatch: pack on the calling (host) thread, submit
        every bucket chunk through JAX's async dispatch, return the device
        handle.  ``result()`` pays the single combined fetch — so large
        catch-up batches stream bucket-sized sub-dispatches through the
        device while the caller packs the next batch."""
        mesh = self._resolve_mesh()
        # The fused sharded kernel requires 32-byte messages (block digests);
        # other lengths fall back to the single-device host-hash path so the
        # result never depends on the device count.
        if mesh is not None and all(len(d) == 32 for d in digests):
            if self._table is not None:
                from .parallel.mesh import dispatch_sharded_indexed

                return dispatch_sharded_indexed(
                    mesh, self._table, public_keys, digests, signatures
                )
            from .parallel.mesh import dispatch_sharded_fused

            return dispatch_sharded_fused(
                mesh, public_keys, digests, signatures
            )
        from .ops import ed25519

        if self._table is not None:
            return ed25519.dispatch_batch_table(
                self._table, public_keys, digests, signatures
            )
        return ed25519.dispatch_batch(public_keys, digests, signatures)

    def verify_signatures(self, public_keys, digests, signatures):
        return list(
            self.verify_signatures_async(
                public_keys, digests, signatures
            ).result()
        )


def _update_ema(current: float, sample: float, outlier_s: float) -> float:
    """EMA with outlier rejection, shared by the batching collector's window
    and the hybrid router's calibration: samples past ``outlier_s`` (one-time
    JAX compiles) never enter; the first sample seeds."""
    if sample >= outlier_s:
        return current
    return sample if current == 0.0 else 0.8 * current + 0.2 * sample


class HybridSignatureVerifier(SignatureVerifier):
    """Route each batch to the CPU oracle or the TPU backend by MEASURED
    cost (SURVEY §7 hard part #2: "CPU fallback for stragglers").

    The accelerator's cost model has TWO measured parameters, not one:

    * ``tpu_dispatch_s`` — the fixed per-dispatch cost (µs co-located,
      ~100 ms over a tunnel), seeded by a 1-signature probe after warmup;
    * ``tpu_per_sig_s`` — the marginal per-signature cost, learned from
      live TPU-routed dispatches (``max(0, (t - fixed) / n)``).

    A fixed-only model routed saturation batches to "accelerators" that are
    actually slower per signature than the oracle — on a host whose JAX
    backend degraded to CPU, a 256-batch "offload" cost 1.5 s where the
    oracle takes 32 ms, and light-load fleet latency collapsed to ~2 s
    (round-5 NODE_BENCH draft).  Routing per batch of size n:

    1. ``tpu_time(n) <= cpu_time(n)``       -> TPU (genuinely faster);
    2. ``cpu_time(n) > MAX_CPU_BUDGET_S``   -> TPU **iff**
       ``tpu_time(n) <= MAX_OFFLOAD_LATENCY_S`` — offloading frees the
       host core for the engine (worth paying bounded extra latency on an
       engine-bound fleet), but never to a backend whose turnaround would
       itself stall consensus;
    3. otherwise                            -> CPU.
    """

    DEFAULT_THRESHOLD = 32  # n-based routing until both sides are seeded
    MAX_CPU_BUDGET_S = 0.010  # max host time one CPU-routed batch may take
    # Offload-to-free-the-core is only sane when the accelerator turnaround
    # is itself consensus-compatible: a tunneled chip (~150 ms) qualifies, a
    # degraded jax-CPU backend (seconds per dispatch) must not.
    MAX_OFFLOAD_LATENCY_S = 0.5
    EMA_OUTLIER_S = 5.0  # ignore one-time compile stalls
    # Circuit breaker over the accelerator route: a dead backend (verifier
    # service restart, tunnel outage) degrades to the CPU oracle instead of
    # crashing the dispatch thread; re-probes use jittered exponential
    # backoff so a fleet that lost ONE shared service never re-probes it in
    # lockstep.  Only transport/timeout failures trip it — a
    # VerificationError-shaped rejection is a verdict, not an outage.
    BREAKER_EXCEPTIONS = (ConnectionError, TimeoutError, OSError)
    BREAKER_BASE_BACKOFF_S = 1.0
    BREAKER_MAX_BACKOFF_S = 30.0
    # Advertised backends with no accelerator behind them (HELLO_OK suffix,
    # verifier_service.py): a service running on one of these has nothing to
    # offload TO — routing pins to the in-process oracle and the socket goes
    # silent (zero frames per batch) until a re-HELLO probe sees an upgrade.
    CPU_ONLY_BACKENDS = frozenset({"cpu"})

    def __init__(
        self,
        tpu: Optional[SignatureVerifier] = None,
        cpu: Optional[SignatureVerifier] = None,
        threshold: Optional[int] = None,
        metrics=None,
    ) -> None:
        self.tpu = tpu or TpuSignatureVerifier()
        self.cpu = cpu or CpuSignatureVerifier()
        self._fixed_threshold = threshold
        self.metrics = metrics
        self.cpu_per_sig_s = 0.0
        self.tpu_dispatch_s = 0.0  # fixed component
        self.tpu_per_sig_s = 0.0  # marginal component
        # EMA read-modify-writes happen from executor threads; serialize them.
        self._ema_lock = threading.Lock()
        # Breaker state shares _ema_lock (same writer threads, same cadence).
        # backoff == 0.0 means closed; while open, dispatches fall back to
        # the CPU oracle until the probe deadline passes.  _breaker_probing
        # keeps the probe EXCLUSIVE even when it outlives the backoff
        # interval (a hung service blocks the probe thread for the whole
        # dispatch timeout; new windows must not admit more victims).
        self._breaker_backoff_s = 0.0
        self._breaker_open_until = 0.0
        self._breaker_probing = False
        # Trip generation: with several dispatches in flight, a PRE-outage
        # success can surface at fetch AFTER a newer failure tripped the
        # circuit — it must not re-close it (see result()).
        self._breaker_gen = 0
        self._breaker_rng = random.Random(0x0B7EA6E5)
        self._breaker_clock = time.monotonic  # injectable for tests
        # Backend pin (shares _ema_lock and the breaker's probe-exclusivity
        # flag): while the remote side advertises a CPU-only backend, every
        # batch short-circuits to the in-process oracle and a low-frequency
        # re-HELLO probe (jittered exponential backoff, same schedule
        # constants as the breaker) watches for an accelerator upgrade.
        self._pinned_backend: Optional[str] = None
        self._pin_backoff_s = 0.0
        self._pin_next_probe_t = 0.0
        # Routing label of the dispatch that ran in THIS thread: the batching
        # collector reads it right after verify_signatures returns, in the
        # same executor thread, so thread-local storage is exactly the
        # lifetime needed — a concurrent flush routed the other way cannot
        # overwrite it (it writes its own thread's slot).
        self._tls = threading.local()

    @property
    def backend_label(self) -> str:
        return getattr(self._tls, "label", "hybrid")

    @property
    def dispatch_padded(self) -> Optional[int]:
        """Padded lane count of the dispatch that ran in THIS thread (same
        thread-local lifetime as ``backend_label``).  Recorded at dispatch
        time because re-deriving the route afterwards can disagree: the
        dispatch itself updates the EMA cost model, so near the routing
        crossover ``padded_batch`` would attribute the waste to the wrong
        route — exactly the drift regime this telemetry exists to debug."""
        return getattr(self._tls, "padded", None)

    def _tpu_time(self, n: int) -> float:
        return self.tpu_dispatch_s + n * self.tpu_per_sig_s

    def _route_to_tpu(self, n: int) -> bool:
        if self._fixed_threshold is not None:
            return n >= self._fixed_threshold
        if not (self.cpu_per_sig_s > 0.0 and self.tpu_dispatch_s > 0.0):
            return n >= self.DEFAULT_THRESHOLD
        cpu_t = n * self.cpu_per_sig_s
        tpu_t = self._tpu_time(n)
        if tpu_t <= cpu_t:
            return True
        return (
            cpu_t > self.MAX_CPU_BUDGET_S
            and tpu_t <= self.MAX_OFFLOAD_LATENCY_S
        )

    # threshold() sentinel: no batch size is currently routed to the
    # accelerator (degraded backend).
    NEVER = 1 << 32

    def threshold(self) -> int:
        """Smallest batch size currently routed to the accelerator
        (introspection/logging; routing itself is per-batch).  Closed form
        over the two linear cost models — routes agree with
        ``_route_to_tpu`` by construction."""
        import math

        if self._pinned_backend is not None:
            return self.NEVER  # CPU-only backend: nothing to offload to
        if self._fixed_threshold is not None:
            return self._fixed_threshold
        if not (self.cpu_per_sig_s > 0.0 and self.tpu_dispatch_s > 0.0):
            return self.DEFAULT_THRESHOLD
        best = self.NEVER
        # Rule 1: tpu genuinely faster from the speed crossover on.
        denom = self.cpu_per_sig_s - self.tpu_per_sig_s
        if denom > 0.0:
            best = max(1, math.ceil(self.tpu_dispatch_s / denom))
        # Rule 2: smallest over-budget batch, if the offload is sane there.
        n_budget = int(self.MAX_CPU_BUDGET_S / self.cpu_per_sig_s) + 1
        if self._tpu_time(n_budget) <= self.MAX_OFFLOAD_LATENCY_S:
            best = min(best, n_budget)
        return best

    # -- circuit breaker --

    @property
    def breaker_open(self) -> bool:
        return self._breaker_backoff_s > 0.0

    def _admit_accelerator(self) -> Tuple[bool, bool]:
        """(blocked, is_probe).  Blocked while the breaker holds the route
        closed.  Once the probe deadline passes, exactly ONE dispatch gets
        through as the probe — the ``_breaker_probing`` flag (not a pushed
        deadline) keeps it exclusive even when the probe outlives the
        backoff interval.  ``is_probe`` tells the admitted dispatch it OWNS
        that flag: only the owner may release it on a non-verdict exit
        (abandon, propagating non-breaker exception) — an unconditional
        clear could release a DIFFERENT in-flight probe's exclusivity."""
        with self._ema_lock:
            if self._breaker_backoff_s == 0.0:
                return False, False
            now = self._breaker_clock()
            if self._breaker_probing or now < self._breaker_open_until:
                return True, False
            self._breaker_probing = True
            return False, True

    def _trip_breaker(self, exc: BaseException,
                      owns_probe: bool = False) -> None:
        """Open (or widen) the circuit.  ``owns_probe`` mirrors the
        ``is_probe`` admission flag: only the dispatch that OWNS the
        exclusive probe slot may release it on failure — a pre-outage
        straggler failing at fetch while a probe hangs must not readmit
        victims behind the hung probe's back."""
        now = self._breaker_clock()
        with self._ema_lock:
            self._breaker_gen += 1
            if owns_probe:
                self._breaker_probing = False
            prev = self._breaker_backoff_s
            backoff = (
                self.BREAKER_BASE_BACKOFF_S
                if prev == 0.0
                else min(prev * 2.0, self.BREAKER_MAX_BACKOFF_S)
            )
            self._breaker_backoff_s = backoff
            self._breaker_open_until = now + jittered_backoff(
                backoff, self._breaker_rng
            )
        log.warning(
            "accelerator verify path failed (%r): circuit open, degrading to "
            "the CPU oracle; next probe in ~%.1f s", exc, backoff,
        )

    def _close_breaker(self, expected_gen: Optional[int] = None) -> bool:
        """Close the circuit.  With ``expected_gen``, close only while the
        breaker generation still matches — compared under the lock, so a
        success surfacing at fetch can never erase a trip that raced it
        between the caller's generation read and the close."""
        with self._ema_lock:
            if (expected_gen is not None
                    and expected_gen != self._breaker_gen):
                return False
            was_open = self._breaker_backoff_s > 0.0
            self._breaker_backoff_s = 0.0
            self._breaker_probing = False
        if was_open:
            log.info("accelerator verify path recovered: circuit closed")
        return True

    def _clear_probe(self) -> None:
        """Release probe exclusivity when the dispatch neither succeeded nor
        counted as an outage (a propagating non-breaker exception) — a stuck
        flag would otherwise hold the breaker open forever."""
        with self._ema_lock:
            self._breaker_probing = False

    # -- backend pin (short-circuit routing) --

    @property
    def pinned_backend(self) -> Optional[str]:
        """The CPU-only backend routing is currently pinned against, or
        None when offload is open (introspection/tests)."""
        return self._pinned_backend

    def _sync_pin_with_advertisement(self) -> None:
        """Cheap per-batch attr read: a mid-run reconnect (service restart)
        can change the remote client's advertised backend between probes —
        a CPU-only advertisement pins routing the moment any thread sees
        it, not a probe interval later."""
        adv = getattr(self.tpu, "advertised_backend", None)
        if adv in self.CPU_ONLY_BACKENDS and self._pinned_backend is None:
            self._pin_routing(adv)

    def _pin_routing(self, backend: str) -> None:
        now = self._breaker_clock()
        with self._ema_lock:
            if self._pinned_backend is not None:
                return
            self._pinned_backend = backend
            self._pin_backoff_s = self.BREAKER_BASE_BACKOFF_S
            self._pin_next_probe_t = now + jittered_backoff(
                self._pin_backoff_s, self._breaker_rng
            )
        log.info(
            "verifier backend %r has no accelerator: routing pinned to the "
            "in-process oracle (re-HELLO upgrade probe in ~%.1f s)",
            backend, self.BREAKER_BASE_BACKOFF_S,
        )

    def _admit_pin_probe(self) -> bool:
        """At most one re-HELLO upgrade probe at a time, past the backoff
        deadline — the ``_breaker_probing`` flag is shared with
        ``_admit_accelerator`` so a hung HELLO admits no further probes and
        never races a breaker probe for the same exclusivity."""
        with self._ema_lock:
            if self._pinned_backend is None:
                return False
            now = self._breaker_clock()
            if self._breaker_probing or now < self._pin_next_probe_t:
                return False
            self._breaker_probing = True
            return True

    def _finish_pin_probe(self, backend: Optional[str], calibration,
                          probed: bool = False) -> None:
        """Probe outcome.  With ``probed`` (the re-HELLO round-trip actually
        completed): any answer that is not a CPU-only advertisement unpins —
        including NO advertisement (a pre-r6 service replaced the one that
        pinned us; its platform is unknown, and unknown must never stay
        pinned — the same conservative default that refuses to pin in the
        first place), and a fresh calibration reseeds the cost model.
        Without ``probed`` (unreachable service, no rehello support, or an
        abandoned probe) the pin stands and the backoff doubles, decaying
        the steady-state probe cost to one HELLO per
        ``BREAKER_MAX_BACKOFF_S``."""
        now = self._breaker_clock()
        upgraded = probed and backend not in self.CPU_ONLY_BACKENDS
        with self._ema_lock:
            self._breaker_probing = False
            if upgraded:
                self._pinned_backend = None
                self._pin_backoff_s = 0.0
                if calibration is not None:
                    self.tpu_dispatch_s, self.tpu_per_sig_s = calibration
            else:
                self._pin_backoff_s = min(
                    self._pin_backoff_s * 2.0, self.BREAKER_MAX_BACKOFF_S
                )
                self._pin_next_probe_t = now + jittered_backoff(
                    self._pin_backoff_s, self._breaker_rng
                )
        if upgraded:
            log.info(
                "verifier service re-advertised backend %r: offload "
                "re-opened", backend,
            )

    def _reprobe_pin_and_verify(self, public_keys, digests, signatures, n):
        """Fetch-stage body of the probe-carrying batch: ONE re-HELLO round
        trip (never a verify frame), then the batch verifies on the oracle
        exactly as its window-mates did.  A service outage here is not an
        outage of the route in use — the pin already avoids the socket — so
        it only pushes the next probe out, never trips the breaker."""
        backend = calibration = None
        probed = False
        try:
            rehello = getattr(self.tpu, "rehello", None)
            if rehello is not None:
                backend, calibration = rehello()
                probed = True
        except VerifierProtocolError as exc:
            log.warning(
                "pin re-probe HELLO rejected (%r): staying on the oracle",
                exc,
            )
        except self.BREAKER_EXCEPTIONS as exc:
            log.debug(
                "pin re-probe HELLO failed (%r): staying on the oracle", exc
            )
        finally:
            self._finish_pin_probe(backend, calibration, probed=probed)
        return self._verify_cpu(public_keys, digests, signatures, n)

    def warmup(self) -> None:
        from . import crypto

        signer = crypto.Signer.dummy()
        digest = crypto.blake2b_256(b"hybrid-warmup")
        sig = signer.sign(digest)
        pk = signer.public_key.bytes
        # Accelerator cost model: prefer the BACKEND's own calibration (the
        # verifier service measures its warmed dispatch once and shares it
        # with every client over HELLO_OK) — N co-located validators each
        # probing a shared service would serialize N dispatches behind boot
        # contention.  A local backend without one gets the probe dispatch.
        # An unreachable backend (service not yet up, tunnel down) must not
        # kill the warmup thread: trip the breaker and boot on the oracle.
        provided = None
        try:
            self.tpu.warmup()  # trace/compile (or persistent-cache load)
            calibrate = getattr(self.tpu, "dispatch_calibration", None)
            provided = calibrate() if calibrate is not None else None
            if provided is None:
                started = time.monotonic()
                self.tpu.verify_signatures([pk], [digest], [sig])
                # Real-backend boot calibration only: sims construct oracle
                # verifiers (chaos.py), so these EMAs keep their
                # deterministic __init__ defaults in virtual time.
                provided = (time.monotonic() - started, 0.0)  # lint: ignore[sim-taint]
        except self.BREAKER_EXCEPTIONS as exc:
            if isinstance(exc, VerifierProtocolError):
                raise  # misconfiguration, not an outage: fail fast
            self._trip_breaker(exc)
        # The warmup HELLO told us what actually answers behind the socket:
        # a CPU-only backend pins routing before the first real batch, so
        # even boot traffic never pays the socket round-trip for nothing.
        self._sync_pin_with_advertisement()
        started = time.monotonic()
        reps = 32
        self.cpu.verify_signatures([pk] * reps, [digest] * reps, [sig] * reps)
        # Same boot-calibration exemption as the TPU probe above.
        cpu_probe = (time.monotonic() - started) / reps  # lint: ignore[sim-taint]
        # Warmup runs on a background thread while live dispatches may
        # already be updating the EMAs from executor threads — the
        # calibration writes must join the same lock or a concurrent RMW
        # that read the pre-warmup value could land after and discard them.
        with self._ema_lock:
            if provided is not None:
                self.tpu_dispatch_s, self.tpu_per_sig_s = provided
            self.cpu_per_sig_s = cpu_probe
        log.info(
            "hybrid verifier calibrated: tpu %.1f ms fixed + %.1f µs/sig, "
            "cpu %.0f µs/sig -> tpu from batch %d",
            1e3 * self.tpu_dispatch_s,
            1e6 * self.tpu_per_sig_s,
            1e6 * self.cpu_per_sig_s,
            self.threshold(),
        )

    def _note_route(self, route: str, estimated_s: float, actual_s: float) -> None:
        """Router decision telemetry: which way the batch went, and how far
        the cost model's estimate was from the measured dispatch (a drifting
        estimate is exactly the misroute precursor round 5 debugged blind)."""
        if self.metrics is None:
            return
        self.metrics.verify_route_total.labels(route).inc()
        if estimated_s > 0.0:
            self.metrics.verify_route_estimate_error_s.observe(
                abs(actual_s - estimated_s)
            )

    def verify_signatures_async(self, public_keys, digests, signatures):
        """Staged routing: a TPU-routed batch submits through the backend's
        own async queue (JAX dispatch, the service socket) and returns an
        in-flight handle; a breaker failure AT FETCH degrades that one batch
        to the oracle inside ``result()`` — zero lost futures.  CPU-routed
        (and breaker-blocked) batches defer the oracle to the fetch stage
        unchanged."""
        n = len(signatures)
        if n == 0:
            return CompletedDispatch([])
        self._sync_pin_with_advertisement()
        if self._pinned_backend is not None:
            # Short-circuit: the service advertised a CPU-only backend, so
            # the batch completes wholly in-process — zero socket frames,
            # zero collector serialization toward the wire.  At most one
            # batch per backoff interval carries the re-HELLO upgrade probe
            # into its fetch stage (a HELLO frame, never a verify).
            if self.metrics is not None:
                self.metrics.verify_shortcircuit_total.labels(
                    "backend-cpu"
                ).inc()
            if self._admit_pin_probe():
                return _PinProbeDispatch(
                    self, public_keys, digests, signatures, n
                )
            return DeferredDispatch(
                self._verify_cpu, public_keys, digests, signatures, n
            )
        degraded = False
        breaker_blocked = False
        if self._route_to_tpu(n):
            blocked, is_probe = self._admit_accelerator()
            if blocked:
                # Circuit open: the route is held closed and the batch
                # never touches the socket (unlike a mid-dispatch failure
                # below, which may have sent frames before raising).
                degraded = True
                breaker_blocked = True
            else:
                # Captured BEFORE the submit: a trip racing the submission
                # means this dispatch's eventual success is ambiguous
                # evidence and must not close the circuit.
                gen = self._breaker_gen
                try:
                    handle = self.tpu.verify_signatures_async(
                        public_keys, digests, signatures
                    )
                except self.BREAKER_EXCEPTIONS as exc:
                    if isinstance(exc, VerifierProtocolError):
                        if is_probe:
                            self._clear_probe()
                        raise
                    self._trip_breaker(exc, owns_probe=is_probe)
                    degraded = True
                except BaseException:
                    if is_probe:
                        self._clear_probe()
                    raise
                else:
                    return _HybridTpuDispatch(
                        self, handle, public_keys, digests, signatures, n,
                        is_probe, gen,
                    )
        if self.metrics is not None:
            if degraded:
                self.metrics.verifier_fallback_total.inc()
                if breaker_blocked:
                    self.metrics.verify_shortcircuit_total.labels(
                        "breaker"
                    ).inc()
            else:
                # The cost-model router decided against offloading: the
                # batch must never touch the socket — and doesn't (the
                # oracle runs in-process at the fetch stage).
                self.metrics.verify_shortcircuit_total.labels("router").inc()
        return DeferredDispatch(
            self._verify_cpu, public_keys, digests, signatures, n
        )

    def verify_signatures(self, public_keys, digests, signatures):
        """One routing/breaker implementation for both call shapes: the
        sync path is the async path fetched immediately (submit-time breaker
        handling in ``verify_signatures_async``, fetch-time in
        ``_HybridTpuDispatch.result`` — keeping a second copy in lockstep is
        how probe-ownership bugs breed)."""
        return self.verify_signatures_async(
            public_keys, digests, signatures
        ).result()

    def _verify_cpu(self, public_keys, digests, signatures, n):
        estimated = n * self.cpu_per_sig_s
        started = time.monotonic()
        out = self.cpu.verify_signatures(public_keys, digests, signatures)
        elapsed = time.monotonic() - started
        sample = elapsed / n
        with self._ema_lock:
            self.cpu_per_sig_s = _update_ema(
                self.cpu_per_sig_s, sample, self.EMA_OUTLIER_S
            )
        self._note_route("cpu", estimated, elapsed)
        self._tls.label = "hybrid-cpu"
        self._tls.padded = n  # host oracle: no padding lanes
        return out

    def _absorb_tpu_sample(self, sample: float, n: int) -> None:
        """Fold one measured TPU dispatch into the two-parameter cost model.

        The residual against the CURRENT model is split 50/50 between the
        fixed and marginal components (ADVICE r5): attributing the FULL
        residual to both in the same update — each computed against the
        other's pre-update value — let one slow dispatch inflate the summed
        model by ~double the residual and wrongly veto the rule-2 saturation
        offload until the EMAs decayed.  With the split, the summed model
        moves by exactly the residual; observations at varied batch sizes
        still disambiguate fixed from marginal over time, and the fixed
        component can still rise (a tunnel settling slower than its warmup
        probe is not misattributed wholesale to per-signature cost).
        """
        if sample >= self.EMA_OUTLIER_S:
            return
        with self._ema_lock:
            residual = sample - (self.tpu_dispatch_s + n * self.tpu_per_sig_s)
            implied_fixed = max(0.0, self.tpu_dispatch_s + 0.5 * residual)
            implied_marginal = max(
                0.0, self.tpu_per_sig_s + 0.5 * residual / n
            )
            self.tpu_dispatch_s = _update_ema(
                self.tpu_dispatch_s, implied_fixed, self.EMA_OUTLIER_S
            )
            self.tpu_per_sig_s = _update_ema(
                self.tpu_per_sig_s, implied_marginal, self.EMA_OUTLIER_S
            )

class _PinProbeDispatch:
    """The pinned route's probe-carrying batch: ``result()`` runs the
    re-HELLO + oracle verify on the fetch stage's executor thread.  The
    handle OWNS the shared probe-exclusivity flag from admission, so a
    flush cancelled between submit and fetch must release it via
    ``abandon()`` — a bare DeferredDispatch here would strand the flag
    forever (no further pin probes, and the breaker's own probes blocked),
    the exact leak PR 4's abandon protocol exists to prevent."""

    __slots__ = ("_hybrid", "_args")

    def __init__(self, hybrid, public_keys, digests, signatures, n) -> None:
        self._hybrid = hybrid
        self._args = (public_keys, digests, signatures, n)

    def result(self) -> List[bool]:
        return self._hybrid._reprobe_pin_and_verify(*self._args)

    def abandon(self) -> None:
        """Released without fetching: not a completed probe (``probed``
        stays False), so the pin stands and only the backoff advances."""
        self._hybrid._finish_pin_probe(None, None)


class _HybridTpuDispatch:
    """An in-flight TPU-routed batch of the hybrid verifier.

    ``result()`` runs on the fetch stage's executor thread, so the breaker
    bookkeeping, cost-model update, and the thread-local backend label all
    land exactly where the sync path put them — the collector reads
    ``backend_label``/``dispatch_padded`` right after ``result()`` in the
    same thread.  A transport/timeout failure surfacing at fetch trips the
    breaker and verifies THIS batch on the oracle: a backend dying
    mid-pipeline loses zero futures."""

    __slots__ = ("_hybrid", "_handle", "_args", "_n", "_estimated",
                 "_padded", "_started", "_is_probe", "_gen")

    def __init__(self, hybrid, handle, public_keys, digests, signatures,
                 n, is_probe: bool = False, gen: int = 0) -> None:
        self._hybrid = hybrid
        self._handle = handle
        self._args = (public_keys, digests, signatures)
        self._n = n
        self._estimated = hybrid._tpu_time(n)
        self._padded = hybrid.tpu.padded_batch(n)
        self._started = time.monotonic()
        self._is_probe = is_probe
        self._gen = gen

    def result(self) -> List[bool]:
        h = self._hybrid
        try:
            out = self._handle.result()
        except h.BREAKER_EXCEPTIONS as exc:
            if isinstance(exc, VerifierProtocolError):
                if self._is_probe:
                    h._clear_probe()
                raise
            h._trip_breaker(exc, owns_probe=self._is_probe)
            if h.metrics is not None:
                h.metrics.verifier_fallback_total.inc()
            return h._verify_cpu(*self._args, self._n)
        except BaseException:
            if self._is_probe:
                h._clear_probe()
            raise
        # Submit-to-fetch wall time: under pipelining this is the batch's
        # actual turnaround (what the router's model predicts), queueing
        # included; the EMA's outlier gate still drops compile stalls.
        sample = time.monotonic() - self._started
        if not h._close_breaker(expected_gen=self._gen) and self._is_probe:
            # A newer trip owns the circuit: this probe's success is stale
            # evidence — its only remaining obligation is releasing the
            # exclusive probe slot it still holds.
            h._clear_probe()
        h._note_route("tpu", self._estimated, sample)
        h._absorb_tpu_sample(sample, self._n)
        h._tls.label = "hybrid-tpu"
        h._tls.padded = self._padded
        return list(out)

    def abandon(self) -> None:
        """Release per-dispatch state without fetching (the flush was
        cancelled): if THIS dispatch owns the breaker's exclusive probe
        flag it must not stay stuck — only ``result()`` would otherwise
        clear it — and the inner handle may hold its own releasable state.
        A non-probe dispatch touches nothing (clearing unconditionally
        could release a concurrent probe's exclusivity)."""
        if self._is_probe:
            self._hybrid._clear_probe()
        inner = getattr(self._handle, "abandon", None)
        if inner is not None:
            inner()


async def aggregate_verify(
    blocks: Sequence[StatementBlock],
    committee: Committee,
    direct_verify,
    count=None,
    prior_endorsers=None,
    defer_unresolved: bool = False,
) -> List[Optional[bool]]:
    """The threshold-aggregate acceptance rule over one batch of blocks
    (shared by the frame-level ``ThresholdAggregateVerifier`` and the
    collector-level aggregate mode of ``BatchedSignatureVerifier``).

    ``direct_verify(sub_blocks) -> List[bool]`` is the inner signature check
    (awaitable); ``count(aggregated, direct)`` is an optional accounting
    callback.  ``prior_endorsers(ref) -> set[AuthorityIndex]`` optionally
    supplies authors of PREVIOUSLY ACCEPTED blocks that include ``ref``
    (every accepted block was itself signature-verified or quorum-endorsed,
    so its endorsement carries inductively) — this is what makes the rule
    bite during catch-up, where peers' own-block streams run at different
    round offsets and a block's verified children usually arrived earlier
    via a faster stream.  See ``ThresholdAggregateVerifier`` and
    ``docs/aggregate-verification.md`` for the safety argument: acceptance
    chains are well-founded and terminate at directly verified signatures.

    Dispatch shape (the round-4 tpu-agg lesson, VERDICT weak #3): one
    frontier dispatch, then the descending-round cascade accepts interiors
    off those results with NO further dispatch.  Blocks whose endorsement
    fell short once non-accepted endorsers were excluded ("unresolved"):

    * ``defer_unresolved=False`` (frame-level wrapper): a second direct
      dispatch resolves them here.  Correct, but SERIALIZED behind the
      frontier dispatch — on a remote accelerator (~100 ms/round-trip) the
      second trip halves flush cadence exactly where aggregation was meant
      to help.
    * ``defer_unresolved=True`` (the batching collector's deployed mode):
      their slots return ``None`` and the collector folds them into the
      NEXT flush window, where they are either endorsed by newly arrived
      children or dispatched as ordinary frontier — every flush pays
      exactly one round-trip, same as the plain verifier.  The collector
      force-dispatches a block on its SECOND deferral: otherwise a
      Byzantine author could park a forged block in "maybe" forever by
      minting fresh structure-valid endorsers each window (liveness, not
      safety — acceptance still requires a quorum of ACCEPTED endorsers).
    """
    n = len(blocks)
    if count is None:
        count = lambda aggregated, direct: None  # noqa: E731
    if n == 0:
        return []
    if n == 1 and prior_endorsers is None:
        count(0, n)
        return list(await direct_verify(list(blocks)))
    index_of = {b.reference: i for i, b in enumerate(blocks)}
    # endorsers[i] = indexes of in-batch blocks that include block i.
    endorsers: List[List[int]] = [[] for _ in range(n)]
    for j, b in enumerate(blocks):
        for ref in b.includes:
            i = index_of.get(ref)
            if i is not None:
                endorsers[i].append(j)

    quorum = committee.quorum_threshold()

    def endorsement_stake(i, accepted_flags) -> int:
        seen = (
            set(prior_endorsers(blocks[i].reference))
            if prior_endorsers is not None
            else set()
        )
        stake = sum(committee.get_stake(a) for a in seen)
        for j in endorsers[i]:
            if accepted_flags[j] is not True:
                continue
            author = blocks[j].author()
            if author in seen:
                continue
            seen.add(author)
            stake += committee.get_stake(author)
        return stake

    # Frontier = blocks that cannot possibly reach quorum endorsement
    # even if every endorser were accepted.
    maybe: List[Optional[bool]] = [None] * n
    all_true = [True] * n
    frontier = [i for i in range(n) if endorsement_stake(i, all_true) < quorum]
    frontier_set = set(frontier)
    # Descending claimed-round order: honest endorsers sit in strictly
    # higher rounds than the blocks they include, so an endorser's fate is
    # known by the time its endorsee is evaluated.  Rounds are attacker-
    # claimed, but a mis-ordered (forged) endorser merely evaluates as
    # not-yet-accepted (False) — never as accepted (see
    # docs/aggregate-verification.md, well-foundedness).
    order = sorted(
        (i for i in range(n) if i not in frontier_set),
        key=lambda i: -blocks[i].round(),
    )
    direct = await direct_verify([blocks[i] for i in frontier])
    for i, ok in zip(frontier, direct):
        maybe[i] = bool(ok)
    count(0, len(frontier))
    for i in order:
        maybe[i] = endorsement_stake(i, maybe) >= quorum
        if maybe[i]:
            count(1, 0)
    unresolved = [i for i in order if maybe[i] is False]
    if unresolved:
        if defer_unresolved:
            # The caller folds these into its next flush window — no second
            # serialized dispatch on this one.
            for i in unresolved:
                maybe[i] = None
            return list(maybe)
        # Endorsement fell short once non-accepted endorsers were excluded:
        # these still deserve a direct check rather than a blanket reject.
        second = await direct_verify([blocks[i] for i in unresolved])
        count(0, len(unresolved))
        for i, ok in zip(unresolved, second):
            maybe[i] = bool(ok)
    return [bool(v) for v in maybe]


class ThresholdAggregateVerifier(BlockVerifier):
    """Threshold-aggregate verification (BASELINE config #5's technique).

    Exploits the digest/signature layering (crypto.rs:77-84): a block's
    reference digest is computed over its full serialization INCLUDING the
    signature, and honest validators only include blocks they verified.  So
    when blocks signed by a quorum (2f+1 stake, hence >= f+1 honest) of
    distinct authorities reference block B, B's authenticity is already
    certified by the quorum — its signature need not be re-checked here.

    Applied at batch granularity on the receive path: within one incoming
    batch (catch-up and sync deliver hundreds of blocks spanning many
    rounds), only the non-endorsed FRONTIER is signature-verified through
    the inner verifier (one TPU dispatch); interior blocks are accepted when
    a quorum of distinct accepted in-batch endorsers references them.
    Acceptance is evaluated in descending-round order, so every acceptance
    chain terminates at directly verified frontier signatures — a forged
    interior block needs 2f+1 distinct accepted endorsers, which exceeds the
    fault model.

    Blocks that do not reach quorum endorsement (including every singleton
    steady-state delivery) go through the inner verifier unchanged.
    """

    def __init__(self, committee: Committee, inner: BlockVerifier,
                 metrics=None) -> None:
        self.committee = committee
        self.inner = inner
        self.metrics = metrics
        # Plain counters for tests; scrapeable via verified_signatures_total
        # {backend="aggregate"} when metrics are wired.
        self.aggregated_total = 0
        self.direct_total = 0

    def _count(self, aggregated: int, direct: int) -> None:
        self.aggregated_total += aggregated
        self.direct_total += direct
        if self.metrics is not None:
            if aggregated:
                self.metrics.verified_signatures_total.labels(
                    "aggregate", "skipped"
                ).inc(aggregated)
            if direct:
                self.metrics.verified_signatures_total.labels(
                    "aggregate", "direct"
                ).inc(direct)

    async def verify(self, block: StatementBlock) -> None:
        await self.inner.verify(block)

    async def verify_blocks(self, blocks: Sequence[StatementBlock]) -> List[bool]:
        return await aggregate_verify(
            blocks, self.committee, self.inner.verify_blocks, self._count
        )

    def note_committee(self, committee: Committee) -> None:
        """Quorum endorsement is stake-weighted: follow the epoch's stakes."""
        self.committee = committee
        note = getattr(self.inner, "note_committee", None)
        if note is not None:
            note(committee)


def _observe_orphan(fut) -> None:
    """Retrieve an orphaned executor future's exception so a backend crash
    after the awaiting flush was cancelled is logged, not swallowed into an
    'exception was never retrieved' warning at shutdown."""
    if fut.cancelled():
        return
    exc = fut.exception()
    if exc is not None:
        log.warning("orphaned verify dispatch failed after cancel: %r", exc)


def _abandon_dispatch(fut) -> None:
    """Dispose a submitted-but-never-fetched dispatch handle.

    Handles that hold releasable state expose ``abandon()``; plain handles
    (completed/deferred/JAX device arrays) need nothing.  A submit that
    RAISED already cleaned up after itself (the hybrid clears its probe, the
    remote client discards its connection)."""
    if fut.cancelled() or fut.exception() is not None:
        return
    abandon = getattr(fut.result(), "abandon", None)
    if abandon is None:
        return
    try:
        abandon()
    except Exception:  # noqa: BLE001 - best-effort cleanup on shutdown
        log.exception("abandoning an in-flight verify dispatch failed")


class BatchedSignatureVerifier(BlockVerifier):
    """Deadline/size-triggered batching collector in front of a SignatureVerifier.

    Consensus wants low verification turnaround; the TPU wants large batches.
    Policy: a block's verification completes when either (a) ``max_batch``
    items have accumulated, or (b) the collection window elapsed since the
    first pending item — whichever comes first (SURVEY §7 hard part #2).
    The window is ``max_delay_s`` on a co-located device and widens to 20%
    of the observed dispatch latency (capped at ``MAX_ADAPTIVE_DELAY_S``)
    when the accelerator is remote — see ``_effective_delay_s``.

    Usable from any number of asyncio tasks (one per peer connection); the
    device dispatch runs in a worker thread so the event loop never blocks on
    the accelerator.
    """

    def __init__(
        self,
        committee: Committee,
        verifier: Optional[SignatureVerifier] = None,
        max_batch: int = 256,
        max_delay_s: float = 0.005,
        metrics=None,
        aggregate: bool = False,
        pipeline_depth: Optional[int] = None,
    ) -> None:
        self.committee = committee
        self.verifier = verifier or TpuSignatureVerifier()
        self.max_batch = max_batch
        self.max_delay_s = max_delay_s
        self.metrics = metrics
        # Staged dispatch window: several flushes may be in flight at once
        # (pack N+1 while N computes and N-1's results ride back), bounded
        # so a flooding peer cannot queue unbounded device work.  Depth
        # adapts to the router's measured fixed dispatch cost unless pinned.
        self.pipeline = VerifyPipeline(
            depth=pipeline_depth,
            metrics=metrics,
            fixed_cost_fn=self._pipeline_fixed_cost,
        )
        # Collector-level threshold aggregation (BASELINE #5's technique at
        # the place it actually bites): one flush window pools blocks from
        # EVERY peer connection, so the batch spans authors — exactly what
        # quorum endorsement needs.  (A frame-level wrapper never sees that:
        # the push disseminator's frames carry a single peer's own blocks,
        # whose one author can never reach 2f+1 endorsement stake.)  Interior
        # quorum-endorsed blocks skip the signature dispatch; only the
        # frontier pays.
        self.aggregate = aggregate
        self.aggregated_total = 0
        self.direct_total = 0
        # Cross-flush endorsement index: ref -> authors of ACCEPTED blocks
        # that include it.  Catch-up streams from different peers run at
        # different round offsets, so a backlog block's quorum of verified
        # children has usually been accepted in EARLIER flushes — in-batch
        # endorsement alone almost never fires there.  Strictly size-bounded
        # with insertion-order (FIFO) eviction: rounds CLAIMED by blocks are
        # attacker-controlled (a Byzantine author can sign structure-valid
        # blocks at arbitrary rounds over fabricated include refs), so
        # neither the prune window nor residency may key on them.
        self._endorsements: dict = {}
        # id(future) of entries deferred once (aggregate mode): the next
        # unresolved verdict force-dispatches instead of deferring again.
        self._deferred: set = set()
        self._pending: List[Tuple[StatementBlock, asyncio.Future]] = []
        self._lock = threading.Lock()
        self._flush_task: Optional[asyncio.TimerHandle] = None
        # EMA of observed dispatch latency: when the accelerator is far away
        # (tunneled/remote chip, ~100 ms+ per dispatch), a 5 ms collection
        # window dispatches tiny batches back-to-back and the queue of
        # round-trips becomes the latency — waiting a fraction of the
        # measured latency instead coalesces them at a bounded cost on a
        # latency already dominated by the round-trip.  The window is clamped
        # to MAX_ADAPTIVE_DELAY_S (a compile stall or compute-heavy batch
        # must never push consensus turnaround past ~0.1 s), and dispatches
        # slower than EMA_OUTLIER_S (one-time JAX compiles) are not fed into
        # the EMA at all.
        self._dispatch_ema_s = 0.0
        # Arrival-rate EMA (loop-clocked, so it reads VIRTUAL time under the
        # deterministic simulator and seeded sims stay byte-identical): the
        # collection window only pays off when more arrivals are coming.
        # At low load the window shrinks toward the floor instead of taxing
        # every lone block with the full batch window — see
        # ``_effective_delay_s``.
        self._arrival_gap_ema_s = 0.0
        self._last_arrival_t: Optional[float] = None

    MAX_ADAPTIVE_DELAY_S = 0.1
    MIN_ADAPTIVE_DELAY_S = 0.0005
    EMA_OUTLIER_S = 5.0
    # Inter-arrival gaps are clamped here before entering the EMA: an idle
    # stretch means "low rate" (signal, fed in at the cap), not an outlier
    # to discard — but it must not drag the EMA so far that a resuming
    # burst needs minutes of samples to recover the window.
    ARRIVAL_GAP_CAP_S = 1.0

    def note_committee(self, committee: Committee) -> None:
        """Epoch switch (reconfig.py): rebind the stake table.  Key tables
        (TpuSignatureVerifier's KeyTable) are indexed by the stable registry
        and need no rebuild; only the quorum-endorsement stake math and
        per-author key lookups follow the new committee object."""
        self.committee = committee

    def _pipeline_fixed_cost(self) -> float:
        """Fixed dispatch cost estimate for the adaptive pipeline depth: the
        hybrid router's measured fixed component when available, else the
        collector's own dispatch-latency EMA (reads are unlocked snapshots —
        depth adaptation tolerates a stale value)."""
        fixed = getattr(self.verifier, "tpu_dispatch_s", 0.0)
        return fixed if fixed > 0.0 else self._dispatch_ema_s

    def _effective_delay_s(self) -> float:
        """Collection window, adaptive in BOTH directions around the
        ``max_delay_s`` default:

        * expensive dispatches (remote accelerator, ~100 ms round-trips)
          widen it to 20% of the dispatch-latency EMA (capped) — coalescing
          is nearly free on a latency already dominated by the round-trip;
        * cheap dispatches (the hybrid's CPU route at light load, µs-ms)
          SHRINK it toward the dispatch cost — the window exists to amortize
          an expensive dispatch, and holding blocks 5 ms to amortize a
          0.5 ms verify is pure added latency (round-4 weak #5: hybrid
          light-load latency trailed cpu by exactly this window).

        Saturation is unaffected either way: ``max_batch`` arrivals flush
        immediately without waiting for any timer.

        One continuous curve covers both: 20% of the EMA, clamped to
        [MIN, MAX]; ``max_delay_s`` is the pre-calibration default (no
        dispatch measured yet).  Tunneled chip (~100 ms dispatch) -> 20 ms
        window; saturated CPU batch (~30 ms) -> 6 ms; light-load CPU route
        (~0.5 ms) -> the 0.5 ms floor.

        On top of that dispatch-cost CEILING, the window is arrival-rate-
        adaptive: waiting is only worth it when more blocks are coming.
        With ``ceiling / gap_ema`` expected further arrivals inside the
        window, a rate that would deliver fewer than ~2 scales the wait
        down linearly (to the floor) — a lone steady-state block flushes
        almost immediately instead of paying the full batch window, while
        dense arrivals (gap << window) and same-tick frame bursts keep the
        full window and batch exactly as before.  Saturation is unaffected
        either way: ``max_batch`` arrivals flush without any timer.
        """
        ema = self._dispatch_ema_s
        if ema == 0.0:
            ceiling = self.max_delay_s
        else:
            ceiling = max(
                self.MIN_ADAPTIVE_DELAY_S,
                min(0.2 * ema, self.MAX_ADAPTIVE_DELAY_S),
            )
        gap = self._arrival_gap_ema_s
        if gap <= 0.0:
            return ceiling
        expected = ceiling / gap  # further arrivals inside a full window
        if expected >= 2.0:
            return ceiling
        return max(self.MIN_ADAPTIVE_DELAY_S, ceiling * expected / 2.0)

    def _schedule_flush(self, loop) -> None:  # lint: holds[_lock]
        """Arm the window timer (caller holds ``self._lock``) and publish
        the chosen window — the adaptive curve is otherwise invisible when
        a misroute needs debugging."""
        delay = self._effective_delay_s()
        if self.metrics is not None:
            self.metrics.verify_collector_window_seconds.set(delay)
        self._flush_task = loop.call_later(
            delay, lambda: spawn_logged(self._flush(), log, name="verify-flush")
        )

    async def verify(self, block: StatementBlock) -> None:
        loop = asyncio.get_running_loop()
        future: asyncio.Future = loop.create_future()
        window = None
        # Loop clock, not the wall: virtual under the simulator, so the
        # adaptive window cannot make a seeded sim's flush schedule depend
        # on host weather.
        now = loop.time()
        with self._lock:
            last = self._last_arrival_t
            self._last_arrival_t = now
            if last is not None:
                gap = min(now - last, self.ARRIVAL_GAP_CAP_S)
                # Same-tick arrivals (gather bursts, one frame's blocks)
                # sample as 0.0 — pulling the EMA toward "dense", which is
                # exactly what they are; a zero first sample leaves the EMA
                # unseeded (full window) rather than pinning it there.
                self._arrival_gap_ema_s = (
                    gap
                    if self._arrival_gap_ema_s == 0.0
                    else 0.8 * self._arrival_gap_ema_s + 0.2 * gap
                )
            self._pending.append((block, future))
            if len(self._pending) >= self.max_batch:
                # Take the full window NOW (max_batch stays the dispatch
                # bound) and open a fresh one immediately.
                window = self._pending
                self._pending = []
                if self._flush_task is not None:
                    self._flush_task.cancel()
                    self._flush_task = None
            elif self._flush_task is None:
                self._schedule_flush(loop)
        if window is not None:
            # Flush as its own task instead of awaiting it: the PRIOR
            # window's dispatch may still be in flight, and the staged
            # pipeline (bounded depth) is what lets this window's pack
            # overlap it.  The spawned task observes/attributes its own
            # failures; this caller still awaits its block's future below.
            spawn_logged(self._flush(window), log, name="verify-flush")
        ok = await future
        if not ok:
            raise VerificationError(
                f"signature verification failed for {block.reference!r}"
            )

    def _submit_dispatch(self, pks, digests, sigs):
        """Device stage (executor thread): pack-to-wire + non-blocking
        submission through the backend's async seam.  Returns the in-flight
        handle; for host backends without a device queue the handle defers
        the work to the fetch stage."""
        timer = (
            self.metrics.utilization_timer("verify:dispatch")
            if self.metrics is not None
            else contextlib.nullcontext()
        )
        with timer:
            submit = getattr(self.verifier, "verify_signatures_async", None)
            if submit is None:
                # Duck-typed backend predating the async seam: defer the
                # sync path to the fetch stage.
                return DeferredDispatch(
                    self.verifier.verify_signatures, pks, digests, sigs
                )
            return submit(pks, digests, sigs)

    def _dispatch_and_fetch(self, pks, digests, sigs):
        """Single-hop dispatch (simulation path): submit + fetch in one
        executor call — the pre-pipeline per-dispatch shape."""
        return self._fetch_dispatch(
            self._submit_dispatch(pks, digests, sigs), len(sigs)
        )

    def _fetch_dispatch(self, handle, n):
        """Fetch stage (executor thread): block until the verdicts are
        ready.  The backend label AND the padded lane count must be read in
        THIS thread, right after ``result()`` — the hybrid verifier records
        them thread-locally at fetch, so reading after the await would race
        with concurrent flushes that routed the other way."""
        timer = (
            self.metrics.utilization_timer("verify:dispatch")
            if self.metrics is not None
            else contextlib.nullcontext()
        )
        with timer:
            out = handle.result()
        label = getattr(
            self.verifier, "backend_label", type(self.verifier).__name__
        )
        padded = getattr(self.verifier, "dispatch_padded", None)
        if padded is None:
            padder = getattr(self.verifier, "padded_batch", None)
            padded = padder(n) if padder is not None else n
        return out, label, padded

    async def _flush(self, batch=None) -> None:
        if batch is None:
            with self._lock:
                batch = self._pending
                self._pending = []
                if self._flush_task is not None:
                    self._flush_task.cancel()
                    self._flush_task = None
        if not batch:
            return
        blocks = [b for b, _ in batch]
        loop = asyncio.get_running_loop()

        async def _direct(sub_blocks) -> List[bool]:
            if not sub_blocks:
                return []
            tracer = spans.active()
            # -- pack stage (host, loop thread): key lookup + list building;
            # the numpy pack-to-wire happens inside the submit below.
            t_pack = tracer.now() if tracer is not None else 0.0
            pack_started = time.monotonic()
            pks = [
                self.committee.get_public_key(b.author()).bytes
                for b in sub_blocks
            ]
            digests = [b.signed_digest() for b in sub_blocks]
            sigs = [b.signature for b in sub_blocks]
            self.pipeline.note_stage(
                STAGE_PACK, time.monotonic() - pack_started
            )
            if tracer is not None:
                for block in sub_blocks:
                    tracer.record_span("verify_pack", block.reference, t_pack)
            # -- bounded in-flight window: held from device submission
            # through result fetch.  Other flush windows keep packing (and
            # submitting, up to the depth) while this dispatch is in flight.
            async with self.pipeline.slot():
                t_dispatch = tracer.now() if tracer is not None else 0.0
                t_fetch = t_dispatch
                started = time.monotonic()
                if is_simulated():
                    # Inline (no executor hop) under the virtual-time
                    # simulator: while a real thread works, the virtual
                    # clock leaps timers, so ANY hop makes the sim's commit
                    # schedule depend on host load (a starved 2-core CI box
                    # can blow the whole virtual duration past one verify).
                    # Synchronous on the loop thread the virtual clock is
                    # frozen for the dispatch's duration — deterministic
                    # regardless of machine weather.  Slots still bound
                    # concurrency; sims measure determinism, not overlap.
                    out, label, padded = self._dispatch_and_fetch(
                        pks, digests, sigs
                    )
                    device_done = started
                    # Keep the stage decomposition honest: the single hop
                    # has no separate submit, so device is an explicit zero
                    # (not a missing sample) and fetch carries the whole
                    # dispatch.
                    self.pipeline.note_stage(STAGE_DEVICE, 0.0)
                else:
                    submit_fut = loop.run_in_executor(
                        None, self._submit_dispatch, pks, digests, sigs
                    )
                    try:
                        handle = await asyncio.shield(submit_fut)
                    except asyncio.CancelledError:
                        # Flush task cancelled mid-submit (node shutdown):
                        # the shielded executor job still runs and its
                        # handle may hold per-dispatch backend state (a
                        # pooled service connection, the breaker's exclusive
                        # probe flag) that only the fetch normally releases
                        # — dispose it the moment it lands.
                        submit_fut.add_done_callback(_abandon_dispatch)
                        raise
                    device_done = time.monotonic()
                    self.pipeline.note_stage(
                        STAGE_DEVICE, device_done - started
                    )
                    if tracer is not None:
                        t_fetch = tracer.now()
                        for block in sub_blocks:
                            tracer.record_span(
                                "verify_device", block.reference, t_dispatch,
                                t1=t_fetch,
                            )
                    # The fetch hop is shielded for the same reason the
                    # submit hop is: an unshielded cancel can cancel a
                    # QUEUED executor job before it starts, and then nothing
                    # ever consumes the handle (pooled connection, probe
                    # flag).  Shielded, the job always runs; result() does
                    # its own cleanup, so cancellation here needs only to
                    # observe the orphaned outcome.
                    fetch_fut = loop.run_in_executor(
                        None, self._fetch_dispatch, handle, len(sigs)
                    )
                    try:
                        out, label, padded = await asyncio.shield(fetch_fut)
                    except asyncio.CancelledError:
                        fetch_fut.add_done_callback(_observe_orphan)
                        raise
                self.pipeline.note_stage(
                    STAGE_FETCH, time.monotonic() - device_done
                )
            # The window EMA shares self._lock with the pending queue: the
            # read-modify-write must not interleave with _effective_delay_s
            # readers scheduling a flush from another flush's critical
            # section.  Under the simulator the EMA stays unseeded: it is a
            # WALL-clock measurement, and _effective_delay_s arms a
            # VIRTUAL-time flush timer from it — folding it in would make a
            # seeded sim's flush schedule (and so its whole commit
            # trajectory) depend on host load.  Sims run the fixed
            # max_delay_s window instead (the arrival-gap term is loop-
            # clocked and stays live).
            if not is_simulated():
                with self._lock:
                    self._dispatch_ema_s = _update_ema(
                        self._dispatch_ema_s,
                        time.monotonic() - started,
                        self.EMA_OUTLIER_S,
                    )
            if tracer is not None:
                t1 = tracer.now()
                for block in sub_blocks:
                    tracer.record_span(
                        "verify_fetch", block.reference, t_fetch, t1=t1
                    )
                    tracer.record_span(
                        "verify_dispatch", block.reference, t_dispatch, t1=t1
                    )
            # Backend counters measure ACTUAL dispatches: counted here, per
            # dispatch, so aggregate-skipped blocks never inflate them.
            if self.metrics is not None:
                self.metrics.verify_dispatch_batch_size.observe(len(sigs))
                # Padding waste: lanes the device computed beyond the real
                # signatures (bucket-shaped dispatches); host backends report
                # n (zero waste).
                self.metrics.verify_padding_wasted_total.labels(label).inc(
                    max(0, padded - len(sigs))
                )
                accepted = sum(bool(ok) for ok in out)
                if accepted:
                    self.metrics.verified_signatures_total.labels(
                        label, "accepted"
                    ).inc(accepted)
                if accepted < len(out):
                    self.metrics.verified_signatures_total.labels(
                        label, "rejected"
                    ).inc(len(out) - accepted)
            return out

        def _account(aggregated: int, direct: int) -> None:
            self.aggregated_total += aggregated
            self.direct_total += direct
            if self.metrics is not None and aggregated:
                self.metrics.verified_signatures_total.labels(
                    "aggregate", "skipped"
                ).inc(aggregated)

        try:
            if self.aggregate:
                results = await aggregate_verify(
                    blocks, self.committee, _direct, _account,
                    prior_endorsers=self._prior_endorsers,
                    defer_unresolved=True,
                )
                results = await self._resolve_deferred(batch, results, _direct)
                self._note_endorsements(blocks, results)
            else:
                _account(0, len(blocks))
                results = await _direct(blocks)
        except asyncio.CancelledError:
            # Flush task cancelled mid-dispatch (node teardown — the timer
            # handle's cancel() can't interrupt a running flush): the
            # window's futures must still resolve or verify() callers that
            # outlive this task park on `await future` forever.  Cancelling
            # them marks the infra outcome (never a verdict) and the
            # abandon/orphan callbacks above already released the backend
            # state.
            for _, future in batch:
                self._deferred.discard(id(future))
                if not future.done():
                    future.cancel()
            raise
        except Exception as exc:
            # A JAX runtime/compile failure must not strand the awaiting
            # connection tasks forever — fail every future in the batch.
            # The ORIGINAL exception propagates (not a VerificationError):
            # an infra failure is not evidence the signatures were invalid,
            # and callers must be able to tell "reject this block" apart from
            # "the verifier is down" (the latter resets the connection
            # instead of flagging the peer Byzantine).
            log.error("signature verifier crashed on %d blocks: %r",
                      len(batch), exc)
            for _, future in batch:
                self._deferred.discard(id(future))
                if not future.done():
                    future.set_exception(exc)
            return
        if self.metrics is not None:
            self.metrics.verify_batch_size.observe(len(batch))
        for (_, future), ok in zip(batch, results):
            if ok is None:
                continue  # deferred: resolves with the next flush
            if not future.done():
                future.set_result(bool(ok))

    async def _resolve_deferred(self, batch, results, _direct):
        """Route ``None`` (unresolved) slots from an aggregate flush.

        First deferral: fold the entry into the NEXT flush window — it will
        be endorsed there by newly arrived children or dispatched as
        ordinary frontier, so this flush stays at one accelerator
        round-trip (the round-4 tpu-agg saturation collapse was the second
        serialized trip).  Second deferral: force a direct dispatch — a
        block that stays "maybe" across windows is either ahead of its
        children (direct check settles it) or a Byzantine park attempt
        (minting fresh endorsers each window must not stall it forever).
        """
        results = list(results)
        requeue, force = [], []
        for slot, ((block, future), ok) in enumerate(zip(batch, results)):
            if ok is not None:
                self._deferred.discard(id(future))
                continue
            if id(future) in self._deferred:
                self._deferred.discard(id(future))
                force.append((slot, block))
            else:
                self._deferred.add(id(future))
                requeue.append((block, future))
        if force:
            out = await _direct([b for _, b in force])
            self.direct_total += len(force)
            for (slot, _), ok in zip(force, out):
                results[slot] = bool(ok)
        if requeue:
            loop = asyncio.get_running_loop()
            with self._lock:
                # Oldest first: deferred entries re-enter at the head.
                self._pending[:0] = requeue
                if self._flush_task is None:
                    self._schedule_flush(loop)
        return results

    async def verify_blocks(self, blocks: Sequence[StatementBlock]) -> List[bool]:
        """All blocks of a frame join the collector CONCURRENTLY — the base
        class's sequential per-block await would pay one collection window +
        dispatch per block.

        Only VerificationError means "invalid signature" (False).  Anything
        else — a JAX dispatch/compile crash, CancelledError during shutdown —
        re-raises, matching the base class's except-VerificationError-only
        semantics: infra failures must not masquerade as Byzantine rejections.
        """
        results = await asyncio.gather(
            *(self.verify(b) for b in blocks), return_exceptions=True
        )
        out: List[bool] = []
        for r in results:
            if isinstance(r, VerificationError):
                out.append(False)
            elif isinstance(r, BaseException):
                raise r
            else:
                out.append(True)
        return out

    ENDORSEMENT_MAX_ENTRIES = 200_000  # hard cap; FIFO eviction beyond it

    _EMPTY = frozenset()

    def _prior_endorsers(self, ref):
        # Callers must not mutate (endorsement_stake copies before mutating).
        return self._endorsements.get(ref, self._EMPTY)

    def _note_endorsements(self, blocks, results) -> None:
        """Record accepted blocks' includes in the endorsement index; only
        ACCEPTED blocks endorse (each was signature-verified or quorum-
        endorsed itself, so the license carries inductively).  Eviction is
        strictly by first-endorsement insertion order — recent entries (the
        live catch-up window) survive regardless of the rounds blocks CLAIM."""
        endorsements = self._endorsements
        for block, ok in zip(blocks, results):
            if not ok:
                continue
            author = block.author()
            for ref in block.includes:
                prev = endorsements.get(ref)
                if prev is None:
                    endorsements[ref] = {author}
                else:
                    prev.add(author)
        excess = len(endorsements) - self.ENDORSEMENT_MAX_ENTRIES
        if excess > 0:
            # dicts iterate in insertion order: drop the oldest entries.
            for ref in list(islice(iter(endorsements), excess)):
                del endorsements[ref]

    async def flush_now(self) -> None:
        """Test/shutdown hook: drain whatever is pending immediately —
        including aggregate-mode deferrals (a deferred entry re-enters
        ``_pending``; its second appearance force-dispatches, so this loop
        terminates)."""
        await self._flush()
        while self._pending:
            await self._flush()

    def health_state(self) -> dict:
        """Verifier-path state for the fleet health plane (health.py):
        breaker, routing pin, and staged-pipeline occupancy in one cheap
        read (unlocked snapshots — the probe tolerates a torn read)."""
        backend = self.verifier
        return {
            "breaker_open": bool(getattr(backend, "breaker_open", False)),
            "pinned_backend": getattr(backend, "pinned_backend", None),
            "backend": getattr(
                backend, "backend_label", type(backend).__name__
            ),
            "pipeline_inflight": self.pipeline.inflight,
            "pipeline_depth": self.pipeline.depth(),
        }
