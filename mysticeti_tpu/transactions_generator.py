"""Open-loop benchmark transaction generator, with overload modes.

Capability parity with ``mysticeti-core/src/transactions_generator.rs``:

* seeded RNG, fixed transaction size (default 512 B), target tx/s (:29-45)
* 100 ms ticks producing evenly-sized batches, submitted to the block handler
  (:47-101)
* each transaction is prefixed with an 8-byte submission timestamp + 8-byte
  nonce; ``extract_timestamp`` recovers it for end-to-end latency metrics
  (:103-108)

Ingress-plane additions (the OVERLOAD artifact's load clients):

* **overload schedule** — ``overload_schedule=[(t_offset_s, multiplier),...]``
  scales the offered rate over the run (1x -> 5x ramps), so one generator can
  drive a saturation sweep without restarts.
* **closed loop** — ``closed_loop=True`` consumes the typed
  :class:`~mysticeti_tpu.ingress.SubmitResult` the ingress plane returns
  from ``submit``: on SHED the generator honors ``retry_after_ms`` before
  submitting again and re-offers the shed tail from a bounded retry queue
  (overflow is counted on ``client_drops``, never silent).  Legacy handlers
  returning ``None`` keep the pure open-loop behavior.

Clocks are the RUNTIME clock (``runtime.timestamp_utc`` for the embedded
stamps, the loop clock for pacing): identical to wall time in production,
virtual under the deterministic simulator — which is what makes the seeded
overload sim's offered load and shed schedule byte-identical across runs.
"""
from __future__ import annotations

import asyncio
import random
import struct
from collections import deque
from typing import Callable, Deque, List, Optional, Sequence, Tuple

from .runtime import now as runtime_now, timestamp_utc

TRANSACTION_SIZE_DEFAULT = 512
TICK_S = 0.1

# Closed loop: retry-queue bound in ticks of offered load; beyond it the
# client itself drops (and counts) — a shed backlog must not grow without
# limit on the client either.
RETRY_QUEUE_TICKS = 10


def parse_overload_schedule(text: str) -> List[Tuple[float, float]]:
    """Parse ``"0:1,30:3,60:5"`` (``t_offset_s:multiplier`` pairs) — the
    ``MYSTICETI_OVERLOAD_SCHEDULE`` env format the node CLI accepts."""
    schedule: List[Tuple[float, float]] = []
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        t, _, mult = part.partition(":")
        schedule.append((float(t), float(mult)))
    return sorted(schedule)


class TransactionGenerator:
    def __init__(
        self,
        submit: Callable[[List[bytes]], object],
        seed: int,
        tps: int,
        transaction_size: int = TRANSACTION_SIZE_DEFAULT,
        initial_delay_s: float = 0.0,
        ready: Optional[Callable[[], bool]] = None,
        overload_schedule: Optional[Sequence[Tuple[float, float]]] = None,
        closed_loop: bool = False,
        finality_sample_every: int = 0,
        metrics=None,
    ) -> None:
        assert transaction_size >= 16, "needs room for timestamp + nonce"
        self.submit = submit
        self.rng = random.Random(seed)
        self.tps = tps
        self.transaction_size = transaction_size
        self.initial_delay_s = initial_delay_s
        self.ready = ready
        self.overload_schedule = sorted(overload_schedule or [])
        self.closed_loop = closed_loop
        self.metrics = metrics
        self._task: Optional[asyncio.Task] = None
        # Offered-load accounting (the OVERLOAD artifact's client ledger).
        self.submitted = 0
        self.accepted = 0
        self.shed_observed = 0
        self.retries = 0
        self.client_drops = 0
        self._retry_queue: Deque[bytes] = deque()
        self._hold_until = 0.0
        # CLIENT-observed finality (finality.py): sampled submit stamps
        # closed when commit notifications echo the ingress keys back.
        # Same content-based sampling stride as the server tracker, so
        # both sides measure the same transactions.  Loop-thread only.
        self.finality = None
        if finality_sample_every > 0:
            from .finality import ClientFinalityRecorder

            self.finality = ClientFinalityRecorder(
                sample_every=finality_sample_every
            )

    def make_batch(self, count: int) -> List[bytes]:
        now = timestamp_utc()
        ts = struct.pack("<d", now)
        pad = b"\x00" * (self.transaction_size - 16)
        return [
            ts + struct.pack("<Q", self.rng.getrandbits(64)) + pad
            for _ in range(count)
        ]

    @staticmethod
    def extract_timestamp(transaction: bytes) -> float:
        """First 8 bytes = float64 submission time (transactions_generator.rs:103-108)."""
        if len(transaction) < 8:
            return 0.0
        return struct.unpack("<d", transaction[:8])[0]

    def multiplier(self, elapsed_s: float) -> float:
        """Offered-load multiplier at ``elapsed_s`` into the run: the last
        schedule entry whose offset has passed (1.0 before the first)."""
        current = 1.0
        for t, mult in self.overload_schedule:
            if elapsed_s >= t:
                current = mult
            else:
                break
        return current

    def stats(self) -> dict:
        out = {
            "submitted": self.submitted,
            "accepted": self.accepted,
            "shed_observed": self.shed_observed,
            "retries": self.retries,
            "client_drops": self.client_drops,
            "retry_queue": len(self._retry_queue),
        }
        if self.finality is not None:
            p = self.finality.percentiles()
            out["client_finality_p50_s"] = round(p["p50_s"], 6)
            out["client_finality_p99_s"] = round(p["p99_s"], 6)
            out["client_finality_samples"] = p["samples"]
        return out

    def note_commit_notification(self, keys, info=None) -> None:
        """Commit-notification feed (an ingress-plane sink or the gateway
        subscription stream): close client-observed finality for sampled
        keys this client submitted.  ``info`` (leader round, commit
        timestamp) is accepted for sink-signature compatibility."""
        if self.finality is None:
            return
        self.finality.note_finalized(keys)
        if self.metrics is not None:
            p = self.finality.percentiles()
            self.metrics.mysticeti_client_finality_p50_seconds.set(p["p50_s"])
            self.metrics.mysticeti_client_finality_p99_seconds.set(p["p99_s"])

    def start(self) -> asyncio.Task:
        self._task = asyncio.get_event_loop().create_task(self._run())
        return self._task

    def _offer(self, batch: List[bytes]) -> None:
        """One submission, honoring the closed-loop contract when armed."""
        if self.finality is not None:
            from .ingress import ingress_key

            for tx in batch:
                # note_submitted keeps the FIRST stamp on retries, so the
                # sample covers the whole client-experienced wait.
                self.finality.note_submitted(ingress_key(tx))
        result = self.submit(batch)
        self.submitted += len(batch)
        if result is None or not self.closed_loop:
            # Open loop (or a legacy handler with no verdict): fire and
            # forget, exactly the pre-ingress behavior.
            if result is not None:
                self.accepted += getattr(result, "accepted", len(batch))
                self.shed_observed += getattr(result, "shed", 0)
            return
        accepted = getattr(result, "accepted", len(batch))
        shed = getattr(result, "shed", 0)
        self.accepted += accepted
        self.shed_observed += shed
        if shed:
            retry_ms = getattr(result, "retry_after_ms", 0)
            self._hold_until = runtime_now() + max(retry_ms, 1) / 1000.0
            # The plane admits a PREFIX and sheds the tail (admission funds
            # in order; lane/pool caps reject in order), so the shed tail is
            # the batch's last `shed` transactions.  Duplicates are not
            # worth re-offering, but they cannot appear here: this client
            # never re-generates a nonce, and retried txs that were ADMITTED
            # are not in the tail.
            tail = batch[len(batch) - shed:]
            room = RETRY_QUEUE_TICKS * max(1, int(self.tps * TICK_S)) - len(
                self._retry_queue
            )
            if room < len(tail):
                self.client_drops += len(tail) - max(0, room)
                tail = tail[: max(0, room)]
            self._retry_queue.extend(tail)

    async def _run(self) -> None:
        # Offered load is pointless against a node that cannot process it yet:
        # wait for the verifier's one-time warmup (JAX trace/compile, possibly
        # minutes when several processes share a host) before the clock-driven
        # initial delay, so submission timestamps measure steady state and not
        # a warmup backlog.
        if self.ready is not None:
            while not self.ready():
                await asyncio.sleep(0.5)
        if self.initial_delay_s:
            await asyncio.sleep(self.initial_delay_s)
        start = runtime_now()
        while True:
            tick_started = runtime_now()
            per_tick = max(
                1, int(self.tps * self.multiplier(tick_started - start) * TICK_S)
            )
            if self.closed_loop and tick_started < self._hold_until:
                # Shed backoff: generate nothing new this tick (the retry
                # queue holds what the plane told us to re-offer later).
                pass
            else:
                batch: List[bytes] = []
                if self._retry_queue:
                    n_retry = min(len(self._retry_queue), per_tick)
                    batch.extend(
                        self._retry_queue.popleft() for _ in range(n_retry)
                    )
                    self.retries += n_retry
                batch.extend(self.make_batch(per_tick - len(batch)))
                self._offer(batch)
            elapsed = runtime_now() - tick_started
            await asyncio.sleep(max(0.0, TICK_S - elapsed))

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
