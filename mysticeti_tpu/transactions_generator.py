"""Open-loop benchmark transaction generator.

Capability parity with ``mysticeti-core/src/transactions_generator.rs``:

* seeded RNG, fixed transaction size (default 512 B), target tx/s (:29-45)
* 100 ms ticks producing evenly-sized batches, submitted to the block handler
  (:47-101)
* each transaction is prefixed with an 8-byte submission timestamp + 8-byte
  nonce; ``extract_timestamp`` recovers it for end-to-end latency metrics
  (:103-108)
"""
from __future__ import annotations

import asyncio
import random
import struct
import time
from typing import Callable, List, Optional

TRANSACTION_SIZE_DEFAULT = 512
TICK_S = 0.1


class TransactionGenerator:
    def __init__(
        self,
        submit: Callable[[List[bytes]], None],
        seed: int,
        tps: int,
        transaction_size: int = TRANSACTION_SIZE_DEFAULT,
        initial_delay_s: float = 0.0,
        ready: Optional[Callable[[], bool]] = None,
    ) -> None:
        assert transaction_size >= 16, "needs room for timestamp + nonce"
        self.submit = submit
        self.rng = random.Random(seed)
        self.tps = tps
        self.transaction_size = transaction_size
        self.initial_delay_s = initial_delay_s
        self.ready = ready
        self._task: Optional[asyncio.Task] = None

    def make_batch(self, count: int) -> List[bytes]:
        now = time.time()
        ts = struct.pack("<d", now)
        pad = b"\x00" * (self.transaction_size - 16)
        return [
            ts + struct.pack("<Q", self.rng.getrandbits(64)) + pad
            for _ in range(count)
        ]

    @staticmethod
    def extract_timestamp(transaction: bytes) -> float:
        """First 8 bytes = float64 submission time (transactions_generator.rs:103-108)."""
        if len(transaction) < 8:
            return 0.0
        return struct.unpack("<d", transaction[:8])[0]

    def start(self) -> asyncio.Task:
        self._task = asyncio.get_event_loop().create_task(self._run())
        return self._task

    async def _run(self) -> None:
        # Offered load is pointless against a node that cannot process it yet:
        # wait for the verifier's one-time warmup (JAX trace/compile, possibly
        # minutes when several processes share a host) before the clock-driven
        # initial delay, so submission timestamps measure steady state and not
        # a warmup backlog.
        if self.ready is not None:
            while not self.ready():
                await asyncio.sleep(0.5)
        if self.initial_delay_s:
            await asyncio.sleep(self.initial_delay_s)
        per_tick = max(1, int(self.tps * TICK_S))
        while True:
            started = time.monotonic()
            self.submit(self.make_batch(per_tick))
            elapsed = time.monotonic() - started
            await asyncio.sleep(max(0.0, TICK_S - elapsed))

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
