"""Always-on flight recorder: the seconds that led up to the incident.

When an SLO alert or a chaos safety failure fires, the metrics say *that*
something broke and the spans say where committed blocks spent their time —
but neither holds the recent *event sequence*: which connections churned,
which breaker tripped, what the GC deleted, what the node adopted.  This
module is the bounded black box that does:

* :class:`FlightRecorder` — a fixed-capacity in-memory ring of structured
  events, one per node, recorded from the consensus hot paths at edge
  granularity (block lifecycle edges, breaker/pin transitions, SLO alerts,
  GC/checkpoint actions, sync decisions, connection churn, and the host
  attribution plane's ``blocking-call`` detections — hostattr.py flags a
  synchronous hold of the core owner past the threshold — never per
  message).  The ring is lock-disciplined (``_ring_lock``; the lint's
  GUARDED_FIELDS covers the ring field) because dumps may be requested from
  the metrics endpoint or a signal path while the loop records.
* Dump triggers, all writing the SAME canonical JSON document atomically
  (tmp + rename):
  - orderly shutdown / SIGTERM — ``Validator.stop`` dumps to the path from
    ``MYSTICETI_FLIGHT_RECORDER`` (``%p`` expands to the pid);
  - ``GET /debug/flight-recorder`` on the metrics endpoint returns the
    document live (``metrics.serve_metrics``);
  - SLO alert transitions — the health watchdog calls :meth:`on_alert`,
    which records the alert and writes a debounced ``<path>.alert`` dump so
    a flapping threshold cannot turn the recorder into a disk hose;
  - chaos safety failures — ``run_chaos_sim`` dumps every live node's
    recorder the moment the :class:`~mysticeti_tpu.chaos.SafetyChecker`
    fails, so the forensic window is preserved exactly when it matters.

Events are clocked by the RUNTIME clock and recorded on the loop thread, so
under the deterministic simulator a seeded run produces a byte-identical
dump every run (pinned by ``tests/test_fleet_trace.py``).  Production dumps
additionally carry a wall-clock stamp; simulated ones deliberately do not
(it would break reproducibility for zero diagnostic value — virtual time IS
the sim's wall time).
"""
from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Deque, List, Optional

from .runtime import is_simulated, now as runtime_now
from .tracing import logger

log = logger(__name__)

ENV_FLIGHT_RECORDER = "MYSTICETI_FLIGHT_RECORDER"

# Ring capacity: at edge granularity (commits batched per handle_commit,
# transitions, churn) a busy node records a few events per second, so 4096
# holds many minutes of history in ~1 MB — enough to cover any alert's
# debounce window plus the run-up.
DEFAULT_CAPACITY = 4096

# Minimum seconds between alert-triggered dumps (runtime-clocked).
ALERT_DEBOUNCE_S = 30.0


def path_from_env(authority: Optional[int] = None) -> Optional[str]:
    """The dump path from ``MYSTICETI_FLIGHT_RECORDER`` (``%p`` -> pid,
    ``%a`` -> authority index), or None when the operator did not ask for
    on-disk dumps (the ring still records — the debug route serves it).
    ``%a`` matters for the in-process testbed, where every validator shares
    one pid and a bare ``%p`` path would leave only the last-stopped
    node's dump."""
    path = os.environ.get(ENV_FLIGHT_RECORDER)
    if not path:
        return None
    path = path.replace("%p", str(os.getpid()))
    if authority is not None:
        path = path.replace("%a", str(authority))
    return path


def _canonical(obj) -> bytes:
    return json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()


class FlightRecorder:
    """Bounded ring of recent structured events for one node."""

    def __init__(
        self,
        authority: Optional[int] = None,
        capacity: int = DEFAULT_CAPACITY,
        dump_path: Optional[str] = None,
        metrics=None,
        alert_debounce_s: float = ALERT_DEBOUNCE_S,
    ) -> None:
        self.authority = authority
        self.capacity = max(1, capacity)
        self.dump_path = dump_path
        self.metrics = metrics
        self.alert_debounce_s = alert_debounce_s
        self._ring_lock = threading.Lock()
        # Guarded by _ring_lock (lint GUARDED_FIELDS): the loop thread
        # records while the metrics endpoint / a signal path snapshots.
        self._flight_ring: Deque[dict] = deque(maxlen=self.capacity)
        self.recorded = 0
        self.dropped = 0
        # Dump ledger: {trigger, file, t} per on-disk dump (basenames only —
        # dumps must stay byte-identical across same-seed sims run in
        # different temp dirs).
        self.dumps: List[dict] = []
        self._last_alert_dump_t: Optional[float] = None

    # -- recording (hot-ish path: edges only, one dict + one lock) --

    def record(self, kind: str, **fields) -> None:
        entry = {"t": round(runtime_now(), 6), "kind": kind}
        for key, value in fields.items():
            if value is not None:
                entry[key] = value
        with self._ring_lock:
            if len(self._flight_ring) == self._flight_ring.maxlen:
                self.dropped += 1
            self._flight_ring.append(entry)
            self.recorded += 1

    def on_alert(
        self, kind: str, authority, stage: str, value: float, detail: str
    ) -> None:
        """SLO watchdog hook: record the alert edge and (when a dump path is
        configured) write a debounced ``<path>.alert`` dump — the forensic
        ring AT the degraded transition, not minutes later."""
        self.record(
            "slo-alert", alert=kind, indicted=authority, stage=stage,
            value=round(float(value), 6), detail=detail,
        )
        if not self.dump_path:
            return
        t = runtime_now()
        if (
            self._last_alert_dump_t is not None
            and t - self._last_alert_dump_t < self.alert_debounce_s
        ):
            return
        self._last_alert_dump_t = t
        self.dump("slo-alert", path=self.dump_path + ".alert")

    # -- snapshots / dumps --

    def events(self, last: Optional[int] = None) -> List[dict]:
        with self._ring_lock:
            events = list(self._flight_ring)
        return events[-last:] if last else events

    def snapshot(self) -> dict:
        """The dump document (also served by ``/debug/flight-recorder``)."""
        with self._ring_lock:
            events = list(self._flight_ring)
            recorded, dropped = self.recorded, self.dropped
        doc = {
            "authority": self.authority,
            "capacity": self.capacity,
            "recorded": recorded,
            "dropped": dropped,
            "events": events,
            "dumps": list(self.dumps),
        }
        if not is_simulated():
            import time as _time

            doc["generated_unix"] = round(_time.time(), 3)
        return doc

    def snapshot_bytes(self) -> bytes:
        return _canonical(self.snapshot())

    def dump(self, trigger: str, path: Optional[str] = None) -> Optional[str]:
        """Atomic dump (tmp + rename) to ``path`` or the configured path.
        Returns the written path, or None when neither is set.  Never
        raises: the recorder is a diagnostic, not a failure mode."""
        path = path or self.dump_path
        if not path:
            return None
        try:
            tmp = f"{path}.tmp"
            with open(tmp, "wb") as f:
                f.write(self.snapshot_bytes())
                f.write(b"\n")
            os.replace(tmp, path)
        except OSError:
            log.exception("flight-recorder dump to %s failed", path)
            return None
        self.dumps.append(
            {
                "trigger": trigger,
                "file": os.path.basename(path),
                "t": round(runtime_now(), 6),
            }
        )
        if self.metrics is not None:
            self.metrics.flight_recorder_dumps_total.labels(trigger).inc()
        log.info("flight recorder dumped (%s) to %s", trigger, path)
        return path
