"""Pure-Python RFC 8032 Ed25519 — the no-dependency fallback oracle.

Loaded by :mod:`mysticeti_tpu.crypto` when the ``cryptography`` package is
absent.  Exposes the exact class surface ``crypto.py`` consumes from
``cryptography.hazmat.primitives.asymmetric.ed25519`` (``generate``,
``from_private_bytes``, ``sign``, ``public_key``, ``public_bytes_raw``,
``from_public_bytes``, ``verify``) plus ``InvalidSignature``.

Verification is STRICT, matching the OpenSSL/RFC 8032 semantics the TPU
kernels are tested against (tests/test_ed25519_fused.py):

* ``S >= L`` rejected (malleability defense);
* non-canonical point encodings (``y >= p``) of A and R rejected;
* the group equation checked without cofactor: ``[S]B == R + [k]A``.

Scalar multiplication is a plain double-and-add over extended homogeneous
coordinates; verification uses Straus/Shamir simultaneous multiplication so
a verify costs roughly one scalar-mult of point additions.  ~1-3 ms per
operation in CPython — the correctness oracle for tests, not a production
signing path (the batched TPU kernel is the fast path).
"""
from __future__ import annotations

import hashlib
import os
from typing import List, Optional, Tuple

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)

# Extended homogeneous coordinates (X, Y, Z, T) with x = X/Z, y = Y/Z,
# x*y = T/Z (RFC 8032 §5.1.4).
_Point = Tuple[int, int, int, int]

_IDENTITY: _Point = (0, 1, 1, 0)

_BASE_Y = 4 * pow(5, P - 2, P) % P


def _recover_x(y: int, sign: int) -> Optional[int]:
    if y >= P:
        return None
    x2 = (y * y - 1) * pow(D * y * y + 1, P - 2, P) % P
    if x2 == 0:
        return None if sign else 0
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * SQRT_M1 % P
    if (x * x - x2) % P != 0:
        return None
    if x & 1 != sign:
        x = P - x
    return x


_BASE: _Point = (
    _recover_x(_BASE_Y, 0),  # type: ignore[assignment]
    _BASE_Y,
    1,
    _recover_x(_BASE_Y, 0) * _BASE_Y % P,  # type: ignore[operator]
)


def _add(p: _Point, q: _Point) -> _Point:
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = 2 * t1 * t2 * D % P
    dd = 2 * z1 * z2 % P
    e, f, g, h = b - a, dd - c, dd + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def _mul(s: int, p: _Point) -> _Point:
    q = _IDENTITY
    while s > 0:
        if s & 1:
            q = _add(q, p)
        p = _add(p, p)
        s >>= 1
    return q


def _double_mul(s: int, k: int, a: _Point) -> _Point:
    """Straus simultaneous [s]B + [k]A — one shared doubling chain."""
    ba = _add(_BASE, a)
    q = _IDENTITY
    for bit in range(max(s.bit_length(), k.bit_length()) - 1, -1, -1):
        q = _add(q, q)
        sb, kb = (s >> bit) & 1, (k >> bit) & 1
        if sb and kb:
            q = _add(q, ba)
        elif sb:
            q = _add(q, _BASE)
        elif kb:
            q = _add(q, a)
    return q


def _compress(p: _Point) -> bytes:
    x, y, z, _ = p
    zinv = pow(z, P - 2, P)
    x, y = x * zinv % P, y * zinv % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _decompress(raw: bytes) -> Optional[_Point]:
    if len(raw) != 32:
        return None
    enc = int.from_bytes(raw, "little")
    y = enc & ((1 << 255) - 1)
    x = _recover_x(y, enc >> 255)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


def _equal(p: _Point, q: _Point) -> bool:
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return (x1 * z2 - x2 * z1) % P == 0 and (y1 * z2 - y2 * z1) % P == 0


def _sha512_mod_l(*parts: bytes) -> int:
    h = hashlib.sha512()
    for part in parts:
        h.update(part)
    return int.from_bytes(h.digest(), "little") % L


class InvalidSignature(Exception):
    """Raised by ``Ed25519PublicKey.verify`` on rejection (API parity with
    ``cryptography.exceptions.InvalidSignature``)."""


class Ed25519PublicKey:
    __slots__ = ("_raw", "_point")

    def __init__(self, raw: bytes) -> None:
        self._raw = raw
        self._point: Optional[_Point] = None  # decoded lazily, at first verify

    @classmethod
    def from_public_bytes(cls, raw: bytes) -> "Ed25519PublicKey":
        if len(raw) != 32:
            raise ValueError("public key must be 32 bytes")
        return cls(bytes(raw))

    def public_bytes_raw(self) -> bytes:
        return self._raw

    def verify(self, signature: bytes, message: bytes) -> None:
        if len(signature) != 64:
            raise InvalidSignature("signature must be 64 bytes")
        if self._point is None:
            point = _decompress(self._raw)
            if point is None:
                raise InvalidSignature("undecodable public key")
            self._point = point
        r_point = _decompress(signature[:32])
        if r_point is None:
            raise InvalidSignature("undecodable R")
        s = int.from_bytes(signature[32:], "little")
        if s >= L:
            raise InvalidSignature("non-canonical S")
        k = _sha512_mod_l(signature[:32], self._raw, message)
        # [S]B == R + [k]A  <=>  [S]B + [k](-A) == R
        x, y, z, t = self._point
        neg_a = (P - x, y, z, P - t)
        if not _equal(_double_mul(s, k, neg_a), r_point):
            raise InvalidSignature("group equation failed")


class Ed25519PrivateKey:
    __slots__ = ("_scalar", "_prefix", "_pk_bytes")

    def __init__(self, seed: bytes) -> None:
        h = hashlib.sha512(seed).digest()
        scalar = int.from_bytes(h[:32], "little")
        scalar &= (1 << 254) - 8
        scalar |= 1 << 254
        self._scalar = scalar
        self._prefix = h[32:]
        self._pk_bytes = _compress(_mul(scalar, _BASE))

    @classmethod
    def generate(cls) -> "Ed25519PrivateKey":
        return cls(os.urandom(32))

    @classmethod
    def from_private_bytes(cls, seed: bytes) -> "Ed25519PrivateKey":
        if len(seed) != 32:
            raise ValueError("private key seed must be 32 bytes")
        return cls(bytes(seed))

    def public_key(self) -> Ed25519PublicKey:
        return Ed25519PublicKey(self._pk_bytes)

    def sign(self, message: bytes) -> bytes:
        r = _sha512_mod_l(self._prefix, message)
        r_bytes = _compress(_mul(r, _BASE))
        k = _sha512_mod_l(r_bytes, self._pk_bytes, message)
        s = (r + k * self._scalar) % L
        return r_bytes + s.to_bytes(32, "little")


def selftest() -> None:
    """RFC 8032 test vector 1 (empty message) — cheap import-time sanity
    guard used by the test suite, not run on import."""
    seed = bytes.fromhex(
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60"
    )
    key = Ed25519PrivateKey.from_private_bytes(seed)
    assert key.public_key().public_bytes_raw() == bytes.fromhex(
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a"
    )
    sig = key.sign(b"")
    assert sig == bytes.fromhex(
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
        "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b"
    )
    key.public_key().verify(sig, b"")


__all__ = [
    "Ed25519PrivateKey",
    "Ed25519PublicKey",
    "InvalidSignature",
    "selftest",
]
