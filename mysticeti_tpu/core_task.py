"""Single-owner consensus dispatcher — the L7 concurrency bridge.

Capability parity with ``mysticeti-core/src/core_thread/spawned.rs``: all
consensus state mutation is serialized through ONE owner; network tasks submit
``CoreTaskCommand``s over a bounded queue (32) and await oneshot replies
(:15-60,117-152).  In Python the owner is a dedicated asyncio task rather than
an OS thread — the GIL makes a thread pointless for pure-Python state, and the
TPU dispatch (the actually-parallel part) releases the GIL inside the batched
verifier's executor thread (SURVEY §7 stage 7 note).

The simulator needs no variant (core_thread/simulated.rs): the owner task is
already deterministic under the DeterministicLoop.
"""
from __future__ import annotations

import asyncio
from typing import List, Optional, Sequence, Set, Tuple

from .syncer import Syncer
from .tracing import logger
from .types import AuthoritySet, BlockReference, RoundNumber, StatementBlock

log = logger(__name__)

CORE_QUEUE_SIZE = 32


class CoreTaskDispatcher:
    # Consecutive COUNTED command failures after which the owner halts —
    # and only when the run spans MORE THAN ONE command type.  A failure
    # counts when no live caller received the exception (ADVICE r5: a
    # client retry-looping one failing command gets its exception back
    # every time — caller churn, not state corruption) OR when the command
    # is INTERNAL (cleanup, get_missing, force_new_block: driven by the
    # node's own periodic tasks, which a remote client cannot make fail —
    # under a poisoned store they supply the halt's second command type
    # within seconds even though their callers are alive and observing).
    # The distinct-type requirement covers the churn the observed split
    # alone cannot: a retry loop whose awaits are CANCELLED (e.g. wait_for
    # timeouts) also reads as unobserved, but it hammers one command;
    # genuine corruption poisons every mutation type.
    MAX_CONSECUTIVE_FAILURES = 16

    def __init__(self, syncer: Syncer, metrics=None,
                 fatal_handler=None) -> None:
        self.syncer = syncer
        self.metrics = metrics
        # Called when the owner dies on a persistent failure.  Merely
        # letting the task die would leave a ZOMBIE: ports held, /metrics
        # stale, every subsequent command awaiting a reply forever.  The
        # default terminates the process (the reference's panic posture);
        # tests inject a recorder.
        self.fatal_handler = fatal_handler or self._default_fatal
        # Host attribution plane (hostattr.py): when a HostMonitor is
        # attached, every synchronous command's wall duration is reported
        # to its blocking-call detector — the dynamic twin of the
        # async-blocking lint rule.
        self.blocking_monitor = None
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=CORE_QUEUE_SIZE)
        self._task: Optional[asyncio.Task] = None
        self._stopped = False

    def queue_depth(self) -> int:
        """Commands waiting for the consensus owner — the ingress plane's
        core-congestion tap (a persistently deep queue means intake is
        outrunning the single-owner pipeline)."""
        return self._queue.qsize()

    @property
    def queue_capacity(self) -> int:
        return CORE_QUEUE_SIZE

    @staticmethod
    def _default_fatal() -> None:
        import os
        import signal as _signal

        os.kill(os.getpid(), _signal.SIGTERM)

    def _on_owner_done(self, task: asyncio.Task) -> None:
        if self._stopped or task.cancelled():
            return
        exc = task.exception()
        if exc is not None:
            log.critical("consensus owner died: %r — invoking fatal handler",
                         exc)
            self.fatal_handler()

    def start(self) -> "CoreTaskDispatcher":
        self._task = asyncio.ensure_future(self._run())
        self._task.add_done_callback(self._on_owner_done)
        return self

    async def _run(self) -> None:
        # Every consensus mutation flows through here, so timing each command
        # gives the utilization breakdown the reference gets from its
        # UtilizationTimer instrumentation of the core thread
        # (core.rs/core_thread) — scrapeable as utilization_timer{proc=...}.
        timers = self.metrics.utilization_timer if self.metrics else None
        consecutive_failures = 0
        failed_kinds: Set[str] = set()
        dequeued = self.metrics.core_lock_dequeued if self.metrics else None
        # Wall-clock measurement is a host observation: under the
        # virtual-time loop it would read real elapsed time against
        # simulated schedules, so the detector stays off there (evaluated
        # once — the loop flavor cannot change mid-run).
        from .runtime import is_simulated
        from time import perf_counter

        measure_blocking = not is_simulated()
        while True:
            command, args, reply, internal = await self._queue.get()
            if dequeued is not None:
                dequeued.inc()
            try:
                label = getattr(command, "__name__", "other")
                monitor = self.blocking_monitor
                t0 = (
                    perf_counter()
                    if monitor is not None and measure_blocking
                    else 0.0
                )
                if timers is not None:
                    with timers(f"core:{label}"):
                        result = command(*args)
                else:
                    result = command(*args)
                if monitor is not None and measure_blocking:
                    monitor.note_command(
                        f"core:{label}", perf_counter() - t0
                    )
                consecutive_failures = 0
                failed_kinds.clear()
                if reply is not None and not reply.done():
                    reply.set_result(result)
            except Exception as e:  # propagate to the caller, keep the loop alive
                observed = reply is not None and not reply.done()
                if observed:
                    reply.set_exception(e)
                if observed and not internal:
                    # A live caller received (and handles) the exception:
                    # observed EXTERNAL failures are caller churn, not
                    # corruption — they never count toward the fail-stop
                    # halt.  Internal commands count regardless: a remote
                    # client cannot drive them, so their failures are
                    # trustworthy corruption evidence.
                    continue
                # Unobserved (caller cancelled mid-await) or internal: the
                # owner loop must survive a short run — dying on one would
                # wedge every future consensus command fleet-wide, turning
                # one connection teardown into a total liveness failure.
                consecutive_failures += 1
                failed_kinds.add(getattr(command, "__name__", repr(command)))
                log.exception(
                    "core command %s failed (%s)",
                    getattr(command, "__name__", command),
                    "internal" if internal else "no live caller",
                )
                if (
                    consecutive_failures >= self.MAX_CONSECUTIVE_FAILURES
                    and len(failed_kinds) > 1
                ):
                    # EVERY recent command failed: that is not a transient
                    # (a cancelled caller, one malformed batch) but a
                    # persistent fail-stop condition — WAL/state corruption,
                    # a poisoned store.  Running on, on possibly corrupt
                    # state, is the one thing a fail-stop consensus node
                    # must never do; crash loudly instead (ADVICE r4).
                    log.critical(
                        "%d consecutive core command failures — halting the "
                        "consensus owner (fail-stop)",
                        consecutive_failures,
                    )
                    raise

    async def _call(self, fn, *args, internal: bool = False):
        reply: asyncio.Future = asyncio.get_running_loop().create_future()
        if self.metrics is not None:
            self.metrics.core_lock_enqueued.inc()
        await self._queue.put((fn, args, reply, internal))
        return await reply

    # -- commands (core_thread/spawned.rs:26-46) --

    async def add_blocks(
        self, blocks: Sequence[StatementBlock], connected: AuthoritySet
    ) -> List[BlockReference]:
        return await self._call(self.syncer.add_blocks, list(blocks), connected)

    async def force_new_block(
        self, round_: RoundNumber, connected: AuthoritySet,
        genesis: bool = False,
    ) -> bool:
        # internal: driven by the leader-timeout task (or the boot-time
        # genesis kick, which must not be attributed as a leader timeout),
        # not a remote peer.
        return await self._call(
            self.syncer.force_new_block, round_, connected, genesis,
            internal=True,
        )

    async def cleanup(self) -> None:
        # internal: driven by the node's periodic task.  Routed through the
        # syncer so the observer's settled floor moves in the same owner
        # step as the store's GC (see Syncer.cleanup).
        return await self._call(self.syncer.cleanup, internal=True)

    async def apply_snapshot(self, manifest) -> bool:
        """Adopt a snapshot catch-up baseline (storage.py) on the owner —
        commit-chain state and the observer's linearizer move together."""
        return await self._call(self.syncer.apply_snapshot, manifest)

    async def get_missing(self) -> List[Set[BlockReference]]:
        # internal: driven by the synchronizer's periodic task.
        return await self._call(
            lambda: [set(s) for s in self.syncer.core.block_manager.missing_blocks()],
            internal=True,
        )

    async def processed(
        self, references: Sequence[BlockReference]
    ) -> List[bool]:
        """Which references are already stored/pending (dedup gate before the
        expensive signature verification, net_sync.rs:325-336)."""
        return await self._call(
            lambda: [
                self.syncer.core.block_manager.exists_or_pending(r)
                for r in references
            ]
        )

    def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()


class DataPlaneOffload:
    """Routes batched native data-plane calls off the event loop.

    The native batch helpers (block_digests, decode_block, the frame
    codecs) release the GIL around their heavy work — but calling them ON
    the event loop still serializes that work with consensus scheduling.
    This single-worker executor moves whole-frame decode+digest batches to
    a side thread, in front of the :class:`CoreTaskDispatcher` single-owner
    seam: the decoded blocks still cross the owner exactly as before (the
    ingest invariant), only the CPU burn moves off-loop.

    One worker, deliberately: batches stay ordered per submission site, and
    the GIL-holding portions (Python object construction) never contend
    with a second offload thread.  Stage wall time is observable two ways,
    mirroring verify_pipeline's stage gauges:
    ``utilization_timer{proc="offload:<stage>"}`` (busy µs, measured IN the
    worker thread so executor queue wait is excluded) and the
    ``dataplane_offload_seconds{stage}`` histogram.

    Determinism: ``active()`` is False under ``runtime.is_simulated()`` —
    seeded sims take the caller's inline path and stay byte-identical
    (thread handoff timing is not virtualizable).  It is also False without
    the native extension: the pure-Python fallback gains nothing from a
    thread hop (the GIL is held throughout), so ``MYSTICETI_NO_NATIVE=1``
    pins the fully-inline pure path.
    """

    # Below this many payload bytes the executor round-trip costs more than
    # the GIL-released hashing saves; small frames stay inline.
    MIN_BATCH_BYTES = 16 * 1024

    def __init__(self, metrics=None) -> None:
        self.metrics = metrics
        self._executor = None
        self._active: Optional[bool] = None

    def active(self) -> bool:
        if self._active is None:
            # Evaluated lazily on first use (inside the running loop, like
            # the dispatcher's measure_blocking): the loop flavor cannot
            # change mid-run.
            from .native import native as _native
            from .runtime import is_simulated

            self._active = _native is not None and not is_simulated()
        return self._active

    def should_offload(self, total_bytes: int) -> bool:
        return self.active() and total_bytes >= self.MIN_BATCH_BYTES

    def _ensure_executor(self):
        if self._executor is None:
            from concurrent.futures import ThreadPoolExecutor

            # The prefix feeds profiling.thread_class_of → "offload" in the
            # host-attribution thread taxonomy.
            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="dataplane-offload"
            )
        return self._executor

    async def run(self, stage: str, fn, *args):
        """Run ``fn(*args)`` on the offload worker; awaitable result."""
        loop = asyncio.get_running_loop()
        metrics = self.metrics

        def work():
            if metrics is None:
                return fn(*args)
            from time import perf_counter

            t0 = perf_counter()
            try:
                with metrics.utilization_timer(f"offload:{stage}"):
                    return fn(*args)
            finally:
                metrics.dataplane_offload_seconds.labels(stage).observe(
                    perf_counter() - t0
                )

        return await loop.run_in_executor(self._ensure_executor(), work)

    def stop(self) -> None:
        if self._executor is not None:
            self._executor.shutdown(wait=False)
            self._executor = None
