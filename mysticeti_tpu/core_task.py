"""Single-owner consensus dispatcher — the L7 concurrency bridge.

Capability parity with ``mysticeti-core/src/core_thread/spawned.rs``: all
consensus state mutation is serialized through ONE owner; network tasks submit
``CoreTaskCommand``s over a bounded queue (32) and await oneshot replies
(:15-60,117-152).  In Python the owner is a dedicated asyncio task rather than
an OS thread — the GIL makes a thread pointless for pure-Python state, and the
TPU dispatch (the actually-parallel part) releases the GIL inside the batched
verifier's executor thread (SURVEY §7 stage 7 note).

The simulator needs no variant (core_thread/simulated.rs): the owner task is
already deterministic under the DeterministicLoop.
"""
from __future__ import annotations

import asyncio
from typing import List, Optional, Sequence, Set, Tuple

from .syncer import Syncer
from .tracing import logger
from .types import AuthoritySet, BlockReference, RoundNumber, StatementBlock

log = logger(__name__)

CORE_QUEUE_SIZE = 32


class CoreTaskDispatcher:
    # Consecutive command failures (with or without a live caller) after
    # which the owner halts: a run this long is a persistent fail-stop
    # condition, not caller churn.
    MAX_CONSECUTIVE_FAILURES = 16

    def __init__(self, syncer: Syncer, metrics=None,
                 fatal_handler=None) -> None:
        self.syncer = syncer
        self.metrics = metrics
        # Called when the owner dies on a persistent failure.  Merely
        # letting the task die would leave a ZOMBIE: ports held, /metrics
        # stale, every subsequent command awaiting a reply forever.  The
        # default terminates the process (the reference's panic posture);
        # tests inject a recorder.
        self.fatal_handler = fatal_handler or self._default_fatal
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=CORE_QUEUE_SIZE)
        self._task: Optional[asyncio.Task] = None
        self._stopped = False

    @staticmethod
    def _default_fatal() -> None:
        import os
        import signal as _signal

        os.kill(os.getpid(), _signal.SIGTERM)

    def _on_owner_done(self, task: asyncio.Task) -> None:
        if self._stopped or task.cancelled():
            return
        exc = task.exception()
        if exc is not None:
            log.critical("consensus owner died: %r — invoking fatal handler",
                         exc)
            self.fatal_handler()

    def start(self) -> "CoreTaskDispatcher":
        self._task = asyncio.ensure_future(self._run())
        self._task.add_done_callback(self._on_owner_done)
        return self

    async def _run(self) -> None:
        # Every consensus mutation flows through here, so timing each command
        # gives the utilization breakdown the reference gets from its
        # UtilizationTimer instrumentation of the core thread
        # (core.rs/core_thread) — scrapeable as utilization_timer{proc=...}.
        timers = self.metrics.utilization_timer if self.metrics else None
        consecutive_failures = 0
        dequeued = self.metrics.core_lock_dequeued if self.metrics else None
        while True:
            command, args, reply = await self._queue.get()
            if dequeued is not None:
                dequeued.inc()
            try:
                if timers is not None:
                    label = getattr(command, "__name__", "other")
                    with timers(f"core:{label}"):
                        result = command(*args)
                else:
                    result = command(*args)
                consecutive_failures = 0
                if reply is not None and not reply.done():
                    reply.set_result(result)
            except Exception as e:  # propagate to the caller, keep the loop alive
                consecutive_failures += 1
                if reply is not None and not reply.done():
                    reply.set_exception(e)
                else:
                    # Caller gone (connection task cancelled mid-await): the
                    # owner loop must survive — dying here would wedge every
                    # future consensus command fleet-wide, turning one
                    # connection teardown into a total liveness failure.
                    log.exception(
                        "core command %s failed with no live caller",
                        getattr(command, "__name__", command),
                    )
                if consecutive_failures >= self.MAX_CONSECUTIVE_FAILURES:
                    # EVERY recent command failed: that is not a transient
                    # (a cancelled caller, one malformed batch) but a
                    # persistent fail-stop condition — WAL/state corruption,
                    # a poisoned store.  Running on, on possibly corrupt
                    # state, is the one thing a fail-stop consensus node
                    # must never do; crash loudly instead (ADVICE r4).
                    log.critical(
                        "%d consecutive core command failures — halting the "
                        "consensus owner (fail-stop)",
                        consecutive_failures,
                    )
                    raise

    async def _call(self, fn, *args):
        reply: asyncio.Future = asyncio.get_running_loop().create_future()
        if self.metrics is not None:
            self.metrics.core_lock_enqueued.inc()
        await self._queue.put((fn, args, reply))
        return await reply

    # -- commands (core_thread/spawned.rs:26-46) --

    async def add_blocks(
        self, blocks: Sequence[StatementBlock], connected: AuthoritySet
    ) -> List[BlockReference]:
        return await self._call(self.syncer.add_blocks, list(blocks), connected)

    async def force_new_block(
        self, round_: RoundNumber, connected: AuthoritySet
    ) -> bool:
        return await self._call(self.syncer.force_new_block, round_, connected)

    async def cleanup(self) -> None:
        return await self._call(self.syncer.core.cleanup)

    async def get_missing(self) -> List[Set[BlockReference]]:
        return await self._call(
            lambda: [set(s) for s in self.syncer.core.block_manager.missing_blocks()]
        )

    async def processed(
        self, references: Sequence[BlockReference]
    ) -> List[bool]:
        """Which references are already stored/pending (dedup gate before the
        expensive signature verification, net_sync.rs:325-336)."""
        return await self._call(
            lambda: [
                self.syncer.core.block_manager.exists_or_pending(r)
                for r in references
            ]
        )

    def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
