"""Syncer: drives the Core on block arrival and leader timeouts, emits signals.

Capability parity with ``mysticeti-core/src/syncer.rs``:

* ``Signals`` {new_block_ready, new_round} (:24-52) — wake the dissemination
  streams / reset the leader-timeout clock.
* ``Syncer.add_blocks`` (:72-93) — feed core, signal round advance, maybe propose.
* ``Syncer.force_new_block`` (:95-108) — leader-timeout path, bypasses the
  ready gate.
* ``try_new_block`` (:110-167) — ready-gate -> propose -> signal -> commit ->
  observer -> persist commit + aggregator state.
"""
from __future__ import annotations

from typing import List, Sequence

from . import spans
from .commit_observer import CommitObserver
from .core import Core
from .tracing import logger
from .types import AuthoritySet, BlockReference, RoundNumber, StatementBlock

log = logger(__name__)


class SyncerSignals:
    """Interface; the asyncio node wires these to Event/condition primitives."""

    def new_block_ready(self) -> None:
        pass

    def new_round(self, round_: RoundNumber) -> None:
        pass


class Syncer:
    def __init__(
        self,
        core: Core,
        commit_period: int,
        signals: SyncerSignals,
        commit_observer: CommitObserver,
        metrics=None,
    ) -> None:
        self.core = core
        self.force_new_block_flag = False
        self.commit_period = commit_period
        self.signals = signals
        self.commit_observer = commit_observer
        self.metrics = metrics

    def add_blocks(
        self, blocks: Sequence[StatementBlock], connected_authorities: AuthoritySet
    ) -> List[BlockReference]:
        previous_round = self.core.current_round()
        missing_references = self.core.add_blocks(blocks)
        new_round = self.core.current_round()
        if new_round > previous_round:
            self.signals.new_round(new_round)
            if self.metrics is not None:
                self.metrics.threshold_clock_round.set(new_round)
        self.try_new_block(connected_authorities)
        return missing_references

    def force_new_block(
        self, round_: RoundNumber, connected_authorities: AuthoritySet,
        genesis: bool = False,
    ) -> bool:
        if self.core.last_proposed() < round_:
            if self.metrics is not None:
                self.metrics.leader_timeout_total.inc()
                if not genesis:
                    # Attribute the stall: the timeout fired because the
                    # leader(s) of the round being abandoned never showed —
                    # counted per authority so fleet health can name the
                    # validator whose slots keep timing out.  The boot-time
                    # genesis kick reaches here too and indicts nobody.
                    for leader in self.core.leaders(max(1, round_ - 1)):
                        channel = (
                            self.metrics.mysticeti_health_leader_timeout_total
                        )
                        channel.labels(str(leader)).inc()
            self.force_new_block_flag = True
            self.try_new_block(connected_authorities)
            return True
        return False

    def cleanup(self) -> None:
        """Periodic maintenance on the consensus owner: cache eviction + GC
        (core) AND the observer's settled floor, in ONE step — the
        linearizer must never run a commit DFS with a floor older than the
        store's (a ref retired by this pass but below the linearizer's
        stale floor would fail the 'whole sub-dag must be stored' check)."""
        self.core.cleanup()
        floor = self.core.dag_floor()
        if floor:
            self.commit_observer.note_gc_round(floor)

    def apply_snapshot(self, manifest) -> bool:
        """Snapshot catch-up (storage.py): adopt the remote commit baseline
        on the core, then jump the observer's linearizer to the same
        baseline — both or neither, on the single consensus owner."""
        if not self.core.apply_snapshot(manifest):
            return False
        self.commit_observer.adopt_snapshot(manifest)
        return True

    def try_new_block(self, connected_authorities: AuthoritySet) -> None:
        if self.force_new_block_flag or self.core.ready_new_block(
            self.commit_period, connected_authorities
        ):
            if self.core.try_new_block() is None:
                return
            self.signals.new_block_ready()
            self.force_new_block_flag = False

            if self.core.epoch_closed():
                return  # no commits needed once the epoch is safe to close

            tracer = spans.active()
            t_commit = tracer.now() if tracer is not None else 0.0
            while True:
                newly_committed = self.core.try_commit()
                if newly_committed:
                    log.debug(
                        "committed %d leaders up to round %d",
                        len(newly_committed),
                        max(b.round() for b in newly_committed),
                    )
                committed_subdags = self.commit_observer.handle_commit(
                    newly_committed
                )
                self.core.handle_committed_subdag(
                    committed_subdags, self.commit_observer.aggregator_state()
                )
                if tracer is not None:
                    # One span per decided leader: decision + observer +
                    # commit/state persistence.
                    for block in newly_committed:
                        tracer.record_span(
                            "commit", block.reference, t_commit,
                            authority=self.core.authority,
                        )
                # Reconfiguration makes try_commit slot-sequential (one
                # decided leader per pass, so an epoch switch lands between
                # slots); drain the remaining decidable slots here.  With
                # the knob off a pass decides everything at once and this
                # loop runs exactly once — the seed behavior.
                if self.core.reconfig is None or not newly_committed:
                    break
