"""Commit observers: consume committed leaders, produce ordered sub-dags.

Capability parity with ``mysticeti-core/src/commit_observer.rs``:

* ``CommitObserver`` interface {handle_commit, aggregator_state} (:23-32)
* ``TestCommitObserver`` (:42-198) — benchmark observer: linearizes commits,
  tallies committed transactions through a TransactionAggregator, records the
  benchmark-defining latency metrics (latency_s{shared}, latency_squared_s,
  benchmark_duration), tracks committed leaders.
* ``SimpleCommitObserver`` (:200-290) — production observer: forwards sub-dags
  to an application queue; on recovery re-sends commits above the consumer's
  ``last_sent_height``.
"""
from __future__ import annotations

import os
import time
from typing import Callable, Dict, List, Optional

from . import spans
from .block_store import BlockStore
from .committee import Committee, QUORUM, TransactionAggregator
from .consensus.linearizer import CommittedSubDag, Linearizer
from .runtime import now as runtime_now
from .state import CommitObserverRecoveredState
from .types import BlockReference, StatementBlock


def _trace_committed(tracer, t0: float, committed, authority) -> None:
    """Shared by both observers: one ``finalize`` span per sequenced sub-dag
    (anchored at its leader) and the close of every sequenced block's
    ``proposal_wait`` span (opened when the block entered the DAG)."""
    t1 = tracer.now()
    for commit in committed:
        tracer.record_span(
            "finalize", commit.anchor, t0, t1=t1, authority=authority
        )
        for block in commit.blocks:
            tracer.end_span(
                "proposal_wait", block.reference, authority=authority, t=t1
            )


class CommitObserver:
    # Flight recorder (flight_recorder.py), wired post-construction by the
    # node assembly: one "commit" edge per handle_commit batch — the block
    # lifecycle signal the incident ring keeps, at commit (not per-block)
    # granularity.
    recorder = None
    # Ingress plane (ingress.IngressPlane), wired post-construction like the
    # recorder: the committed sequence feeds gateway commit notifications
    # and the admission controller's progress signal.
    ingress = None

    def _record_committed(
        self, committed: List[CommittedSubDag], t_commit: Optional[float] = None
    ) -> None:
        if self.recorder is not None and committed:
            last = committed[-1]
            self.recorder.record(
                "commit",
                height=last.height,
                sub_dags=len(committed),
                anchor=spans.format_ref(last.anchor),
            )
        if self.ingress is not None and committed:
            # t_commit = the observer's entry time (the commit decision);
            # note_committed's own clock supplies the finalize time.
            self.ingress.note_committed(committed, t_commit=t_commit)

    def handle_commit(
        self, committed_leaders: List[StatementBlock]
    ) -> List[CommittedSubDag]:
        raise NotImplementedError

    def aggregator_state(self) -> bytes:
        raise NotImplementedError

    # -- storage lifecycle seams (storage.py; default: forward to the
    #    linearizer both concrete observers own) --

    def note_gc_round(self, gc_round: int) -> None:
        """The store's retired floor moved: references below it are settled
        and must stop the linearizer's DFS (they are no longer on disk)."""
        interpreter = getattr(self, "commit_interpreter", None)
        if interpreter is not None:
            interpreter.set_gc_round(gc_round)

    def adopt_snapshot(self, manifest) -> None:
        """Snapshot catch-up: adopt a remote commit baseline — the
        linearizer resumes sequencing at ``manifest.commit_height + 1``.
        The transaction aggregator is deliberately NOT transferred
        (application-level, per-node); commits below the baseline are
        outside this node's observation window."""
        interpreter = getattr(self, "commit_interpreter", None)
        if interpreter is not None:
            interpreter.adopt_snapshot(
                manifest.commit_height,
                manifest.committed_refs,
                manifest.gc_round,
            )
        votes = getattr(self, "transaction_votes", None)
        if votes is not None and hasattr(votes, "relax_below"):
            # The observer aggregator only learns shares when their block is
            # processed in a commit — and every commit at or below the
            # adopted height was skipped.  Those sub-dags reach up to the
            # adopted leader's round, so the leniency watermark must too
            # (the handler's stays at the lower GC floor: it handled every
            # RECEIVED block, which covers [floor, frontier]).
            watermark = manifest.gc_round
            if manifest.last_committed_leader is not None:
                watermark = max(watermark, manifest.last_committed_leader.round)
            votes.relax_below(watermark)


class TestCommitObserver(CommitObserver):
    """Benchmark/test observer (commit_observer.rs:42-198)."""

    __test__ = False  # not a pytest class

    def __init__(
        self,
        block_store: BlockStore,
        committee: Committee,
        # Interface parity with commit_observer.rs (which computes shared-tx
        # latency from this map); HERE latency comes from the 8-byte
        # timestamp the generator embeds in each transaction, so the map —
        # keyed per own proposal block since round 4 — is accepted but
        # never read.
        transaction_time: Optional[Dict[BlockReference, float]] = None,
        metrics=None,
        handler=None,
        recovered_state: Optional[CommitObserverRecoveredState] = None,
    ) -> None:
        self.commit_interpreter = Linearizer(block_store)
        self.transaction_votes = handler or TransactionAggregator(QUORUM)
        self.committee = committee
        self.committed_leaders: List[BlockReference] = []
        # Measurement window opens at the FIRST committed benchmark tx, not at
        # node boot: tps = count / benchmark_duration must not be diluted by
        # warmup (JAX compile, INITIAL_DELAY) that precedes any load.  The
        # reference gets the same effect by scraping duration from the load
        # client rather than the node (protocol/mod.rs:57-67).
        self._bench_t0: float | None = None
        self.transaction_time = transaction_time if transaction_time is not None else {}
        self.metrics = metrics
        self.consensus_only = "CONSENSUS_ONLY" in os.environ
        if recovered_state is not None:
            self._recover_committed(recovered_state)

    def _recover_committed(self, recovered: CommitObserverRecoveredState) -> None:
        if recovered.state is not None:
            self.transaction_votes.with_state(
                recovered.state,
                self.commit_interpreter.block_store.highest_round(),
            )
        else:
            assert not recovered.sub_dags
        self.commit_interpreter.recover_state(recovered)

    def handle_commit(self, committed_leaders):
        # transaction_time stamps (shared with the block handler) are on the
        # runtime clock (monotonic in production, virtual under the
        # simulator), same-process: certificate intervals read the same
        # source.  Generator-embedded stamps are wall-clock by design
        # (cross-process) and are read with time.time() at the batch-metrics
        # call below.
        now = runtime_now()
        tracer = spans.active()
        committed = self.commit_interpreter.handle_commit(committed_leaders)
        stamps: List[bytes] = []
        for commit in committed:
            self.committed_leaders.append(commit.anchor)
            for block in commit.blocks:
                if not self.consensus_only:
                    certified = self.transaction_votes.process_block(
                        block, None, self.committee
                    )
                    if certified and self.metrics is not None:
                        # Certificates completing during commit processing
                        # (metrics.rs:59 certificate_committed_latency):
                        # one sample per range, stamped at proposal.
                        channel = self.metrics.certificate_committed_latency
                        for rng in certified:
                            created = self.transaction_time.get(rng.block)
                            if created is not None:
                                channel.observe(max(0.0, now - created))
                if self.metrics is not None:
                    stamps.append(block.shared_transaction_stamps())
        if committed and self.metrics is not None:
            # meta_creation_time_ns is stamped with runtime.timestamp_utc()
            # (virtual time under the simulator) — the comparison clock must
            # be the same source, NOT wall time.
            from .runtime import timestamp_utc

            now_utc = timestamp_utc()
            self.metrics.commit_round.set(committed[-1].anchor.round)
            self.metrics.sub_dags_per_commit_count.observe(len(committed))
            for commit in committed:
                self.metrics.committed_leaders_total.labels(
                    str(commit.anchor.authority), "committed"
                ).inc()
                self.metrics.blocks_per_commit_count.observe(len(commit.blocks))
                for block in commit.blocks:
                    created = block.meta_creation_time_ns
                    if created:
                        self.metrics.block_commit_latency.observe(
                            max(0.0, now_utc - created / 1e9)
                        )
        heads = b"".join(stamps)
        if heads:
            # Wall clock on purpose: the generator's embedded submission
            # stamps are wall-clock floats shared across processes.
            self._update_metrics_batch(heads, time.time())
        if tracer is not None:
            _trace_committed(
                tracer,
                now,
                committed,
                self.commit_interpreter.block_store.authority,
            )
        self._record_committed(committed, t_commit=now)
        return committed

    def _update_metrics_batch(self, heads: bytes, now: float) -> None:
        """Benchmark metrics (commit_observer.rs:104-140): latency measured
        from the 8-byte float64 submission timestamp the generator prefixes
        to each tx.  ``heads`` is the pre-concatenated stamp bytes
        (``shared_transaction_stamps``); everything from here is one
        vectorized pass — per-transaction Python objects dominated the
        engine profile at load, twice (r4: prometheus observes; r5: locator
        construction + double iteration)."""
        import numpy as np

        # Loop clock, not the wall: virtual under the simulator, so the
        # benchmark-duration counter advances deterministically in a seeded
        # sim instead of absorbing host scheduling.
        if self._bench_t0 is None:
            self._bench_t0 = runtime_now()
        elapsed = runtime_now() - self._bench_t0
        delta = int(elapsed) - int(self.metrics.benchmark_duration._value.get())
        if delta > 0:
            self.metrics.benchmark_duration.inc(delta)
        ts = np.frombuffer(heads, "<f8")
        latencies = np.maximum(0.0, now - ts)
        latencies[ts == 0.0] = 0.0  # unstamped txs count as zero latency
        self.metrics.observe_latency_batch("shared", latencies)
        self.metrics.transaction_committed_latency.observe_many(latencies)

    def aggregator_state(self) -> bytes:
        return self.transaction_votes.state()


class SimpleCommitObserver(CommitObserver):
    """Production observer: forward sub-dags to the application
    (commit_observer.rs:200-290)."""

    def __init__(
        self,
        block_store: BlockStore,
        sender: Callable[[CommittedSubDag], None],
        last_sent_height: int = 0,
        recovered_state: Optional[CommitObserverRecoveredState] = None,
        metrics=None,
    ) -> None:
        self.block_store = block_store
        self.commit_interpreter = Linearizer(block_store)
        self.sender = sender
        self.metrics = metrics
        if recovered_state is not None:
            self._recover_committed(last_sent_height, recovered_state)

    def _recover_committed(
        self, last_sent_height: int, recovered: CommitObserverRecoveredState
    ) -> None:
        self.commit_interpreter.recover_state(recovered)
        for commit_data in recovered.sub_dags:
            if commit_data.height > last_sent_height:
                self.sender(
                    CommittedSubDag.new_from_commit_data(commit_data, self.block_store)
                )

    def handle_commit(self, committed_leaders):
        tracer = spans.active()
        now = runtime_now()
        t0 = tracer.now() if tracer is not None else 0.0
        committed = self.commit_interpreter.handle_commit(committed_leaders)
        for commit in committed:
            self.sender(commit)
        if tracer is not None:
            _trace_committed(tracer, t0, committed, self.block_store.authority)
        self._record_committed(committed, t_commit=now)
        return committed

    def aggregator_state(self) -> bytes:
        return b""
