"""Logging: env-filterable levels + the virtual-time per-authority formatter.

Capability parity with the reference's tracing setup:

* env-filter levels a la ``RUST_LOG`` (``mysticeti/src/main.rs:80-83``):
  ``MYSTICETI_LOG="info"`` or ``MYSTICETI_LOG="net_sync=debug,core=info,warning"``
  — bare token sets the package root level, ``module=level`` tokens set
  per-module levels (module names relative to ``mysticeti_tpu``).
* the simulator formatter (``simulator_tracing.rs:14-56``): when a log record
  is emitted inside a :class:`~mysticeti_tpu.runtime.simulated.DeterministicLoop`
  the timestamp printed is the VIRTUAL time, and the emitting validator's
  authority index (a contextvar set per simulated node task) prefixes the
  line — so a 10-node sim failure produces one readable interleaved trace.
"""
from __future__ import annotations

import asyncio
import contextvars
import logging
import os
import sys
from typing import Optional

# Which authority (validator index) the current task belongs to — the
# equivalent of future_simulator.rs:336-361's per-node context.
current_authority: contextvars.ContextVar[Optional[int]] = contextvars.ContextVar(
    "mysticeti_authority", default=None
)

PACKAGE = "mysticeti_tpu"

_LEVELS = {
    "trace": logging.DEBUG,  # python has no TRACE; map to DEBUG
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "off": logging.CRITICAL + 10,
}


# Lazy module-level cache: the import must stay deferred (runtime.simulated
# is only importable once the package is fully initialized), but re-importing
# on EVERY log record made the formatter's isinstance check pay a sys.modules
# lookup per line of output.
_DeterministicLoop = None


class SimAwareFormatter(logging.Formatter):
    """``[  12.345s A3] level module: msg`` under a virtual-time loop,
    wall-clock otherwise."""

    def format(self, record: logging.LogRecord) -> str:
        global _DeterministicLoop
        if _DeterministicLoop is None:
            from .runtime.simulated import DeterministicLoop

            _DeterministicLoop = DeterministicLoop

        stamp = None
        try:
            loop = asyncio.get_running_loop()
            if isinstance(loop, _DeterministicLoop):
                stamp = f"{loop.time():9.3f}s"
        except RuntimeError:
            pass
        if stamp is None:
            stamp = self.formatTime(record, "%H:%M:%S")
        authority = current_authority.get()
        who = f" A{authority}" if authority is not None else ""
        module = record.name
        if module.startswith(PACKAGE + "."):
            module = module[len(PACKAGE) + 1 :]
        return (
            f"[{stamp}{who}] {record.levelname.lower():<7} {module}: "
            f"{record.getMessage()}"
        )


# Child loggers whose level the last applied spec set; reset before the next
# spec is applied so stale per-module levels never leak across re-installs.
_touched_modules: set = set()


def setup_logging(
    spec: Optional[str] = None, stream=None, force: bool = False
) -> None:
    """Install the handler/levels from ``spec`` (default: $MYSTICETI_LOG).

    No-op when the env var is unset and no spec given (library mode: stay
    silent, as the reference does without RUST_LOG).
    """
    if spec is None:
        spec = os.environ.get("MYSTICETI_LOG")
    if not spec:
        return
    root = logging.getLogger(PACKAGE)
    if root.handlers and not force:
        return
    for h in list(root.handlers):
        root.removeHandler(h)
    # Reset per-module levels a PREVIOUS spec installed: child logger levels
    # outlive the handler swap, so a force re-install of "warning" after
    # "net_sync=debug" would otherwise keep net_sync at debug forever.
    for name in _touched_modules:
        logging.getLogger(name).setLevel(logging.NOTSET)
    _touched_modules.clear()
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(SimAwareFormatter())
    root.addHandler(handler)
    root.propagate = False
    base_level = logging.INFO
    for token in spec.split(","):
        token = token.strip()
        if not token:
            continue
        if "=" in token:
            module, _, level = token.partition("=")
            name = f"{PACKAGE}.{module.strip()}"
            logging.getLogger(name).setLevel(
                _LEVELS.get(level.strip().lower(), logging.INFO)
            )
            _touched_modules.add(name)
        else:
            base_level = _LEVELS.get(token.lower(), logging.INFO)
    root.setLevel(base_level)


def logger(name: str) -> logging.Logger:
    """Module logger factory: ``logger(__name__)``."""
    return logging.getLogger(name)
