"""RangeMap — an ordered map that compacts contiguous key ranges with one value.

Capability parity with ``mysticeti-core/src/range_map.rs:14-180``: maps half-open
``[start, end)`` integer ranges to values, with ``mutate_range`` visiting every
sub-range that overlaps a requested range (splitting existing entries at the
boundaries) and every gap (value ``None``).  Backs the per-block fast-path vote
aggregation in ``TransactionAggregator`` (committee.rs:368-425), where many
contiguous transaction offsets share one ``StakeAggregator``.

Python-idiomatic design rather than a BTreeMap translation: entries live in a flat
sorted list of ``(start, end, value)`` and ``mutate_range`` does a single linear
sweep, rebuilding the overlapped span.  The mutation callback *returns* the new
value (``None`` deletes), instead of mutating an Option in place.
"""
from __future__ import annotations

from bisect import bisect_left, insort
from typing import Callable, Iterator, List, Optional, Tuple, TypeVar

V = TypeVar("V")

MutateFn = Callable[[int, int, Optional[V]], Optional[V]]


def _clone(v: object) -> object:
    """Independent copy for split fragments; immutable values pass through."""
    copy_method = getattr(v, "copy", None)
    return copy_method() if callable(copy_method) else v


class RangeMap:
    __slots__ = ("_entries",)

    def __init__(self) -> None:
        # Sorted, disjoint, non-empty [start, end) -> value entries.
        self._entries: List[Tuple[int, int, object]] = []

    def mutate_range(self, start: int, end: int, f: MutateFn) -> None:
        """Visit every overlapping sub-range and gap of [start, end) with
        ``f(sub_start, sub_end, value_or_None) -> new_value_or_None``.

        ``f`` may be invoked multiple times (once per overlapped fragment), matching
        range_map.rs:33-38.  Returning ``None`` removes the fragment.
        """
        if start >= end:
            return
        out: List[Tuple[int, int, object]] = []
        cursor = start  # next uncovered key within the requested range
        for s, e, v in self._entries:
            if e <= start or s >= end:
                out.append((s, e, v))
                continue
            # Splitting an entry must give each fragment an independent value
            # (range_map.rs clones on split) — otherwise a vote tallied on one
            # fragment would leak into its siblings through the shared aggregator.
            first_fragment = True
            # keep the part of this entry before the requested range
            if s < start:
                out.append((s, start, v))
                first_fragment = False
            ov_start, ov_end = max(s, start), min(e, end)
            # gap between previous fragment and this entry
            if cursor < ov_start:
                nv = f(cursor, ov_start, None)
                if nv is not None:
                    out.append((cursor, ov_start, nv))
            after_v = _clone(v) if e > end else None  # clone BEFORE f mutates v
            nv = f(ov_start, ov_end, v if first_fragment else _clone(v))
            if nv is not None:
                out.append((ov_start, ov_end, nv))
            cursor = ov_end
            # keep the part of this entry after the requested range
            if e > end:
                out.append((end, e, after_v))
        if cursor < end:
            nv = f(cursor, end, None)
            if nv is not None:
                out.append((cursor, end, nv))
        out.sort(key=lambda t: t[0])
        self._entries = out

    def get(self, key: int) -> Optional[object]:
        i = bisect_left(self._entries, (key + 1,)) - 1
        if i >= 0:
            s, e, v = self._entries[i]
            if s <= key < e:
                return v
        return None

    def items(self) -> Iterator[Tuple[int, int, object]]:
        return iter(self._entries)

    def is_empty(self) -> bool:
        return not self._entries

    def __len__(self) -> int:
        return len(self._entries)

    def __repr__(self) -> str:
        return "RangeMap(" + ", ".join(f"[{s},{e})={v!r}" for s, e, v in self._entries) + ")"
