"""Byzantine adversary plane: active-attack injection for the chaos tier.

The paper's headline property (arXiv 2310.14821) is *Byzantine* fault
tolerance — safety and liveness with up to f < n/3 arbitrary-faulty
authorities — yet the chaos engine (PR 3) only ever exercised benign
faults: link loss, partitions, crash-restarts, torn WAL tails.  This
module adds authorities that actively lie.

An adversary here is an otherwise-honest simulated validator whose
OUTBOUND traffic is rewritten at the network seam (the same
``SimulatedNetwork.fault_injector`` hook the chaos engine owns), which
models the attacks the protocol actually defends against without forking
the consensus code:

* ``equivocate`` — distinct VALID blocks at the same round to disjoint
  peer subsets: one half of the committee receives the node's real
  proposal, the other half a re-signed variant with a different digest.
  Both are structurally valid and correctly signed, so honest nodes
  accept both — detection happens in the DAG index
  (``mysticeti_equivocation_detected_total{authority}``).
* ``withhold`` — proposals are disseminated only to a favored subset
  smaller than a quorum; everyone else sees the authority go silent and
  must recover its blocks through includes + the fetch path.
* ``invalid_sig`` — own blocks ship with tampered Ed25519 signatures
  (digest-consistent, so the structure check passes and rejection happens
  exactly at the batched signature verifier —
  ``mysticeti_invalid_blocks_total{authority, reason="signature"}``).
* ``mangle`` — outbound messages are probabilistically replaced with
  garbage block payloads, exercising the malformed-input drop paths
  (``reason="malformed"``; on real sockets the analogous garbage *frames*
  sever the connection and count on
  ``mysticeti_malformed_frames_total{peer}``, see network.py).
* ``lag`` — own proposals are delayed just under the leader timeout, the
  grey-failure leader that stalls rounds without ever looking dead.

Determinism: the engine's per-message draws come from a dedicated
``random.Random`` seeded from the :class:`~mysticeti_tpu.chaos.FaultPlan`
seed, and every injected action is appended to an :class:`AttackLedger`
whose canonical-JSON serialization is byte-identical across same-seed
runs — the attack schedule is as reproducible as the benign fault log.

``docs/adversary.md`` documents the behavior catalog, the detection
surfaces, and the trust model; ``mysticeti_tpu/scenarios.py`` composes
adversary mixes with the chaos/storage/health planes into the declarative
resilience scenario matrix.
"""
from __future__ import annotations

import asyncio
import json
import random
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import crypto
from .network import Blocks, EncodedFrame, RequestBlocksResponse, TimestampedBlocks
from .tracing import logger
from .types import StatementBlock

log = logger(__name__)

_U64X2 = struct.Struct("<QQ")

BEHAVIORS = ("equivocate", "withhold", "invalid_sig", "mangle", "lag")

# Default favored-subset size for ``withhold``: strictly below any quorum
# for committees the sim tier runs (the attack is "disseminate to < quorum").
DEFAULT_WITHHOLD_KEEP = 2
DEFAULT_MANGLE_P = 0.05
DEFAULT_LAG_S = 0.8


@dataclass(frozen=True)
class AdversarySpec:
    """One Byzantine authority's declared behavior, JSON round-trippable
    (rides inside :class:`~mysticeti_tpu.chaos.FaultPlan` so the attack
    schedule is part of the same declarative, seeded plan as the benign
    faults).  ``start_s``/``end_s`` window the attack; ``params`` carries
    behavior-specific knobs (``keep``, ``mangle_p``, ``lag_s``)."""

    node: int
    behavior: str
    start_s: float = 0.0
    end_s: Optional[float] = None
    params: Tuple[Tuple[str, float], ...] = ()

    def __post_init__(self):
        if self.behavior not in BEHAVIORS:
            raise ValueError(
                f"unknown adversary behavior {self.behavior!r} "
                f"(known: {', '.join(BEHAVIORS)})"
            )

    def active(self, t: float) -> bool:
        if t < self.start_s:
            return False
        return self.end_s is None or t < self.end_s

    def param(self, key: str, default: float) -> float:
        for k, v in self.params:
            if k == key:
                return v
        return default

    def to_dict(self) -> dict:
        return {
            "node": self.node,
            "behavior": self.behavior,
            "start_s": self.start_s,
            "end_s": self.end_s,
            "params": {k: v for k, v in self.params},
        }

    @staticmethod
    def from_dict(d: dict) -> "AdversarySpec":
        return AdversarySpec(
            node=int(d["node"]),
            behavior=str(d["behavior"]),
            start_s=float(d.get("start_s", 0.0)),
            end_s=None if d.get("end_s") is None else float(d["end_s"]),
            params=tuple(sorted(
                (str(k), float(v))
                for k, v in (d.get("params") or {}).items()
            )),
        )


def _canonical_json(obj) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


class AttackLedger:
    """Every injected attack action, in injection order.

    Under the DeterministicLoop message order is reproducible and the
    engine's draws consume a plan-seeded RNG in that order, so
    :meth:`ledger_bytes` is byte-identical across same-seed runs — the
    adversarial twin of the chaos engine's fault log."""

    def __init__(self) -> None:
        self._entries: List[dict] = []

    def note(self, kind: str, **fields) -> None:
        entry = {"t": asyncio.get_event_loop().time(), "kind": kind}
        entry.update(fields)
        self._entries.append(entry)

    @property
    def entries(self) -> List[dict]:
        return list(self._entries)

    def ledger_bytes(self) -> bytes:
        return _canonical_json(self._entries).encode()

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for entry in self._entries:
            key = f"{entry['kind']}:{entry['node']}"
            out[key] = out.get(key, 0) + 1
        return out


def tamper_signature(raw: bytes) -> bytes:
    """A signature-invalid twin of a serialized block: the trailing Ed25519
    signature is flipped, everything else untouched.  The receiver's
    ``from_bytes`` recomputes the digest over the TAMPERED bytes, so the
    reference stays self-consistent and ``verify_structure`` passes — the
    rejection happens exactly where it should, at the signature verifier
    (the end-to-end path satellite 3 pins)."""
    raw = bytes(raw)
    body, sig = raw[: -crypto.SIGNATURE_SIZE], raw[-crypto.SIGNATURE_SIZE :]
    return body + bytes(b ^ 0xFF for b in sig)


def equivocating_variant(raw: bytes, signer: crypto.Signer) -> bytes:
    """A second VALID block at the same (authority, round): identical
    includes and statements, perturbed creation-time meta, re-signed — a
    different digest that every honest structure/signature check accepts.
    This is real equivocation, not corruption: only the DAG-level
    double-proposal detector can see it."""
    block = StatementBlock.from_bytes(raw)
    variant = StatementBlock.build(
        block.author(),
        block.round(),
        block.includes,
        block.statements,
        meta_creation_time_ns=block.meta_creation_time_ns ^ 1,
        epoch_marker=block.epoch_marker,
        epoch=block.epoch,
        signer=signer,
    )
    assert variant.reference.digest != block.reference.digest
    return variant.to_bytes()


def _block_frames(msg):
    """(kind, payload tuple) when ``msg`` carries serialized blocks."""
    if type(msg) is EncodedFrame:
        msg = msg.message
    if isinstance(msg, (Blocks, RequestBlocksResponse)):
        return msg
    return None


def _rebuild(msg, payload: Tuple[bytes, ...]):
    """Same message type, new block payload (stamps preserved)."""
    if isinstance(msg, TimestampedBlocks):
        return TimestampedBlocks(
            payload,
            sent_monotonic_ns=msg.sent_monotonic_ns,
            sent_wall_ns=msg.sent_wall_ns,
        )
    if isinstance(msg, Blocks):
        return Blocks(payload)
    return RequestBlocksResponse(payload)


def _block_author_round(raw) -> Tuple[int, int]:
    """Author/round of a serialized block without a full decode (the wire
    layout leads with both u64s)."""
    return _U64X2.unpack_from(raw, 0)


class AdversaryEngine:
    """Rewrites adversary nodes' outbound traffic per their specs.

    Mounted by the :class:`~mysticeti_tpu.chaos.ChaosEngine`: its
    ``filter_batch`` routes every (src, dst, batch) through
    :meth:`transform` BEFORE the benign link faults, so Byzantine behavior
    composes with drops/partitions/crashes in one plan.  All state
    (variant cache, favored subsets, RNG) is deterministic from the plan.
    """

    def __init__(
        self,
        specs: Sequence[AdversarySpec],
        signers: Sequence[crypto.Signer],
        n: int,
        seed: int = 0,
    ) -> None:
        self.specs = list(specs)
        self.signers = signers
        self.n = n
        self.ledger = AttackLedger()
        self._rng = random.Random((seed << 2) ^ 0xBAD5EED)
        self._by_node: Dict[int, List[AdversarySpec]] = {}
        for spec in self.specs:
            self._by_node.setdefault(spec.node, []).append(spec)
        # Equivocation variants: raw bytes -> variant bytes (one mint per
        # distinct own block, logged once).
        self._variants: Dict[bytes, bytes] = {}
        # Tampered-signature twins, same caching.
        self._tampered: Dict[bytes, bytes] = {}

    @property
    def adversaries(self) -> Set[int]:
        return set(self._by_node)

    # -- per-behavior peer subsets (pure functions of the spec) --

    def _peers(self, node: int) -> List[int]:
        return [a for a in range(self.n) if a != node]

    def _variant_side(self, node: int) -> Set[int]:
        """The disjoint subset that receives the equivocating variant: the
        upper half of the peer list (a fixed, seed-independent split — the
        schedule must be a pure function of the plan)."""
        peers = self._peers(node)
        return set(peers[len(peers) // 2 :])

    def _favored(self, node: int, keep: int) -> Set[int]:
        return set(self._peers(node)[: max(0, keep)])

    # -- the transform --

    def transform(
        self, src: int, dst: int, batch: list, t: float
    ) -> List[Tuple[float, list]]:
        """One outbound batch src->dst at sim time ``t`` -> delay groups
        ``[(extra_delay_s, messages), ...]`` (the fault-injector group
        shape).  Untouched messages keep their original objects, so the
        sim's zero-serialization EncodedFrame delivery is unchanged when
        no behavior fires."""
        specs = [s for s in self._by_node.get(src, []) if s.active(t)]
        if not specs:
            return [(0.0, batch)]
        on_time: list = []
        delayed: List[Tuple[float, list]] = []
        for msg in batch:
            out_msg, delay = self._transform_message(src, dst, msg, specs)
            if out_msg is None:
                continue
            if delay > 0.0:
                delayed.append((delay, [out_msg]))
            else:
                on_time.append(out_msg)
        out: List[Tuple[float, list]] = []
        if on_time:
            out.append((0.0, on_time))
        out.extend(delayed)
        return out

    def _transform_message(self, src, dst, msg, specs):
        """-> (message or None to drop, extra delay)."""
        delay = 0.0
        for spec in specs:
            if msg is None:
                break
            behavior = spec.behavior
            if behavior == "mangle":
                p = spec.param("mangle_p", DEFAULT_MANGLE_P)
                if self._rng.random() < p:
                    garbage = bytes(
                        self._rng.getrandbits(8) for _ in range(40)
                    )
                    self.ledger.note("mangle", node=src, dst=dst)
                    msg = Blocks((garbage,))
                continue
            frame = _block_frames(msg)
            if frame is None:
                continue
            own = [
                i for i, raw in enumerate(frame.blocks)
                if _block_author_round(raw)[0] == src
            ]
            if not own:
                continue
            if behavior == "withhold":
                keep = int(spec.param("keep", DEFAULT_WITHHOLD_KEEP))
                if dst in self._favored(src, keep):
                    continue
                kept = tuple(
                    raw for i, raw in enumerate(frame.blocks) if i not in own
                )
                self.ledger.note(
                    "withhold", node=src, dst=dst, blocks=len(own)
                )
                msg = _rebuild(frame, kept) if kept else None
            elif behavior == "equivocate":
                if dst not in self._variant_side(src):
                    continue
                payload = list(frame.blocks)
                for i in own:
                    raw = bytes(payload[i])
                    variant = self._variants.get(raw)
                    if variant is None:
                        variant = equivocating_variant(raw, self.signers[src])
                        self._variants[raw] = variant
                        self.ledger.note(
                            "equivocate-mint", node=src,
                            round=_block_author_round(raw)[1],
                        )
                    payload[i] = variant
                self.ledger.note(
                    "equivocate", node=src, dst=dst, blocks=len(own)
                )
                msg = _rebuild(frame, tuple(payload))
            elif behavior == "invalid_sig":
                payload = list(frame.blocks)
                for i in own:
                    raw = bytes(payload[i])
                    tampered = self._tampered.get(raw)
                    if tampered is None:
                        tampered = tamper_signature(raw)
                        self._tampered[raw] = tampered
                    payload[i] = tampered
                self.ledger.note(
                    "invalid_sig", node=src, dst=dst, blocks=len(own)
                )
                msg = _rebuild(frame, tuple(payload))
            elif behavior == "lag":
                lag = spec.param("lag_s", DEFAULT_LAG_S)
                self.ledger.note(
                    "lag", node=src, dst=dst, blocks=len(own), delay_s=lag
                )
                delay = max(delay, lag)
        return msg, delay
