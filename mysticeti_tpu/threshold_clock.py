"""Threshold clock: round advancement gated on a quorum of previous-round blocks.

Capability parity with ``mysticeti-core/src/threshold_clock.rs``:

* ``threshold_clock_valid_non_genesis`` (threshold_clock.rs:12-35) — a non-genesis
  block is valid iff all includes are from lower rounds AND the authorities of its
  includes at exactly round-1 hold quorum stake.
* ``ThresholdClockAggregator`` (threshold_clock.rs:37-94) — tracks the highest round
  for which we have seen 2f+1 stake of blocks; seeing quorum at the current round
  advances the clock to round+1.
"""
from __future__ import annotations

import time
from typing import Optional

from .committee import Committee, QUORUM, StakeAggregator
from .types import BlockReference, RoundNumber, StatementBlock


def threshold_clock_valid_non_genesis(block: StatementBlock, committee: Committee) -> bool:
    round_number = block.round()
    assert round_number > 0
    for include in block.includes:
        if include.round >= round_number:
            return False
    aggregator = StakeAggregator(QUORUM)
    is_quorum = False
    for include in block.includes:
        if include.round == round_number - 1:
            is_quorum = aggregator.add(include.authority, committee)
    return is_quorum


class ThresholdClockAggregator:
    __slots__ = ("aggregator", "round", "last_quorum_ts", "_observe_quorum_latency")

    def __init__(self, round_: RoundNumber, metrics=None) -> None:
        self.aggregator = StakeAggregator(QUORUM)
        self.round = round_
        self.last_quorum_ts = time.monotonic()
        self._observe_quorum_latency = (
            metrics.quorum_receive_latency.observe if metrics is not None else None
        )

    def add_block(self, block: BlockReference, committee: Committee) -> None:
        if block.round < self.round:
            return  # stale
        if block.round > self.round:
            # Having processed a round-r block implies 2f+1 blocks at r-1 are stored.
            self.aggregator.clear()
            self.aggregator.add(block.authority, committee)
            self.round = block.round
        else:
            if self.aggregator.add(block.authority, committee):
                self.aggregator.clear()
                self.round = block.round + 1
                now = time.monotonic()
                if self._observe_quorum_latency is not None:
                    self._observe_quorum_latency(now - self.last_quorum_ts)
                self.last_quorum_ts = now

    def get_round(self) -> RoundNumber:
        return self.round
