"""Node assembly: storage, handlers, network, syncer — the whole validator.

Capability parity with ``mysticeti-core/src/validator.rs``:

* ``Validator.start_benchmarking`` (:78-163) — benchmark fast-path handler +
  open-loop generator + TestCommitObserver + metrics endpoint.
* ``Validator.start_production`` (:165-212) — SimpleBlockHandler (application
  submits raw transactions, acked on proposal) + SimpleCommitObserver
  (sub-dags to a consumer queue with replay above last_sent_height).
* ``init_storage`` (:334-352) — WAL + BlockStore recovery.
* ``CommitConsumer`` (:50-66) — the application-facing commit stream handle.

TPU addition (the point of this framework): ``verifier=`` selects the signature
backend — "tpu" routes block verification through the batched JAX kernel
(block_validator.py), "cpu" uses the serial OpenSSL oracle (reference
behavior), "accept" skips signature checks (the reference's default
AcceptAllBlockVerifier wiring, validator.rs:137).
"""
from __future__ import annotations

import asyncio
import os
from typing import List, Optional, Tuple

from .block_handler import BenchmarkFastPathBlockHandler, SimpleBlockHandler
from .block_validator import (
    AcceptAllBlockVerifier,
    BatchedSignatureVerifier,
    CpuSignatureVerifier,
    HybridSignatureVerifier,
    TpuSignatureVerifier,
)
from .commit_observer import SimpleCommitObserver, TestCommitObserver
from .committee import Committee
from .config import Parameters, PrivateConfig
from .core import Core, CoreOptions
from .crypto import Signer
from .flight_recorder import FlightRecorder, path_from_env
from .health import HealthProbe, SLOThresholds
from .ingress import IngressGateway, IngressPlane
from .metrics import MetricReporter, Metrics, serve_metrics
from .net_sync import NetworkSyncer
from .tracing import current_authority, logger, setup_logging
from .network import TcpNetwork

log = logger(__name__)
from .transactions_generator import TransactionGenerator


class CommitConsumer:
    """Application handle for consuming committed sub-dags (validator.rs:50-66)."""

    def __init__(self, last_sent_height: int = 0) -> None:
        self.queue: asyncio.Queue = asyncio.Queue()
        self.last_sent_height = last_sent_height

    def send(self, sub_dag) -> None:
        self.queue.put_nowait(sub_dag)


def _make_verifier(kind: str, committee: Committee, metrics=None):
    """Signature verification is ON by default (the reference always verifies
    Ed25519 on every received block, types.rs:315-347 via net_sync.rs:352-372);
    "accept" is an explicit consensus-only escape hatch, not a default.

    The returned verifier carries a ``ready`` threading.Event: set once its
    one-time warmup is done (immediately for cpu/accept; after the JAX
    trace/compile for tpu).  Load generators gate on it."""
    import threading

    ready = threading.Event()
    aggregate = kind.endswith("-agg")
    if aggregate:
        kind = kind[: -len("-agg")]
    # Collection window (ms).  The same small default applies in aggregate
    # mode: a wide window would pace round advance (verification sits on the
    # round-advance critical path), costing more cadence than the skips
    # recover at steady state.  Aggregation instead engages through
    # BACKPRESSURE — when the verifier lags the arrival rate (catch-up
    # bursts, a recovering node's backlog, saturation), pending deepens,
    # flushes span many rounds from every peer, and quorum-endorsed interiors
    # skip their dispatch: a self-relieving valve exactly where verification
    # binds, at zero steady-state cost.
    window_ms = float(os.environ.get("MYSTICETI_VERIFY_WINDOW_MS", "5"))
    # Staged dispatch pipeline depth (verify_pipeline.py): default adapts to
    # the router's measured fixed dispatch cost; pin it for experiments.
    depth_env = os.environ.get("MYSTICETI_VERIFY_PIPELINE_DEPTH")
    collector_opts = dict(
        metrics=metrics,
        aggregate=aggregate,
        max_delay_s=window_ms / 1e3,
        pipeline_depth=int(depth_env) if depth_env else None,
    )
    if kind in ("tpu", "tpu-only"):
        committee_keys = committee.public_key_bytes()
        if os.environ.get("MYSTICETI_VERIFIER_SOCKET"):
            # Shared per-host verifier service: the accelerator runtime is a
            # HOST resource — one warmed PJRT client serving every co-located
            # validator (verifier_service.py).  This process never imports
            # jax: boot is import-light and a rebooted node re-attaches to
            # the warm service instead of re-paying a cold runtime.
            from .verifier_service import RemoteSignatureVerifier

            tpu_backend = RemoteSignatureVerifier(
                committee_keys=committee_keys, metrics=metrics
            )
        else:
            tpu_backend = TpuSignatureVerifier(committee_keys=committee_keys)
            if metrics is not None:
                # In-process JAX: wire device-side attribution (compile
                # events, cache hits/misses, transfer bytes) into this
                # node's registry.  Service-socket validators skip this —
                # their process never imports jax.
                from .ops import ed25519 as _ed25519

                _ed25519.install_device_attribution(metrics)
        # "tpu" deploys the hybrid dispatch policy (small batches take the
        # CPU oracle, sparing them the accelerator round-trip — SURVEY §7
        # hard part #2); "tpu-only" pins every batch to the kernel, which is
        # what a saturation benchmark wants to measure.
        backend = (
            tpu_backend
            if kind == "tpu-only"
            else HybridSignatureVerifier(tpu=tpu_backend, metrics=metrics)
        )

        def _warm() -> None:
            # Pay the JAX trace/compile (or cache load) off the hot path:
            # blocks arriving during warmup queue in the batching collector.
            try:
                backend.warmup()
            finally:
                ready.set()

        threading.Thread(target=_warm, daemon=True, name="verifier-warmup").start()
        verifier = BatchedSignatureVerifier(committee, backend, **collector_opts)
    elif kind == "cpu":
        ready.set()
        verifier = BatchedSignatureVerifier(
            committee, CpuSignatureVerifier(), **collector_opts
        )
    elif kind == "accept":
        ready.set()
        verifier = AcceptAllBlockVerifier()
    else:
        raise ValueError(f"unknown verifier kind {kind!r}")
    verifier.ready = ready
    return verifier


class Validator:
    # Production health-probe cadence (seconds between samples).
    HEALTH_INTERVAL_S = 5.0

    def __init__(self) -> None:
        self.network_syncer: Optional[NetworkSyncer] = None
        self.metrics: Optional[Metrics] = None
        self.reporter: Optional[MetricReporter] = None
        self.generator: Optional[TransactionGenerator] = None
        self._metrics_server = None
        self.core: Optional[Core] = None
        self.health: Optional[HealthProbe] = None
        self.recorder: Optional[FlightRecorder] = None
        self.ingress: Optional[IngressPlane] = None
        self.gateway: Optional[IngressGateway] = None
        self.host_monitor = None

    def _make_recorder(self, authority: int, lifecycle, observer):
        """The always-on flight recorder: ring in memory unconditionally,
        on-disk dumps when ``MYSTICETI_FLIGHT_RECORDER`` names a path."""
        recorder = FlightRecorder(
            authority=authority,
            dump_path=path_from_env(authority),
            metrics=self.metrics,
        )
        if lifecycle is not None:
            lifecycle.recorder = recorder
        observer.recorder = recorder
        self.recorder = recorder
        return recorder

    def _start_health(self, authority, committee, observer, block_verifier):
        """Wire the fleet health plane: probe + SLO watchdog + (when span
        tracing is active) commit critical-path attribution + the host
        attribution plane (hostattr.py: loop-lag probe, blocking-call
        detector, GIL convoy estimate)."""
        from . import profiling, spans
        from .hostattr import HostMonitor

        probe = HealthProbe(
            authority,
            len(committee),
            metrics=self.metrics,
            slo=SLOThresholds(
                max_round_stall_s=float(
                    os.environ.get("MYSTICETI_SLO_ROUND_STALL_S", "30")
                ),
                max_authority_lag_rounds=int(
                    os.environ.get("MYSTICETI_SLO_AUTHORITY_LAG", "100")
                ),
                max_breaker_open_fraction=0.5,
                max_loop_lag_s=float(
                    os.environ.get("MYSTICETI_SLO_LOOP_LAG_S", "0.25")
                ),
                max_blocking_call_ms=float(
                    os.environ.get("MYSTICETI_SLO_BLOCKING_CALL_MS", "50")
                ),
                max_finality_p99_s=float(
                    os.environ.get("MYSTICETI_SLO_FINALITY_P99_S", "5")
                ),
            ),
            recorder=self.recorder,
        )
        monitor = HostMonitor(
            metrics=self.metrics, recorder=self.recorder
        ).start()
        self.host_monitor = monitor
        if self.network_syncer is not None:
            # Every synchronous core command reports its wall duration to
            # the blocking-call detector (core_task.py).
            self.network_syncer.dispatcher.blocking_monitor = monitor
        probe.attach(
            core=self.core,
            net_syncer=self.network_syncer,
            block_verifier=block_verifier,
            commit_observer=observer,
            host_monitor=monitor,
        )
        # Normalize the sampler's per-subsystem CPU seconds by committed
        # leaders (mysticeti_cpu_us_per_leader) when MYSTICETI_PROFILE has
        # an accountant running.
        interpreter = getattr(observer, "commit_interpreter", None)
        profiling.bind_active(
            self.metrics,
            leaders_fn=(
                (lambda: interpreter.last_height)
                if interpreter is not None
                else None
            ),
        )
        tracer = spans.active()
        if tracer is not None:
            probe.attach_critical_path(tracer)
        self.health = probe.start(self.HEALTH_INTERVAL_S)

    # -- storage (validator.rs:334-352 + the storage lifecycle plane) --

    @staticmethod
    def init_storage(
        authority: int,
        committee: Committee,
        private: PrivateConfig,
        parameters: Optional[Parameters] = None,
        metrics=None,
    ):
        """Segmented WAL + checkpoint-seeded recovery (storage.py): boots
        from the newest valid checkpoint and replays only what follows it.
        Returns ``(recovered, observer_recovered, wal_writer, lifecycle)``."""
        from .storage import open_store

        return open_store(
            authority, private.wal(), committee, parameters, metrics
        )

    # -- benchmarking node (validator.rs:78-163) --

    @classmethod
    async def start_benchmarking(
        cls,
        authority: int,
        committee: Committee,
        parameters: Parameters,
        private: PrivateConfig,
        signer: Optional[Signer] = None,
        tps: Optional[int] = None,
        transaction_size: int = 512,
        verifier: str = "cpu",
        serve_metrics_endpoint: bool = True,
        network: Optional[object] = None,
    ) -> "Validator":
        v = cls()
        setup_logging()
        current_authority.set(authority)
        log.info("starting benchmarking validator %d (verifier=%s)", authority, verifier)
        v.metrics = Metrics()
        (recovered, observer_recovered, wal_writer, lifecycle) = cls.init_storage(
            authority, committee, private, parameters, v.metrics
        )
        # Overload-resilient ingress plane (ingress.py): every submission —
        # generator or gateway client — runs through the admission-controlled
        # mempool; proposals drain weighted-round-robin from it.
        plane = (
            IngressPlane(parameters.ingress, authority=authority,
                         metrics=v.metrics)
            if parameters.ingress.enabled
            else None
        )
        handler = BenchmarkFastPathBlockHandler(
            committee,
            authority,
            certified_log_path=private.certified_transactions_log(),
            block_store=recovered.block_store,
            metrics=v.metrics,
            ingress=plane,
        )
        core = Core(
            block_handler=handler,
            authority=authority,
            committee=committee,
            parameters=parameters,
            recovered=recovered,
            wal_writer=wal_writer,
            # Reference benchmarking uses CoreOptions::default() (fsync=false,
            # validator.rs:247): durability rides the 1 s WAL-sync thread.
            options=CoreOptions(fsync=False),
            signer=signer,
            metrics=v.metrics,
            storage=lifecycle,
        )
        v.core = core
        observer = TestCommitObserver(
            core.block_store,
            committee,
            transaction_time=handler.transaction_time,
            metrics=v.metrics,
            recovered_state=observer_recovered,
        )
        tps = tps if tps is not None else int(os.environ.get("TPS", "10"))
        transaction_size = int(
            os.environ.get("TRANSACTION_SIZE", str(transaction_size))
        )
        recorder = v._make_recorder(authority, lifecycle, observer)
        # Equivocation detection events (block_store.py) ride the ring too,
        # as do decision-skip/flip events from the commit-rule ledger.
        core.block_store.recorder = recorder
        core.committer.ledger.recorder = recorder
        block_verifier = _make_verifier(verifier, committee, v.metrics)
        # Overload modes (tools/overload_bench.py drives these through the
        # environment): an offered-load multiplier schedule and a closed
        # loop that consumes the ingress plane's SHED/retry-after verdicts.
        from .transactions_generator import parse_overload_schedule

        schedule_env = os.environ.get("MYSTICETI_OVERLOAD_SCHEDULE")
        v.generator = TransactionGenerator(
            submit=handler.submit,
            seed=authority,
            tps=tps,
            transaction_size=transaction_size,
            initial_delay_s=float(os.environ.get("INITIAL_DELAY", "2")),
            ready=block_verifier.ready.is_set,
            overload_schedule=(
                parse_overload_schedule(schedule_env) if schedule_env else None
            ),
            closed_loop=(
                os.environ.get("MYSTICETI_CLOSED_LOOP", "") == "1"
                and plane is not None
            ),
            # Client-observed finality: armed whenever the server-side
            # tracker runs (or forced via MYSTICETI_CLIENT_FINALITY=1), with
            # the same content-based sampling stride so both sides measure
            # the same transactions.
            finality_sample_every=(
                parameters.ingress.finality_sample_every
                if plane is not None
                and (
                    plane.finality is not None
                    or os.environ.get("MYSTICETI_CLIENT_FINALITY", "") == "1"
                )
                else 0
            ),
            metrics=v.metrics,
        )
        if network is None:
            network = await TcpNetwork.start(
                authority,
                parameters.all_network_addresses(),
                metrics=v.metrics,
                max_latency_s=parameters.network_connection_max_latency_s,
            )
        v.network_syncer = NetworkSyncer(
            core,
            observer,
            network,
            parameters=parameters,
            block_verifier=block_verifier,
            metrics=v.metrics,
            start_wal_sync_thread=True,
            recorder=recorder,
        )
        await v.network_syncer.start()
        v.generator.start()
        v.reporter = MetricReporter(v.metrics).start()
        v._start_health(authority, committee, observer, block_verifier)
        if plane is not None:
            plane.recorder = recorder
            observer.ingress = plane
            plane.attach(
                core=core,
                net_syncer=v.network_syncer,
                block_verifier=block_verifier,
                health=v.health,
            )
            if v.health is not None:
                v.health.attach(ingress=plane)
            if v.generator.finality is not None:
                # The loopback notification path: commit sinks fire on the
                # loop thread, same thread the generator stamps on.
                plane.add_commit_sink(
                    lambda height, keys, info, g=v.generator: (
                        g.note_commit_notification(keys, info)
                    )
                )
            v.ingress = plane.start()
            if parameters.ingress.gateway_port_base:
                v.gateway = await IngressGateway(
                    plane,
                    "0.0.0.0",
                    parameters.ingress.gateway_port_base + authority,
                ).start()
        if serve_metrics_endpoint and parameters.identifiers:
            host, port = parameters.metrics_address(authority)
            v._metrics_server = await serve_metrics(
                v.metrics, "0.0.0.0", port, health_probe=v.health,
                flight_recorder=recorder,
                consensus_debug=v._consensus_debug_doc,
            )
        return v

    def _consensus_debug_doc(self) -> dict:
        """The live ``/debug/consensus`` document: DAG frontier, undecided
        slots, threshold-clock state, and the last-K decision records."""
        core = self.core
        store = core.block_store
        ledger = core.committer.ledger
        state = ledger.state()
        return {
            "authority": core.authority,
            "threshold_clock_round": core.current_round(),
            "last_decided": repr(core.last_decided_leader),
            "highest_round": store.highest_round(),
            "frontier": {
                str(a): store.last_seen_by_authority(a)
                for a in range(len(core.committee))
            },
            "undecided": state["undecided"],
            "recorded": state["recorded"],
            "dropped": state["dropped"],
            "ledger_digest": ledger.digest(),
            "records": ledger.records(64),
            **(
                {"execution": core.execution.state()}
                if core.execution is not None
                else {}
            ),
        }

    # -- production node (validator.rs:165-212) --

    @classmethod
    async def start_production(
        cls,
        authority: int,
        committee: Committee,
        parameters: Parameters,
        private: PrivateConfig,
        signer: Optional[Signer] = None,
        commit_consumer: Optional[CommitConsumer] = None,
        verifier: str = "tpu",
        network: Optional[object] = None,
    ) -> Tuple["Validator", SimpleBlockHandler, CommitConsumer]:
        v = cls()
        setup_logging()
        current_authority.set(authority)
        log.info("starting production validator %d (verifier=%s)", authority, verifier)
        v.metrics = Metrics()
        (recovered, observer_recovered, wal_writer, lifecycle) = cls.init_storage(
            authority, committee, private, parameters, v.metrics
        )
        handler = SimpleBlockHandler()
        core = Core(
            block_handler=handler,
            authority=authority,
            committee=committee,
            parameters=parameters,
            recovered=recovered,
            wal_writer=wal_writer,
            options=CoreOptions.production(),
            signer=signer,
            metrics=v.metrics,
            storage=lifecycle,
        )
        v.core = core
        consumer = commit_consumer or CommitConsumer()
        observer = SimpleCommitObserver(
            core.block_store,
            consumer.send,
            last_sent_height=consumer.last_sent_height,
            recovered_state=observer_recovered,
            metrics=v.metrics,
        )
        if network is None:
            network = await TcpNetwork.start(
                authority,
                parameters.all_network_addresses(),
                metrics=v.metrics,
                max_latency_s=parameters.network_connection_max_latency_s,
            )
        recorder = v._make_recorder(authority, lifecycle, observer)
        core.block_store.recorder = recorder
        core.committer.ledger.recorder = recorder
        block_verifier = _make_verifier(verifier, committee, v.metrics)
        v.network_syncer = NetworkSyncer(
            core,
            observer,
            network,
            parameters=parameters,
            block_verifier=block_verifier,
            metrics=v.metrics,
            start_wal_sync_thread=True,
            recorder=recorder,
        )
        await v.network_syncer.start()
        v.reporter = MetricReporter(v.metrics).start()
        v._start_health(authority, committee, observer, block_verifier)
        return v, handler, consumer

    async def stop(self) -> None:
        if self.generator is not None:
            self.generator.stop()
        if self.gateway is not None:
            await self.gateway.stop()
        if self.ingress is not None:
            self.ingress.stop()
        if self.reporter is not None:
            # Final percentile sweep: an orderly shutdown publishes the tail
            # window instead of losing everything since the last 60 s tick.
            self.reporter.stop(final=True)
        if self.health is not None:
            self.health.stop()
        if self.host_monitor is not None:
            self.host_monitor.stop()
        if self._metrics_server is not None:
            self._metrics_server.close()
        if self.network_syncer is not None:
            await self.network_syncer.stop()
        # Span-trace tail: the periodic flusher runs every few seconds, so a
        # short run stopped between flushes would lose its newest spans.
        from . import spans

        spans.flush_active()
        # Flight-recorder tail: SIGTERM routes here too (the node CLI's
        # handler), so an operator-stopped node always leaves its incident
        # ring on disk when MYSTICETI_FLIGHT_RECORDER is set.
        if self.recorder is not None and self.recorder.dump_path:
            self.recorder.dump("shutdown")
        if self.core is not None:
            self.core.wal_writer.close()
            # Release the WAL reader too (fd + whole-file mmap): embeddings
            # that cycle validators in one process would otherwise leak one
            # of each per stop.
            self.core.block_store.close()

    def committed_leaders(self) -> List:
        observer = self.network_syncer.syncer.commit_observer
        return list(getattr(observer, "committed_leaders", []))
