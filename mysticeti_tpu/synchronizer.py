"""Block dissemination (push) and missing-block fetching (pull).

Capability parity with ``mysticeti-core/src/synchronizer.rs``:

* ``BlockDisseminator`` (:25-164) — per-peer push stream of own blocks, batched
  (default 100), woken by the block-ready signal; answers explicit
  ``RequestBlocks`` with chunks + ``BlockNotFound``.
* ``BlockFetcher`` (:216-407) — every ``sample_precision`` asks the core for
  missing references and requests them (≤ MAXIMUM_BLOCK_REQUEST) from a
  latency-weighted random peer (:376-406).
"""
from __future__ import annotations

import asyncio
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

from .block_store import BlockStore
from .config import SynchronizerParameters
from .core_task import CoreTaskDispatcher
from .tracing import logger
from .utils.tasks import spawn_logged
from .network import (
    BlockNotFound,
    Blocks,
    Connection,
    EncodedFrame,
    RequestBlocks,
    RequestBlocksResponse,
    TimestampedBlocks,
)
from .types import BlockReference, RoundNumber


log = logger(__name__)

MAXIMUM_BLOCK_REQUEST = 50  # net_sync.rs:30
DISSEMINATION_CHUNK = 10  # synchronizer.rs:74 send_blocks chunking


class FrameCache:
    """Encode-once fan-out: one built push frame per (stream, cursor).

    Every ``BlockDisseminator`` of a node shares one FrameCache.  A push
    stream about to send from cursor ``c`` first asks the cache: if another
    subscriber already built the frame for the same stream at the same
    cursor (and no new block has landed since — entries are keyed by the
    ``block_ready`` notify GENERATION, so any store change invalidates by
    key), it ships the identical immutable :class:`EncodedFrame` object —
    N-1 subscribers at one cursor cost 1 store read + 1 serialization
    instead of N.  Per-peer cursors are untouched: the cache only
    deduplicates the (store read, message build, wire encode) work, never
    the stream positions.

    Entries are LRU-bounded (``CAPACITY``): a fleet's subscribers cluster
    at the live frontier, so the working set is a handful of cursors; a
    straggler at an old cursor simply rebuilds (a miss is the pre-cache
    behavior, never an error).  ``dissemination_encode_reuse_total`` counts
    the saved builds; the census test pins N subscribers → 1 build +
    N-1 reuses.

    Thread discipline: all access is on the event loop today, but the
    entry table follows the repo's lock rule anyway (`_frame_entries` mutations
    under ``_frame_lock`` — enforced by the static lint's GUARDED_FIELDS).
    """

    CAPACITY = 64
    # Reuse window for STAMPED frames (timestamp_frames on): a cached
    # TimestampedBlocks carries its build-time sender clocks, and on a
    # quiet network the generation key never advances — without an age
    # bound, a late (re)subscriber at an old cursor would receive a frame
    # stamped arbitrarily earlier and the receiver would record the cache
    # AGE as wire transit, poisoning dissemination_transit_seconds and the
    # fleet-trace skew estimator.  Same-wake subscribers share well inside
    # this window; anything older rebuilds with fresh stamps.  Clocked by
    # the runtime clock, so seeded sims stay deterministic.
    STAMPED_REUSE_WINDOW_S = 0.025

    def __init__(self, metrics=None) -> None:
        self.metrics = metrics
        self._frame_lock = threading.Lock()
        self._frame_entries: "OrderedDict[tuple, tuple]" = OrderedDict()
        # Census counters (tests + the A/B artifact read these directly;
        # the prometheus series mirrors reuses).
        self.builds = 0
        self.reuses = 0

    def get(self, key: tuple, max_age_s: Optional[float] = None) -> Optional[tuple]:
        """The cached ``(frame, to_cursor, block_count)`` for ``key``, or
        None; a hit counts one saved encode.  ``max_age_s`` expires entries
        older than the window (stamped frames) — an expired entry is
        dropped and the caller rebuilds."""
        with self._frame_lock:
            cached = self._frame_entries.get(key)
            if cached is None:
                return None
            entry, built_at = cached
            if max_age_s is not None:
                from .runtime import now as runtime_now

                if runtime_now() - built_at > max_age_s:
                    del self._frame_entries[key]
                    return None
            self._frame_entries.move_to_end(key)
            self.reuses += 1
        if self.metrics is not None:
            self.metrics.dissemination_encode_reuse_total.inc()
        return entry

    def put(self, key: tuple, entry: tuple) -> None:
        from .runtime import now as runtime_now

        with self._frame_lock:
            self.builds += 1
            self._frame_entries[key] = (entry, runtime_now())
            self._frame_entries.move_to_end(key)
            while len(self._frame_entries) > self.CAPACITY:
                self._frame_entries.popitem(last=False)


class BlockDisseminator:
    """Serves one peer connection (synchronizer.rs:25-164)."""

    def __init__(
        self,
        connection: Connection,
        block_store: BlockStore,
        block_ready,  # Notify (net_sync.py): lost-wakeup-free level trigger
        parameters: Optional[SynchronizerParameters] = None,
        metrics=None,
        frame_cache: Optional[FrameCache] = None,
    ) -> None:
        self.connection = connection
        self.block_store = block_store
        self.block_ready = block_ready
        self.parameters = parameters or SynchronizerParameters()
        self.metrics = metrics
        # Encode-once fan-out: shared across the node's disseminators by
        # NetworkSyncer; None (direct construction, MYSTICETI_MESH_LEGACY)
        # keeps the per-peer build path.
        self.frame_cache = frame_cache
        self._stream_task: Optional[asyncio.Task] = None
        # Helper streams (synchronizer.rs:169-205, dormant in the reference;
        # live here behind SynchronizerParameters.disseminate_others_blocks):
        # one relay task per requested authority, serving OUR stored copies
        # of that authority's blocks to a peer that lost its direct
        # connection.  Tests/telemetry read helper_blocks_sent to tell relay
        # traffic from the own-block stream.
        self._helper_tasks: Dict[int, asyncio.Task] = {}
        self.helper_blocks_sent = 0
        # True once any relay stream was requested on this connection: the
        # receive path then wakes the streams on freshly STORED peer blocks
        # (block_ready otherwise fires only on own proposals, which would
        # delay every relayed block by up to a round — always just behind
        # the children that reference it).
        self.relay_serving = False
        # Snapshot catch-up stream (storage.py): one-shot push of the whole
        # retained block window to a far-behind peer that adopted our
        # manifest; counters feed the catch-up artifact/telemetry.
        self._snapshot_task: Optional[asyncio.Task] = None
        self.snapshot_blocks_sent = 0
        self.snapshot_bytes_sent = 0

    def _blocks_message(self, payload) -> Blocks:
        """Push-frame constructor: plain ``Blocks``, or — when the
        ``timestamp_frames`` knob is on — a :class:`TimestampedBlocks`
        stamped with the sender's runtime+wall clocks (both virtual under
        the deterministic simulator, so stamped sims stay reproducible)."""
        if not self.parameters.timestamp_frames:
            return Blocks(payload)
        from .runtime import now as runtime_now, timestamp_utc

        return TimestampedBlocks(
            payload,
            sent_monotonic_ns=int(runtime_now() * 1e9),
            sent_wall_ns=int(timestamp_utc() * 1e9),
        )

    def subscribe_own_from(self, from_round: RoundNumber) -> None:
        """Peer asked for our blocks starting after ``from_round``."""
        if self._stream_task is not None:
            self._stream_task.cancel()
        self._stream_task = spawn_logged(self._stream_own(from_round), log)

    def subscribe_others_from(
        self, authority: int, from_round: RoundNumber
    ) -> None:
        """Peer asked us to relay ``authority``'s blocks (helper stream).

        One stream per requested authority (a re-subscribe replaces it —
        same replace-on-resubscribe contract as the own-block stream), with
        the serving side bounded by ``absolute_maximum_helpers`` so a
        misbehaving peer cannot fan one connection out into a store-scan
        per committee member."""
        existing = self._helper_tasks.pop(authority, None)
        if existing is not None:
            existing.cancel()
        self.relay_serving = True
        live = sum(1 for t in self._helper_tasks.values() if not t.done())
        if live >= self.parameters.absolute_maximum_helpers:
            log.warning(
                "refusing helper stream for authority %d: %d already live",
                authority, live,
            )
            return
        self._helper_tasks[authority] = spawn_logged(
            self._stream_others(authority, from_round), log
        )

    def _push_frame(
        self, kind: str, authority: Optional[int], cursor: RoundNumber
    ) -> Tuple[Optional[EncodedFrame], RoundNumber, int]:
        """One dissemination push frame from ``cursor``: ``(frame,
        new_cursor, block_count)``, with ``frame=None`` when the store has
        nothing past the cursor.

        Encode-once fan-out: when the shared :class:`FrameCache` is wired,
        subscribers at the same (stream, cursor, notify generation) receive
        the IDENTICAL immutable frame object — the store read, the message
        build, and (on the TCP transport) the wire serialization happen
        once per frame instead of once per peer.  The notify generation in
        the key self-invalidates on every new block, so a cached frame can
        never mask store changes; per-peer cursors advance exactly as the
        uncached path would."""
        cache = self.frame_cache
        gen = getattr(self.block_ready, "generation", None)
        key = None
        if cache is not None and gen is not None:
            key = (
                kind, authority, cursor, self.parameters.batch_size,
                self.parameters.timestamp_frames, gen,
            )
            hit = cache.get(
                key,
                max_age_s=(
                    cache.STAMPED_REUSE_WINDOW_S
                    if self.parameters.timestamp_frames
                    else None
                ),
            )
            if hit is not None:
                return hit
        if kind == "own":
            blocks = self.block_store.get_own_blocks(
                cursor, self.parameters.batch_size
            )
        else:
            blocks = self.block_store.get_others_blocks(
                cursor, authority, self.parameters.batch_size
            )
        if not blocks:
            return None, cursor, 0
        to_cursor = max(b.round() for b in blocks)
        # The frame payload stays LAZY (EncodedFrame builds it on first
        # wire access via network.encode_message): the sim delivers the
        # message object and never serializes, while the TCP write path
        # gets the native whole-frame encode (encode_blocks_frame — one
        # GIL-released call per fan-out frame) when the extension is
        # present, the Writer loop otherwise.  Byte-identical either way.
        frame = EncodedFrame(
            self._blocks_message(tuple(b.to_bytes() for b in blocks))
        )
        entry = (frame, to_cursor, len(blocks))
        if key is not None:
            cache.put(key, entry)
        return entry

    def relayed_authorities(self) -> List[int]:
        """Authorities with a LIVE relay stream on this connection (the
        receive path wakes streams only for batches carrying their
        blocks)."""
        return [
            authority
            for authority, task in self._helper_tasks.items()
            if not task.done()
        ]

    async def _stream_others(
        self, authority: int, from_round: RoundNumber
    ) -> None:
        """Relay loop: same batch/wake cadence as ``_stream_own`` but walks
        the store's others-blocks cursor — the peer verifies and re-hashes
        every relayed block (wire-format §5), so a relay cannot forge."""
        cursor = from_round
        while not self.connection.is_closed():
            waiter = self.block_ready.subscribe()
            frame, cursor, count = self._push_frame("others", authority, cursor)
            if frame is not None:
                self.helper_blocks_sent += count
                await self.connection.send(frame)
            else:
                try:
                    await asyncio.wait_for(
                        waiter.wait(), timeout=self.parameters.stream_interval_s
                    )
                except asyncio.TimeoutError:
                    pass

    async def _stream_own(self, from_round: RoundNumber) -> None:
        """Push loop (synchronizer.rs:131-164): batch, send, wait for new blocks."""
        cursor = from_round
        while not self.connection.is_closed():
            # Subscribe BEFORE reading the store: a block landing between the
            # read and the wait then still wakes us (no lost edge).
            waiter = self.block_ready.subscribe()
            frame, cursor, _count = self._push_frame("own", None, cursor)
            if frame is not None:
                await self.connection.send(frame)
            else:
                try:
                    await asyncio.wait_for(
                        waiter.wait(), timeout=self.parameters.stream_interval_s
                    )
                except asyncio.TimeoutError:
                    pass

    def stream_snapshot(self, from_round: RoundNumber, gc_hold=None) -> None:
        """Serve the snapshot block window: every stored block from
        ``from_round`` (the manifest's floor) up to the current frontier,
        round-ascending so parents precede children at the receiver.  A
        re-request replaces a stream still in flight (reconnect semantics,
        like the subscribe streams); blocks that land after the walk reach
        the peer through the ordinary subscribe streams.

        ``gc_hold`` (the serving node's StorageLifecycle) pauses garbage
        collection for the stream's lifetime: a GC pass advancing the
        retired floor mid-walk would silently hole the bottom of the window
        the manifest promised, wedging the rejoiner on unfetchable parents."""
        if self._snapshot_task is not None:
            self._snapshot_task.cancel()
        self._snapshot_task = spawn_logged(
            self._stream_snapshot(from_round, gc_hold), log
        )

    async def _stream_snapshot(self, from_round: RoundNumber, gc_hold) -> None:
        if gc_hold is not None:
            gc_hold.gc_holds += 1
        try:
            chunk: List[bytes] = []
            # Genesis is axiomatic on every node — never shipped.
            for round_ in range(max(1, from_round), self.block_store.highest_round() + 1):
                if self.connection.is_closed():
                    return
                for block in self.block_store.get_blocks_by_round(round_):
                    chunk.append(block.to_bytes())
                    if len(chunk) >= DISSEMINATION_CHUNK:
                        await self._send_snapshot_chunk(chunk)
                        chunk = []
            if chunk:
                await self._send_snapshot_chunk(chunk)
            log.info(
                "snapshot stream to authority %d done: %d blocks, %d bytes",
                self.connection.peer, self.snapshot_blocks_sent,
                self.snapshot_bytes_sent,
            )
        finally:
            if gc_hold is not None:
                gc_hold.gc_holds -= 1

    async def _send_snapshot_chunk(self, chunk: List[bytes]) -> None:
        self.snapshot_blocks_sent += len(chunk)
        self.snapshot_bytes_sent += sum(len(b) for b in chunk)
        await self.connection.send(Blocks(tuple(chunk)))

    async def send_requested(self, references: Sequence[BlockReference]) -> None:
        """Answer an explicit RequestBlocks (synchronizer.rs:74-112)."""
        found: List[bytes] = []
        missing: List[BlockReference] = []
        for ref in references[:MAXIMUM_BLOCK_REQUEST]:
            block = self.block_store.get_block(ref)
            if block is None:
                missing.append(ref)
            else:
                found.append(block.to_bytes())
        for i in range(0, len(found), DISSEMINATION_CHUNK):
            await self.connection.send(
                RequestBlocksResponse(tuple(found[i : i + DISSEMINATION_CHUNK]))
            )
        if missing:
            await self.connection.send(BlockNotFound(tuple(missing)))

    def stop(self) -> None:
        if self._stream_task is not None:
            self._stream_task.cancel()
        if self._snapshot_task is not None:
            self._snapshot_task.cancel()
        for task in self._helper_tasks.values():
            task.cancel()
        self._helper_tasks.clear()


class HelperSubscriptions:
    """Requester-side bookkeeping for helper streams (config.rs:76-100's
    caps): which peers we asked to relay which authority, bounded per
    authority (``maximum_helpers_per_authority``) and in total
    (``absolute_maximum_helpers``)."""

    def __init__(self, parameters: SynchronizerParameters) -> None:
        self.parameters = parameters
        self._by_authority: Dict[int, set] = {}

    def total(self) -> int:
        return sum(len(p) for p in self._by_authority.values())

    def may_ask(self, authority: int, helper: int) -> bool:
        helpers = self._by_authority.get(authority, set())
        return (
            helper not in helpers
            and len(helpers) < self.parameters.maximum_helpers_per_authority
            and self.total() < self.parameters.absolute_maximum_helpers
        )

    def note_asked(self, authority: int, helper: int) -> None:
        self._by_authority.setdefault(authority, set()).add(helper)

    def drop_helper(self, helper: int) -> List[int]:
        """The helper's connection died: its streams are gone with it.
        Returns the authorities it was relaying so the caller can re-ask
        surviving peers — without that, one helper loss silently demotes
        those authorities back to the pull fetcher's crawl."""
        orphaned: List[int] = []
        for authority, helpers in self._by_authority.items():
            if helper in helpers:
                helpers.discard(helper)
                orphaned.append(authority)
        return orphaned

    def drop_authority(self, authority: int) -> None:
        """A direct connection to the authority came (back) up: the relay
        is redundant — forget it so a later outage can re-ask."""
        self._by_authority.pop(authority, None)


class BlockFetcher:
    """Pull loop for missing causal history (synchronizer.rs:216-407)."""

    def __init__(
        self,
        authority: int,
        dispatcher: CoreTaskDispatcher,
        connections: Dict[int, Connection],
        parameters: Optional[SynchronizerParameters] = None,
        metrics=None,
    ) -> None:
        self.authority = authority
        self.dispatcher = dispatcher
        self.connections = connections  # live view maintained by NetworkSyncer
        self.parameters = parameters or SynchronizerParameters()
        self.metrics = metrics
        self._task: Optional[asyncio.Task] = None

    def start(self) -> "BlockFetcher":
        self._task = spawn_logged(self._run(), log)
        return self

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.parameters.sample_precision_s)
            try:
                missing = await self.dispatcher.get_missing()
            except asyncio.CancelledError:
                raise
            except Exception:
                continue
            to_request: List[BlockReference] = []
            for authority_missing in missing:
                to_request.extend(authority_missing)
            if not to_request:
                continue
            if self.metrics is not None:
                self.metrics.missing_blocks_total.inc(len(to_request))
            for i in range(0, len(to_request), MAXIMUM_BLOCK_REQUEST):
                chunk = to_request[i : i + MAXIMUM_BLOCK_REQUEST]
                peer = self._sample_peer(exclude={self.authority})
                if peer is None:
                    break
                log.debug(
                    "fetching %d missing blocks from authority %d",
                    len(chunk),
                    peer,
                )
                await self.connections[peer].send(RequestBlocks(tuple(chunk)))

    def _sample_peer(self, exclude) -> Optional[int]:
        """Latency-weighted random choice (synchronizer.rs:376-406): weight is
        inverse RTT; unmeasured peers get the median weight."""
        import random as _random

        loop = asyncio.get_event_loop()
        rng = getattr(loop, "rng", _random)
        candidates = [
            (peer, conn)
            for peer, conn in self.connections.items()
            if peer not in exclude and not conn.is_closed()
        ]
        if not candidates:
            return None
        latencies = [c.latency() for _, c in candidates]
        finite = sorted(l for l in latencies if l != float("inf"))
        default = finite[len(finite) // 2] if finite else 1.0
        weights = [
            1.0 / max(1e-4, (l if l != float("inf") else default)) for l in latencies
        ]
        total = sum(weights)
        point = rng.uniform(0, total)
        acc = 0.0
        for (peer, _), w in zip(candidates, weights):
            acc += w
            if point <= acc:
                return peer
        return candidates[-1][0]

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
