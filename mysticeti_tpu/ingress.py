"""Overload-resilient ingress plane: gateway, admission-controlled mempool,
graceful degradation under saturation.

The MAXLOAD artifacts show why ingress policy matters: committed throughput
*collapses* past saturation (r4: 40.3k committed at 57.6k offered) because
transactions entered through ``BenchmarkFastPathBlockHandler.submit`` into an
UNBOUNDED queue with nothing but the per-block SOFT_MAX drain cap — no dedup,
no fairness, no shedding, and no backpressure signal from the core.  This
module is the real ingress plane (the ACE-runtime split between an admission
edge and a finality core):

* :class:`Mempool` — bounded (transaction- AND byte-capped) pool with
  nonce/digest dedup over a count-bounded window and per-client fairness
  lanes drained weighted-round-robin with a priority class.  Overflow is
  **explicitly shed** with a typed reason, never silently queued or dropped.
* :class:`AdmissionController` — AIMD on the admitted rate, closing the loop
  from live core signals the health plane already computes (mempool
  occupancy, core owner queue depth, WAL backlog, verifier pipeline
  occupancy): additive raise per tick while healthy, multiplicative cut on
  congestion, a floor so a transient stall cannot starve ingress forever.
  At 2-5x offered overload the core keeps running at its measured saturation
  point instead of collapsing behind an ever-deeper queue.
* :class:`IngressPlane` — the facade the block handler, validator assembly,
  health probe, and gateway share: ``submit`` returns a typed
  :class:`SubmitResult` (``SHED{retry_after_ms, reason}`` instead of a silent
  drop), ``drain`` feeds proposals, ``tick`` runs the controller, and every
  rejection counts on ``mysticeti_ingress_shed_total{reason}`` and lands in
  a bounded structured shed log (byte-identical across same-seed sims).
* :class:`IngressGateway` — the client-facing RPC listener on the existing
  length-prefixed framing (wire tags 13-16, docs/wire-format.md §5b):
  SUBMIT -> ACK/QUEUED/SHED plus an optional commit-notification stream fed
  from the committed sequence.
* :func:`run_overload_sim` — a seeded, deterministic N-node overload
  scenario on the virtual-time simulator (the chaos tier's shape): offered
  load ramps to a multiple of the 1x rate and the run asserts graceful
  degradation, full shed accounting, and a byte-identical shed schedule.

Everything is clocked by the RUNTIME clock (virtual under the deterministic
simulator) and dedup is count-bounded, not time-bounded, so seeded sims are
bit-reproducible.  Trust notes (client-facing surface!) live in
docs/ingress.md.
"""
from __future__ import annotations

import asyncio
import hashlib
import json
import threading
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Tuple

from .config import IngressParameters
from .network import (
    GATEWAY_ACK,
    GATEWAY_QUEUED,
    GATEWAY_SHED,
    GatewayCommitNotification,
    GatewaySubmit,
    GatewaySubmitReply,
    GatewaySubscribeCommits,
    _read_frame,
    _write_frame,
    decode_message,
    encode_message,
)
from .finality import FinalityTracker
from .runtime import now as runtime_now, timestamp_utc
from .tracing import logger
from .utils.tasks import spawn_logged

log = logger(__name__)

# Shed reasons (the mysticeti_ingress_shed_total{reason} label values).
SHED_ADMISSION = "admission"
SHED_MEMPOOL_TXS = "mempool_transactions"
SHED_MEMPOOL_BYTES = "mempool_bytes"
SHED_LANE_CAP = "lane_cap"
SHED_DUPLICATE = "duplicate"
# Not a rejection: transactions deferred to the NEXT proposal when a drain
# would overshoot the per-block cap (the old silent `_receive_with_limit`
# truncation, now visible).  Counted on the same family so the whole
# admitted-but-not-yet-proposed picture reads off one series.
SHED_SOFT_CAP_DEFERRED = "soft_cap_deferred"
# Execution-plane pre-consensus rejects (execution.py): typed verdicts for
# transactions already doomed against current account state — shed here so
# consensus never pays for them.  The label values ARE the execution
# verdict names (one vocabulary across admission and the fold); the checks
# are advisory (in-flight commits may move the account), so only verdicts
# wrong against CURRENT state are shed — a nonce ahead of the account is
# admitted and left to the deterministic fold.
SHED_BAD_NONCE = "bad_nonce"
SHED_INSUFFICIENT_BALANCE = "insufficient_balance"
SHED_UNKNOWN_ACCOUNT = "unknown_account"
SHED_ACCOUNT_EXISTS = "account_exists"
_EXEC_SHED_REASONS = (
    SHED_BAD_NONCE,
    SHED_INSUFFICIENT_BALANCE,
    SHED_UNKNOWN_ACCOUNT,
    SHED_ACCOUNT_EXISTS,
)

# Floor on any retry-after hint: a zero tells a closed-loop client to spin.
RETRY_AFTER_MIN_MS = 25

# WRR drain chunk per turn (priority lanes get priority_weight chunks): big
# enough to amortize the rotation over a 10k-budget drain, small enough that
# a cycle still visits every lane inside one small-budget proposal.
DRAIN_CHUNK = 32

# Fairness-lane table cap: lane tokens are CLIENT-CHOSEN bytes on an
# unauthenticated listener, so an adversary could otherwise mint unbounded
# bookkeeping (docs/ingress.md trust notes).  Submissions that would create
# a lane beyond the cap are shed as lane_cap.
MAX_LANES = 1024


def ingress_key(transaction: bytes) -> bytes:
    """The 16-byte dedup/notification key of a transaction: BLAKE2b-128 over
    the full canonical bytes (the generator's nonce is inside them, so two
    distinct submissions never collide and a resubmission always does)."""
    return hashlib.blake2b(transaction, digest_size=16).digest()


def _canonical(obj) -> bytes:
    return json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()


@dataclass(frozen=True)
class SubmitResult:
    """Typed submission verdict — the explicit-shedding contract.

    ``status`` mirrors the gateway wire values (ACK/QUEUED/SHED);
    ``retry_after_ms`` is when the admission controller expects capacity
    (only meaningful on SHED); ``reason`` names the first rejection cause.
    """

    status: int
    accepted: int
    shed: int
    retry_after_ms: int = 0
    reason: str = ""

    @property
    def is_shed(self) -> bool:
        return self.status == GATEWAY_SHED


class _Lane:
    __slots__ = ("queue", "bytes", "priority", "drained", "shed")

    def __init__(self, priority: bool) -> None:
        # (transaction, ingress_key) pairs: the key rides along so the
        # drain can stamp finality-sampled keys without rehashing.
        self.queue: Deque[Tuple[bytes, bytes]] = deque()
        self.bytes = 0
        self.priority = priority
        self.drained = 0
        self.shed = 0


class Mempool:
    """Bounded transaction pool with dedup and per-client fairness lanes.

    ``submit`` never blocks and never silently drops: every transaction is
    either admitted into its lane or returned as shed with a typed reason.
    ``drain`` serves proposals weighted-round-robin across lanes — one full
    cycle gives every non-empty lane a turn before any lane gets a second,
    so no client can starve another regardless of submission rate; priority
    lanes get ``priority_weight`` chunks per turn.

    The aggregate accounting fields are lock-disciplined
    (``_mempool_lock``; the lint's GUARDED_FIELDS covers them): submissions
    may arrive from application threads (SimpleBlockHandler precedent) while
    the core drains on the loop.
    """

    def __init__(self, params: IngressParameters, finality=None) -> None:
        self.params = params
        # Optional FinalityTracker (finality.py): submit/drain stamp the
        # admission and proposal phases for count-sampled keys.
        self._finality = finality
        self._lanes: "OrderedDict[Tuple[str, bool], _Lane]" = OrderedDict()
        self._seen: "OrderedDict[bytes, None]" = OrderedDict()
        self._mempool_lock = threading.Lock()
        self._mempool_count = 0
        self._mempool_bytes = 0

    # -- intake --

    def submit(
        self,
        client: str,
        transactions: List[bytes],
        priority: bool = False,
        t_submit: Optional[float] = None,
    ) -> Tuple[int, Dict[str, int]]:
        """Admit what fits; return ``(accepted, {shed_reason: count})``.

        ``t_submit`` is the caller-observed arrival time (defaults to the
        admission time) for the finality tracker's admission phase."""
        params = self.params
        fin = self._finality
        accepted = 0
        sheds: Dict[str, int] = {}
        sampled_keys: List[bytes] = []
        with self._mempool_lock:
            lane = self._lanes.get((client, priority))
            if lane is None:
                if len(self._lanes) >= MAX_LANES and not self._evict_lane():
                    # Every lane still holds transactions: genuine pressure,
                    # not bookkeeping exhaustion (empty lanes are evicted, so
                    # 1024 cumulative clients can never wedge ingress).
                    sheds[SHED_LANE_CAP] = len(transactions)
                    return 0, sheds
                lane = self._lanes[(client, priority)] = _Lane(priority)
            for tx in transactions:
                # Dedup FIRST: a duplicate is a duplicate even when the pool
                # is full (it is the one verdict a client must not retry).
                key = ingress_key(tx)
                if key in self._seen:
                    sheds[SHED_DUPLICATE] = sheds.get(SHED_DUPLICATE, 0) + 1
                    lane.shed += 1
                    continue
                # Cap sheds do NOT enter the seen window: the retry the
                # SHED{retry_after_ms} contract invites must be admissible
                # later, not misread as a duplicate.
                if self._mempool_count >= params.mempool_max_transactions:
                    sheds[SHED_MEMPOOL_TXS] = (
                        sheds.get(SHED_MEMPOOL_TXS, 0) + 1
                    )
                    lane.shed += 1
                    continue
                if self._mempool_bytes + len(tx) > params.mempool_max_bytes:
                    sheds[SHED_MEMPOOL_BYTES] = (
                        sheds.get(SHED_MEMPOOL_BYTES, 0) + 1
                    )
                    lane.shed += 1
                    continue
                if len(lane.queue) >= params.lane_max_transactions:
                    sheds[SHED_LANE_CAP] = sheds.get(SHED_LANE_CAP, 0) + 1
                    lane.shed += 1
                    continue
                self._seen[key] = None
                if len(self._seen) > params.dedup_window:
                    self._seen.popitem(last=False)
                lane.queue.append((tx, key))
                lane.bytes += len(tx)
                self._mempool_count += 1
                self._mempool_bytes += len(tx)
                accepted += 1
                if fin is not None and fin.sampled(key):
                    sampled_keys.append(key)
        # Stamp outside _mempool_lock: the tracker has its own lock and the
        # lock-order lint wants no nesting between the two planes.
        if sampled_keys:
            t_admitted = fin.clock()
            if t_submit is None:
                t_submit = t_admitted
            for key in sampled_keys:
                fin.on_submit(key, t_submit, t_admitted)
        return accepted, sheds

    def _evict_lane(self) -> bool:
        """Drop the oldest drained-empty lane to make room for a new one
        (holding ``_mempool_lock``).  Gateway connections mint one lane each
        (``conn-{id}``), so without eviction MAX_LANES would be a LIFETIME
        cap — 1024 cumulative connections would permanently shed every new
        client until restart.  Only stats die with an empty lane, never
        transactions."""
        for key, lane in self._lanes.items():
            if not lane.queue:
                del self._lanes[key]
                return True
        return False

    # -- drain (weighted round-robin) --

    def drain(self, budget: int) -> List[bytes]:
        if budget <= 0:
            return []
        fin = self._finality
        out: List[bytes] = []
        sampled_keys: List[bytes] = []
        with self._mempool_lock:
            if self._mempool_count == 0:
                return out
            lanes = list(self._lanes.items())
            # Rotate the visit order so the lane that led this drain goes
            # last in the next one — fairness across drains, not just
            # within one cycle.
            while len(out) < budget:
                progressed = False
                for key, lane in lanes:
                    if not lane.queue:
                        continue
                    chunk = DRAIN_CHUNK * (
                        self.params.priority_weight if lane.priority else 1
                    )
                    take = min(chunk, budget - len(out), len(lane.queue))
                    for _ in range(take):
                        tx, tx_key = lane.queue.popleft()
                        lane.bytes -= len(tx)
                        self._mempool_count -= 1
                        self._mempool_bytes -= len(tx)
                        out.append(tx)
                        if fin is not None and fin.sampled(tx_key):
                            sampled_keys.append(tx_key)
                    lane.drained += take
                    progressed = progressed or take > 0
                    if len(out) >= budget:
                        break
                if not progressed:
                    break
            if lanes:
                first_key = lanes[0][0]
                if first_key in self._lanes:
                    self._lanes.move_to_end(first_key)
        if sampled_keys:
            t = fin.clock()
            for key in sampled_keys:
                fin.on_proposal(key, t)
        return out

    # -- views --

    def pending(self) -> int:
        return self._mempool_count

    def pending_bytes(self) -> int:
        return self._mempool_bytes

    def occupancy(self) -> float:
        """Fraction of the tighter cap in use (the congestion signal)."""
        p = self.params
        by_count = (
            self._mempool_count / p.mempool_max_transactions
            if p.mempool_max_transactions
            else 0.0
        )
        by_bytes = (
            self._mempool_bytes / p.mempool_max_bytes
            if p.mempool_max_bytes
            else 0.0
        )
        return max(by_count, by_bytes)

    def lane_stats(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        with self._mempool_lock:
            for (client, priority), lane in self._lanes.items():
                name = f"{client}/priority" if priority else client
                out[name] = {
                    "pending": len(lane.queue),
                    "drained": lane.drained,
                    "shed": lane.shed,
                    "priority": priority,
                }
        return out


class AdmissionController:
    """AIMD admitted-rate controller over a token bucket.

    ``admit(n)`` spends tokens refilled at the current rate; the unfunded
    tail is shed with a ``retry_after_ms`` hint sized to the deficit.
    ``tick(signals)`` is the AIMD step: a congested core (mempool past the
    high watermark, core owner queue deep, or WAL backlog while the mempool
    is filling) cuts the rate multiplicatively; a drained mempool raises it
    additively; in between the rate holds (hysteresis).  ``tick`` runs on
    the event loop, but ``admit`` rides the submit path, which the mempool
    contract allows from application threads — so the token bucket is
    lock-disciplined like the mempool counters (two concurrent admits must
    not both spend the same tokens and double the admitted rate).
    """

    # Token bucket burst window: enough to absorb one generator tick's batch
    # without the bucket itself becoming a second (jittery) rate limit.
    BURST_S = 0.5
    # Core owner queue fill fraction that reads as congestion.
    CORE_QUEUE_FRACTION = 0.75

    def __init__(
        self,
        params: IngressParameters,
        clock: Callable[[], float] = runtime_now,
    ) -> None:
        self.params = params
        self.clock = clock
        self.rate = float(params.admission_initial_tx_s)
        self.shed_mode = False
        self._lock = threading.Lock()
        self._tokens = self.rate * self.BURST_S
        self._last_refill: Optional[float] = None

    def admit(self, n: int) -> Tuple[int, int]:
        """Fund up to ``n`` transactions; return ``(admitted,
        retry_after_ms)`` where the hint covers the unfunded remainder."""
        if not self.params.admission or n <= 0:
            return n, 0
        now = self.clock()
        with self._lock:
            if self._last_refill is not None:
                self._tokens = min(
                    self.rate * self.BURST_S,
                    self._tokens + (now - self._last_refill) * self.rate,
                )
            self._last_refill = now
            admitted = min(n, int(self._tokens))
            self._tokens -= admitted
        if admitted >= n:
            return n, 0
        deficit = n - admitted
        retry_ms = max(
            RETRY_AFTER_MIN_MS, int(1000.0 * deficit / max(self.rate, 1.0))
        )
        return admitted, retry_ms

    def tick(self, signals: dict) -> List[str]:
        """One AIMD step; returns the congestion reasons (empty = healthy)."""
        p = self.params
        occupancy = signals.get("mempool_occupancy", 0.0)
        congested: List[str] = []
        if occupancy >= p.high_watermark:
            congested.append("mempool")
        depth = signals.get("core_queue_depth")
        capacity = signals.get("core_queue_capacity") or 0
        if depth is not None and capacity:
            if depth >= capacity * self.CORE_QUEUE_FRACTION:
                congested.append("core-queue")
        # A WAL backlog alone is normal at load (the async drain runs a 1 s
        # cadence); combined with a FILLING mempool it means the core is
        # genuinely behind its intake.
        if signals.get("wal_backlog") and occupancy >= p.low_watermark:
            congested.append("wal")
        if (signals.get("verify_occupancy") or 0.0) >= 1.0 and (
            occupancy >= p.low_watermark
        ):
            congested.append("verifier")
        if congested:
            with self._lock:
                self.rate = max(
                    p.admission_min_tx_s,
                    self.rate * p.admission_decrease_factor,
                )
                self._tokens = min(self._tokens, self.rate * self.BURST_S)
            self.shed_mode = True
        elif occupancy <= p.low_watermark:
            with self._lock:
                self.rate = min(
                    p.admission_max_tx_s, self.rate + p.admission_additive_tx_s
                )
            self.shed_mode = False
        return congested


class IngressPlane:
    """The node's ingress facade: mempool + admission + accounting + feeds.

    Wired by the validator assembly: the block handler submits and drains
    through it, the gateway serves clients off it, the health probe embeds
    its state in ``/health``, the flight recorder gets shed-mode
    transitions, and the commit observer feeds it the committed sequence
    for client notifications.
    """

    def __init__(
        self,
        params: Optional[IngressParameters] = None,
        authority: int = 0,
        metrics=None,
        recorder=None,
        clock: Callable[[], float] = runtime_now,
    ) -> None:
        self.params = params or IngressParameters()
        self.authority = authority
        self.metrics = metrics
        self.recorder = recorder
        self.clock = clock
        # Server-side submit→finality phase joiner (finality.py) over
        # count-sampled ingress keys; finality_sample_every=0 disables it.
        self.finality = (
            FinalityTracker(
                metrics=metrics,
                sample_every=self.params.finality_sample_every,
                clock=clock,
            )
            if self.params.finality_sample_every > 0
            else None
        )
        self.mempool = Mempool(self.params, finality=self.finality)
        self.controller = AdmissionController(self.params, clock=clock)
        # Submit-path accounting: submit() is callable from application
        # threads (same contract as Mempool), so the ledger and shed log
        # move under one lock — a log append racing the canonical
        # serialization in shed_log_bytes() would break the byte-identical
        # shed-schedule claim.
        self._accounting_lock = threading.Lock()
        self.admitted_total = 0
        self.shed_by_reason: Dict[str, int] = {}
        self.shed_log: List[dict] = []
        self._shed_log_dropped = 0
        self.commit_height = 0
        self._commit_sinks: List[Callable[[int, List[bytes]], None]] = []
        self._last_shed_mode = False
        self._task: Optional[asyncio.Task] = None
        # Core signal taps (attach()); all optional.
        self._core = None
        self._net_syncer = None
        self._block_verifier = None
        self._health = None
        # Execution plane tap (attach(core=...) when core.execution is on):
        # commit notifications are then DEFERRED until the core has folded
        # the commit through the state machine — one frame carries both the
        # sequencing decision and the executed root.  The buffer only lives
        # between handle_commit and handle_committed_subdag in the same
        # synchronous syncer pass, so it stays tiny.
        self.execution = None
        self._pending_exec: Deque[Tuple[int, List[bytes], dict]] = deque()
        self.executed_height = 0
        self.executed_root = b""

    # -- wiring --

    def attach(
        self,
        core=None,
        net_syncer=None,
        block_verifier=None,
        health=None,
    ) -> "IngressPlane":
        if core is not None:
            self._core = core
            execution = getattr(core, "execution", None)
            if execution is not None:
                self.execution = execution
                self.executed_height = execution.last_height
                self.executed_root = execution.root
                core.execution_listeners.append(self._on_executed)
                if self.finality is not None:
                    # The headline total SLI now closes at EXECUTED.
                    self.finality.execute_expected = True
        if net_syncer is not None:
            self._net_syncer = net_syncer
        if block_verifier is not None:
            self._block_verifier = block_verifier
        if health is not None:
            self._health = health
        return self

    def add_commit_sink(
        self, sink: Callable[[int, List[bytes], dict], None]
    ) -> None:
        """Register a commit-notification consumer (the gateway's
        subscription stream).  Sinks receive
        ``(height, [ingress keys], info)`` per committed sub-dag, where
        ``info`` carries ``leader_round`` and ``committed_ts_ns`` for the
        tag-16 wire suffix; key extraction only runs while at least one
        sink (or the finality tracker) is active."""
        self._commit_sinks.append(sink)

    def remove_commit_sink(self, sink) -> None:
        try:
            self._commit_sinks.remove(sink)
        except ValueError:
            pass

    # -- intake / drain --

    @property
    def max_per_proposal(self) -> int:
        return self.params.max_per_proposal

    def submit(
        self, client: str, transactions: List[bytes], priority: bool = False
    ) -> SubmitResult:
        n = len(transactions)
        if n == 0:
            return SubmitResult(GATEWAY_ACK, 0, 0)
        t_submit = self.clock()
        admitted_n, retry_ms = self.controller.admit(n)
        sheds: Dict[str, int] = {}
        if admitted_n < n:
            sheds[SHED_ADMISSION] = n - admitted_n
        admitted = transactions[:admitted_n]
        if self.execution is not None:
            lanes = self._route_execution(client, admitted, sheds)
        else:
            lanes = [(client, admitted)]
        accepted = 0
        for lane_client, lane_txs in lanes:
            lane_accepted, pool_sheds = self.mempool.submit(
                lane_client, lane_txs, priority=priority, t_submit=t_submit
            )
            accepted += lane_accepted
            for reason, count in pool_sheds.items():
                sheds[reason] = sheds.get(reason, 0) + count
        shed = n - accepted
        with self._accounting_lock:
            self.admitted_total += accepted
        if self.metrics is not None and accepted:
            self.metrics.mysticeti_ingress_admitted_total.inc(accepted)
        reason = ""
        if sheds:
            # Deterministic reason precedence: the most actionable first
            # (admission has a rate-derived retry hint, pool caps a
            # drain-derived one, duplicates none worth retrying).
            for candidate in (
                SHED_ADMISSION,
                SHED_MEMPOOL_TXS,
                SHED_MEMPOOL_BYTES,
                SHED_LANE_CAP,
            ) + _EXEC_SHED_REASONS + (
                SHED_DUPLICATE,
            ):
                if candidate in sheds:
                    reason = candidate
                    break
            if reason != SHED_ADMISSION:
                # Pool-cap sheds free up at drain cadence, not token cadence.
                retry_ms = max(
                    retry_ms,
                    max(
                        RETRY_AFTER_MIN_MS,
                        int(self.params.tick_interval_s * 1000),
                    ),
                )
            self._count_sheds(client, sheds, retry_ms)
        status = GATEWAY_SHED if shed else GATEWAY_ACK
        if not shed and self.mempool.occupancy() >= self.params.queued_watermark:
            status = GATEWAY_QUEUED
        return SubmitResult(status, accepted, shed, retry_ms if shed else 0,
                            reason)

    def _route_execution(
        self, client: str, transactions: List[bytes], sheds: Dict[str, int]
    ) -> List[Tuple[str, List[bytes]]]:
        """Identity-backed fairness lanes + pre-consensus execution shed.

        Execution transactions are re-laned by the ACCOUNT they spend from
        (``acct:<key>``), not by the client-chosen lane token — one identity
        hammering the pool through many connections still competes as one
        lane, and one gateway fronting many identities no longer serializes
        them behind a single token.  Transactions already doomed against
        current account state (bad nonce, overdraft, unknown account,
        CREATE of an existing account) are shed with a typed verdict BEFORE
        consensus sequences them.  Non-execution payloads keep the caller's
        lane untouched.  No locks are held here: ``admission_verdict`` takes
        the execution lock internally and ``Mempool.submit`` is called after
        (lock-order discipline).
        """
        from .execution import parse_exec_tx

        lanes: "OrderedDict[str, List[bytes]]" = OrderedDict()
        for tx in transactions:
            parsed = parse_exec_tx(tx)
            if parsed is None:
                lanes.setdefault(client, []).append(tx)
                continue
            verdict = self.execution.admission_verdict(parsed)
            if verdict is not None:
                sheds[verdict] = sheds.get(verdict, 0) + 1
                continue
            lanes.setdefault(f"acct:{parsed.account.hex()}", []).append(tx)
        return list(lanes.items())

    def drain(self, budget: int) -> List[bytes]:
        return self.mempool.drain(budget)

    def pending(self) -> int:
        return self.mempool.pending()

    def _count_sheds(
        self, client: str, sheds: Dict[str, int], retry_ms: int
    ) -> None:
        t = round(self.clock(), 6)
        for reason in sorted(sheds):
            count = sheds[reason]
            with self._accounting_lock:
                self.shed_by_reason[reason] = (
                    self.shed_by_reason.get(reason, 0) + count
                )
                if len(self.shed_log) < self.params.shed_log_capacity:
                    self.shed_log.append(
                        {
                            "t": t,
                            "client": client,
                            "reason": reason,
                            "n": count,
                            "retry_after_ms": retry_ms,
                        }
                    )
                else:
                    self._shed_log_dropped += count
            if self.metrics is not None:
                self.metrics.mysticeti_ingress_shed_total.labels(reason).inc(
                    count
                )

    def shed_total(self) -> int:
        with self._accounting_lock:
            return sum(self.shed_by_reason.values())

    def shed_log_bytes(self) -> bytes:
        """Canonical shed schedule — byte-identical across same-seed sims."""
        with self._accounting_lock:
            return _canonical(self.shed_log)

    def shed_schedule_digest(self) -> str:
        return hashlib.sha256(self.shed_log_bytes()).hexdigest()

    # -- the AIMD tick --

    def _signals(self) -> dict:
        signals: dict = {"mempool_occupancy": self.mempool.occupancy()}
        syncer = self._net_syncer
        if syncer is not None:
            # backpressure() already includes the core's wal_backlog tap.
            signals.update(syncer.backpressure())
        elif self._core is not None:
            # The PR 11 bug lived here: a real drain thread's queue depth
            # steering virtual-time admission.  It is safe ONLY because
            # sims construct the WAL with async_writes=False (walf), making
            # pending() constantly False in virtual time — that discipline
            # is what the suppression asserts.
            signals["wal_backlog"] = bool(self._core.wal_writer.pending())  # lint: ignore[sim-taint]
        verifier = self._block_verifier
        state_fn = getattr(verifier, "health_state", None)
        if state_fn is not None:
            state = state_fn()
            depth = state.get("pipeline_depth") or 0
            if depth:
                signals["verify_occupancy"] = (
                    (state.get("pipeline_inflight") or 0) / depth
                )
        health = self._health
        if health is not None and health.last_snapshot is not None:
            signals["commit_rate"] = health.last_snapshot.get(
                "commit_rate", 0.0
            )
        return signals

    def tick(self) -> dict:
        """One controller step + gauge refresh; returns the signal dict."""
        signals = self._signals()
        congested = self.controller.tick(signals)
        shed_mode = self.controller.shed_mode
        if shed_mode != self._last_shed_mode:
            log.info(
                "ingress shed mode %s (rate %.0f tx/s%s)",
                "ON" if shed_mode else "off",
                self.controller.rate,
                f"; congested: {','.join(congested)}" if congested else "",
            )
            if self.recorder is not None:
                self.recorder.record(
                    "shed-mode",
                    on=shed_mode,
                    rate=round(self.controller.rate, 1),
                    congested=",".join(congested),
                )
            self._last_shed_mode = shed_mode
        self._export_gauges(shed_mode)
        return signals

    def _export_gauges(self, shed_mode: bool) -> None:
        m = self.metrics
        if m is None:
            return
        m.mysticeti_ingress_admitted_rate.set(round(self.controller.rate, 3))
        m.mysticeti_ingress_mempool_transactions.set(self.mempool.pending())
        m.mysticeti_ingress_mempool_bytes.set(self.mempool.pending_bytes())
        m.mysticeti_ingress_shed_mode.set(1 if shed_mode else 0)
        if self.finality is not None:
            self.finality.export_gauges()

    # -- commit feed (wired via CommitObserver.ingress) --

    def note_committed(self, committed, t_commit: Optional[float] = None) -> None:
        """Feed from the committed sequence: track commit height and, when
        subscribers or the finality tracker exist, extract the committed
        transactions' ingress keys per sub-dag
        (finalization_interpreter.py is the offline oracle the tests
        cross-check this stream against).  ``t_commit`` is the observer's
        commit-decision time for the finality commit phase (defaults to
        now = the finalize time)."""
        from .types import Share

        if not committed:
            return
        self.commit_height = committed[-1].height
        fin = self.finality
        if not self._commit_sinks and fin is None:
            return
        now = self.clock()
        if t_commit is None:
            t_commit = now
        for commit in committed:
            keys: List[bytes] = []
            for block in commit.blocks:
                for st in block.statements:
                    if isinstance(st, Share):
                        keys.append(ingress_key(st.transaction))
            if fin is not None:
                for key in keys:
                    if fin.sampled(key):
                        fin.on_commit(key, t_commit, now)
            if not self._commit_sinks and self.execution is None:
                continue
            # Duck-typed commits (tests) may lack an anchor; default to 0.
            anchor = getattr(commit, "anchor", None)
            info = {
                "leader_round": int(anchor.round) if anchor is not None else 0,
                "committed_ts_ns": int(timestamp_utc() * 1e9),
            }
            if self.execution is not None:
                # Defer: the syncer calls this observer feed BEFORE the core
                # folds the commit through the execution state machine; the
                # _on_executed listener flushes the notification with the
                # executed root attached — same synchronous loop pass,
                # microseconds later, but the client frame then carries
                # RESULTS, not just sequencing.
                self._pending_exec.append((commit.height, keys, info))
                continue
            self._dispatch(commit.height, keys, info)

    def _dispatch(self, height: int, keys: List[bytes], info: dict) -> None:
        for sink in list(self._commit_sinks):
            try:
                sink(height, keys, info)
            except Exception:  # noqa: BLE001 - a dead sink must not stall commits
                log.exception("ingress commit sink failed; removing")
                self.remove_commit_sink(sink)

    def _on_executed(self, result) -> None:
        """Core execution listener: a committed sub-dag was folded.  Closes
        the ``execute`` finality phase for sampled keys and flushes the
        deferred commit notifications with the executed root attached
        (stale buffered heights — possible only across a snapshot jump —
        fall back to the recent-root window)."""
        self.executed_height = result.height
        self.executed_root = result.root
        fin = self.finality
        now = self.clock()
        while self._pending_exec and self._pending_exec[0][0] <= result.height:
            height, keys, info = self._pending_exec.popleft()
            if height == result.height:
                root = result.root
            else:
                root = self.execution.root_at(height) or result.root
            info["executed_height"] = height
            info["executed_root"] = root
            if fin is not None:
                fin.on_execute([k for k in keys if fin.sampled(k)], now)
            self._dispatch(height, keys, info)
        if self.recorder is not None and result.rejected:
            self.recorder.record(
                "exec-reject",
                height=result.height,
                rejected=result.rejected,
                root=result.root.hex()[:16],
            )

    # -- health / diagnosis --

    def health_state(self) -> dict:
        with self._accounting_lock:
            admitted_total = self.admitted_total
            shed_by_reason = dict(sorted(self.shed_by_reason.items()))
        return {
            "admitted_rate_tx_s": round(self.controller.rate, 3),
            "shed_mode": self.controller.shed_mode,
            "mempool_transactions": self.mempool.pending(),
            "mempool_bytes": self.mempool.pending_bytes(),
            "mempool_occupancy": round(self.mempool.occupancy(), 6),
            "admitted_total": admitted_total,
            "shed_by_reason": shed_by_reason,
            "commit_height": self.commit_height,
            **(
                {"finality": self.finality.state()}
                if self.finality is not None
                else {}
            ),
            **(
                {
                    "execution": {
                        "executed_height": self.execution.last_height,
                        "executed_root": self.execution.root.hex(),
                    }
                }
                if self.execution is not None
                else {}
            ),
        }

    # -- lifecycle (production nodes; sims drive tick() via the loop too) --

    def start(self) -> "IngressPlane":
        if self._task is None:
            self._task = spawn_logged(self._run(), log, name="ingress-tick")
        return self

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.params.tick_interval_s)
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - the controller must outlive glitches
                log.exception("ingress tick failed")

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None


# ---------------------------------------------------------------------------
# Client RPC gateway


class IngressGateway:
    """Client-facing listener: SUBMIT -> ACK/QUEUED/SHED + commit stream.

    Rides the mesh's length-prefixed framing and codec (wire tags 13-16)
    but on its OWN listener — gateway tags never appear on the validator
    mesh.  Each connection gets a default fairness lane; a client may name
    its lane via ``GatewaySubmit.client`` (trust notes: docs/ingress.md —
    lane tokens are client-chosen, so per-lane caps bound the damage one
    identity can do, and the listener should face the public only behind
    an authenticating proxy).

    All writes for one connection flow through a single outbound queue so
    submit replies and commit notifications never interleave mid-frame.
    """

    def __init__(self, plane: IngressPlane, host: str, port: int) -> None:
        self.plane = plane
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self._conn_seq = 0
        self.connections = 0

    async def start(self) -> "IngressGateway":
        self._server = await asyncio.start_server(
            self._handle, host=self.host, port=self.port
        )
        log.info("ingress gateway listening on %s:%d", self.host, self.port)
        return self

    async def stop(self) -> None:
        # Swap before awaiting: a second stop() racing past the await of the
        # first must see None, not close an already-closing server.
        server, self._server = self._server, None
        if server is not None:
            server.close()
            await server.wait_closed()

    async def _handle(self, reader, writer) -> None:
        self._conn_seq += 1
        conn_id = self._conn_seq
        default_lane = f"conn-{conn_id}"
        outbound: asyncio.Queue = asyncio.Queue(maxsize=256)
        sink = None
        self.connections += 1
        if self.plane.metrics is not None:
            self.plane.metrics.mysticeti_ingress_gateway_clients.set(
                self.connections
            )

        async def write_loop() -> None:
            while True:
                msg = await outbound.get()
                _write_frame(writer, encode_message(msg))
                await writer.drain()

        writer_task = spawn_logged(
            write_loop(), log, name=f"gateway-writer-{conn_id}"
        )
        try:
            while True:
                frame = await _read_frame(reader)
                msg = decode_message(frame)
                if isinstance(msg, GatewaySubmit):
                    lane = (
                        msg.client.decode("utf-8", errors="replace")
                        if msg.client
                        else default_lane
                    )
                    result = self.plane.submit(
                        lane,
                        list(msg.transactions),
                        priority=bool(msg.priority),
                    )
                    await outbound.put(
                        GatewaySubmitReply(
                            result.status,
                            result.accepted,
                            result.shed,
                            result.retry_after_ms,
                            result.reason.encode(),
                        )
                    )
                elif isinstance(msg, GatewaySubscribeCommits):
                    # A later subscribe on the same connection REPLACES the
                    # filter (wire-format §5b): silently ignoring it would
                    # leave the client processing notifications it asked to
                    # suppress.
                    if sink is not None:
                        self.plane.remove_commit_sink(sink)
                    from_height = msg.from_height
                    # §5b soft extension: only clients that opted in get
                    # the detail suffix — a pre-r17 client would reset the
                    # connection on the longer frame otherwise.
                    want_details = bool(getattr(msg, "want_details", 0))
                    # §5b second-tier extension (r20): want_executed adds
                    # the EXECUTED result suffix (state root per commit)
                    # and IMPLIES the detail suffix on the wire.
                    want_executed = bool(getattr(msg, "want_executed", 0))

                    # Live stream only: from_height FILTERS future
                    # notifications, it does not replay commits that
                    # happened before the subscription (wire-format §5b
                    # documents the gap contract for resuming clients; the
                    # synthetic executed-height notification below pins
                    # where a resuming client's unknown window ends).
                    def sink(height, keys, info, q=outbound, fh=from_height,
                             details=want_details, executed=want_executed):
                        if height <= fh:
                            return
                        root = (
                            info.get("executed_root", b"") if executed else b""
                        )
                        if details or root:
                            note = GatewayCommitNotification(
                                height,
                                tuple(keys),
                                leader_round=int(
                                    info.get("leader_round", 0)
                                ),
                                committed_ts_ns=int(
                                    info.get("committed_ts_ns", 0)
                                ),
                                executed_root=root,
                            )
                        else:
                            note = GatewayCommitNotification(
                                height, tuple(keys)
                            )
                        try:
                            q.put_nowait(note)
                        except asyncio.QueueFull:
                            # A client not reading its notifications loses
                            # them (bounded queue, never the node's
                            # memory); counted, not silent.
                            m = self.plane.metrics
                            if m is not None:
                                m.mysticeti_ingress_shed_total.labels(
                                    "notify_backpressure"
                                ).inc(len(keys))
                            return
                        fin = self.plane.finality
                        if fin is not None:
                            fin.on_notify(
                                [k for k in keys if fin.sampled(k)],
                                fin.clock(),
                            )

                    self.plane.add_commit_sink(sink)
                    if want_executed and self.plane.execution is not None:
                        # Resume-gap fix: an immediate synthetic
                        # notification (no keys) tells the subscriber
                        # exactly where its unknown window ends — the
                        # node's current executed height and root.  A
                        # resuming client diffs this against its own last
                        # known height before trusting the live stream.
                        await outbound.put(
                            GatewayCommitNotification(
                                self.plane.execution.last_height,
                                (),
                                executed_root=self.plane.execution.root,
                            )
                        )
                else:
                    log.warning(
                        "gateway conn %d sent non-gateway message %s; closing",
                        conn_id,
                        type(msg).__name__,
                    )
                    break
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        except Exception:  # noqa: BLE001 - malformed client input: drop the conn
            log.warning("gateway conn %d failed; closing", conn_id, exc_info=True)
        finally:
            self.connections -= 1
            if self.plane.metrics is not None:
                self.plane.metrics.mysticeti_ingress_gateway_clients.set(
                    self.connections
                )
            if sink is not None:
                self.plane.remove_commit_sink(sink)
            writer_task.cancel()
            writer.close()


# ---------------------------------------------------------------------------
# Deterministic overload simulation (the OVERLOAD scenario tier)


@dataclass
class OverloadScenario:
    """Declarative seeded overload run on the virtual-time simulator.

    ``multiplier_schedule`` is ``[(t_offset_s, multiplier), ...]`` over
    ``base_tps`` — the offered-load ramp.  The small ``max_per_proposal``
    reproduces saturation in virtual time (the simulator does not model
    host CPU, so per-proposal capacity is the binding resource, exactly as
    SOFT_MAX is on a real fleet)."""

    seed: int = 0
    nodes: int = 10
    duration_s: float = 15.0
    base_tps: int = 150
    multiplier_schedule: List[Tuple[float, float]] = field(
        default_factory=lambda: [(0.0, 1.0)]
    )
    closed_loop: bool = False
    transaction_size: int = 32
    max_per_proposal: int = 50
    mempool_max_transactions: int = 1500
    leader_timeout_s: float = 1.0
    # Fairness: split each node's offered load across this many distinct
    # client lanes (1 = the handler's own "local" lane).
    clients_per_node: int = 1
    # Dedup: when True, every node also hosts a client that re-submits the
    # SAME batch forever — only its first submission is fresh, the rest must
    # shed as duplicates.
    duplicate_flood: bool = False

    def ingress_parameters(self) -> IngressParameters:
        return IngressParameters(
            mempool_max_transactions=self.mempool_max_transactions,
            mempool_max_bytes=self.mempool_max_transactions
            * max(self.transaction_size, 64),
            lane_max_transactions=self.mempool_max_transactions,
            max_per_proposal=self.max_per_proposal,
            admission_initial_tx_s=float(self.base_tps * 4),
            admission_min_tx_s=float(max(self.base_tps // 4, 10)),
            admission_additive_tx_s=float(max(self.base_tps // 10, 5)),
            tick_interval_s=0.5,
        )

    def to_dict(self) -> dict:
        return {
            "seed": self.seed,
            "nodes": self.nodes,
            "duration_s": self.duration_s,
            "base_tps": self.base_tps,
            "multiplier_schedule": [list(m) for m in self.multiplier_schedule],
            "closed_loop": self.closed_loop,
            "transaction_size": self.transaction_size,
            "max_per_proposal": self.max_per_proposal,
            "mempool_max_transactions": self.mempool_max_transactions,
            "leader_timeout_s": self.leader_timeout_s,
            "clients_per_node": self.clients_per_node,
            "duplicate_flood": self.duplicate_flood,
        }


@dataclass
class OverloadReport:
    """What an overload scenario pins: throughput, full shed accounting,
    fairness, and the deterministic shed schedule."""

    committed_tx: int
    committed_tx_s: float
    offered_tx: int
    admitted_tx: int
    shed_by_reason: Dict[str, int]
    shed_log_bytes: bytes
    shed_schedule_digest: str
    lane_stats: Dict[str, dict]
    commit_heights: Dict[int, int]
    generator_stats: Dict[str, dict]
    shed_mode_entered: bool
    # Finality SLI plane (defaults keep older constructors working):
    # fleet-merged server-side submit→finalized and client-observed
    # submit→notification percentiles over the sampled keys.
    server_finality: Dict[str, float] = field(default_factory=dict)
    client_finality: Dict[str, float] = field(default_factory=dict)


def run_overload_sim(scenario: OverloadScenario) -> OverloadReport:
    """Run one seeded overload scenario to completion on a fresh
    DeterministicLoop; commit safety is audited by the chaos tier's
    :class:`~mysticeti_tpu.chaos.SafetyChecker` (prefix consistency across
    the fleet survives overload)."""
    import os
    import shutil
    import tempfile

    from .block_handler import BenchmarkFastPathBlockHandler
    from .block_store import BlockStore
    from .chaos import SafetyChecker, _SimNodeNetwork
    from .commit_observer import TestCommitObserver
    from .committee import Committee
    from .config import Parameters
    from .core import Core, CoreOptions
    from .net_sync import NetworkSyncer
    from .runtime.simulated import run_simulation
    from .simulated_network import SimulatedNetwork
    from .transactions_generator import TransactionGenerator
    from .types import Share
    from .wal import walf

    n = scenario.nodes
    committee = Committee.new_test([1] * n)
    signers = Committee.benchmark_signers(n)
    parameters = Parameters(leader_timeout_s=scenario.leader_timeout_s)
    checker = SafetyChecker()
    share_counts: Dict[int, int] = {a: 0 for a in range(n)}

    class _CountingObserver(TestCommitObserver):
        """Counts committed Share statements per node, feeds the ingress
        commit hook and the cross-node safety audit."""

        def __init__(self, authority, plane, *args, **kwargs):
            super().__init__(*args, **kwargs)
            self._authority = authority
            self._plane = plane

        def handle_commit(self, committed_leaders):
            committed = super().handle_commit(committed_leaders)
            for commit in committed:
                for block in commit.blocks:
                    share_counts[self._authority] += sum(
                        1 for st in block.statements if isinstance(st, Share)
                    )
            self._plane.note_committed(committed)
            checker.observe(self._authority, committed)
            return committed

    tmp_dir = tempfile.mkdtemp(prefix="overload-sim-")
    planes: List[IngressPlane] = []
    generators: Dict[str, TransactionGenerator] = {}
    nodes: List[NetworkSyncer] = []
    flood_tasks: List[asyncio.Task] = []
    flood_offered = [0]  # offered-load ledger for the duplicate flooders

    async def _duplicate_flood(plane: IngressPlane, seed: int) -> None:
        """Re-submit one fixed batch forever: everything past the first
        submission must shed as duplicate."""
        import random as _random

        rng = _random.Random(seed)
        batch = [
            rng.getrandbits(64).to_bytes(8, "little")
            * (scenario.transaction_size // 8)
            for _ in range(10)
        ]
        while True:
            plane.submit("flooder", batch)
            flood_offered[0] += len(batch)
            await asyncio.sleep(0.5)

    async def main() -> None:
        sim_net = SimulatedNetwork(n)
        for authority in range(n):
            # Synchronous WAL: the async writer's drain THREAD runs in
            # wall-clock time, and the admission controller observes its
            # progress through the wal_backlog signal — with async writes
            # a seeded virtual-time run would absorb real thread timing
            # and the committed sequence would drift across same-seed runs.
            wal_writer, wal_reader = walf(
                os.path.join(tmp_dir, f"wal-{authority}"), async_writes=False
            )
            recovered, observer_recovered = BlockStore.open(
                authority, wal_reader, wal_writer, committee
            )
            plane = IngressPlane(
                scenario.ingress_parameters(), authority=authority
            )
            handler = BenchmarkFastPathBlockHandler(
                committee, authority, ingress=plane
            )
            core = Core(
                block_handler=handler,
                authority=authority,
                committee=committee,
                parameters=parameters,
                recovered=recovered,
                wal_writer=wal_writer,
                options=CoreOptions.test(),
                signer=signers[authority],
            )
            observer = _CountingObserver(
                authority,
                plane,
                core.block_store,
                committee,
                transaction_time=handler.transaction_time,
                recovered_state=observer_recovered,
            )

            node = NetworkSyncer(
                core,
                observer,
                _SimNodeNetwork(sim_net.node_connections[authority]),
                parameters=parameters,
            )
            plane.attach(core=core, net_syncer=node)
            clients = max(1, scenario.clients_per_node)
            for i in range(clients):
                if clients == 1:
                    submit_fn = handler.submit
                    name = f"a{authority}/local"
                else:
                    submit_fn = (
                        lambda txs, p=plane, c=f"client-{i}": p.submit(c, txs)
                    )
                    name = f"a{authority}/client-{i}"
                generator = TransactionGenerator(
                    submit=submit_fn,
                    seed=scenario.seed * 1000 + authority * 16 + i,
                    tps=max(1, scenario.base_tps // clients),
                    transaction_size=scenario.transaction_size,
                    overload_schedule=list(scenario.multiplier_schedule),
                    closed_loop=scenario.closed_loop,
                    finality_sample_every=(
                        scenario.ingress_parameters().finality_sample_every
                    ),
                )
                generators[name] = generator
                # Client-observed finality: this node's commit stream
                # closes the client's sampled submit stamps (the sim's
                # stand-in for a gateway subscription).
                plane.add_commit_sink(
                    lambda height, keys, info, g=generator: (
                        g.note_commit_notification(keys, info)
                    )
                )
            planes.append(plane)
            nodes.append(node)
        for node in nodes:
            await node.start()
        await sim_net.connect_all()
        for authority, plane in enumerate(planes):
            plane.start()
            if scenario.duplicate_flood:
                flood_tasks.append(
                    spawn_logged(
                        _duplicate_flood(
                            plane, scenario.seed * 7919 + authority
                        ),
                        log,
                        name=f"dup-flood-{authority}",
                    )
                )
        for generator in generators.values():
            generator.start()
        await asyncio.sleep(scenario.duration_s)
        for task in flood_tasks:
            task.cancel()
        for generator in generators.values():
            generator.stop()
        for plane in planes:
            plane.stop()
        for node in nodes:
            await node.stop()
            node.core.wal_writer.close()
            node.core.block_store.close()
        sim_net.close()

    try:
        run_simulation(main(), seed=scenario.seed)
    finally:
        # The per-node WAL segments are scratch: every sim (CLI, bench
        # determinism leg, tier-1 tests) would otherwise leave an
        # overload-sim-* directory in /tmp forever.
        shutil.rmtree(tmp_dir, ignore_errors=True)
    checker.check()
    shed_by_reason: Dict[str, int] = {}
    for plane in planes:
        for reason, count in plane.shed_by_reason.items():
            shed_by_reason[reason] = shed_by_reason.get(reason, 0) + count
    offered = sum(g.submitted for g in generators.values()) + flood_offered[0]
    admitted = sum(p.admitted_total for p in planes)
    committed = share_counts[0]
    return OverloadReport(
        committed_tx=committed,
        committed_tx_s=round(committed / scenario.duration_s, 3),
        offered_tx=offered,
        admitted_tx=admitted,
        shed_by_reason=shed_by_reason,
        shed_log_bytes=planes[0].shed_log_bytes(),
        shed_schedule_digest=planes[0].shed_schedule_digest(),
        lane_stats=planes[0].mempool.lane_stats(),
        commit_heights={
            a: checker.committed_height(a) for a in range(n)
        },
        generator_stats={
            name: gen.stats() for name, gen in sorted(generators.items())
        },
        shed_mode_entered=any(
            entry["reason"] == SHED_ADMISSION
            for plane in planes
            for entry in plane.shed_log
        )
        or any(p.controller.shed_mode for p in planes),
        server_finality=_merged_finality(
            [p.finality for p in planes if p.finality is not None]
        ),
        client_finality=_merged_finality(
            [g.finality for g in generators.values() if g.finality is not None]
        ),
    )


def _merged_finality(trackers) -> Dict[str, float]:
    """Fleet-merged finality percentiles over every tracker's recent
    samples (server planes or client recorders — both expose samples())."""
    from .finality import percentile

    samples: List[float] = []
    completed = 0
    for tracker in trackers:
        samples.extend(tracker.samples())
        completed += tracker.completed
    return {
        "p50_s": round(percentile(samples, 0.50), 6),
        "p99_s": round(percentile(samples, 0.99), 6),
        "samples": len(samples),
        "completed": completed,
    }
