"""In-memory network with seeded random latency and partition control.

Capability parity with ``mysticeti-core/src/simulated_network.rs``: connection
pairs among all committee members with 50-100 ms one-way latency injected per
message (:14-95), plus explicit partition/heal control used by the partition
sim-test (net_sync.rs:753-780).

Drop-in for :class:`mysticeti_tpu.network.TcpNetwork`: exposes the same
``connections`` queue of :class:`Connection` objects.  Message delivery is a
``loop.call_later`` on the DeterministicLoop, so ordering is reproducible by
seed.

Broadcast-once parity: dissemination streams enqueue
:class:`~mysticeti_tpu.network.EncodedFrame` wrappers (encode-once
fan-out).  The pumps move them verbatim — the payload property is lazy, so
a simulation never pays for serialization — and ``Connection.recv`` unwraps
to the message on the receiving side; fault injectors see one object per
message exactly as before, keeping same-seed fault logs byte-identical.
"""
from __future__ import annotations

import asyncio
from typing import Dict, List, Optional, Set, Tuple

from .network import Connection, NetworkMessage
from .tracing import logger
from .utils.tasks import spawn_logged

log = logger(__name__)


class SimulatedNetwork:
    LATENCY_RANGE = (0.050, 0.100)  # one-way seconds (simulated_network.rs:20)

    def __init__(self, num_authorities: int, latency_ranges=None) -> None:
        self.n = num_authorities
        # Geo-latency profile (scenario matrix): optional per-directed-link
        # (src, dst) -> (lo, hi) one-way latency ranges; links not named
        # fall back to LATENCY_RANGE.  Draws still come from the loop RNG
        # in delivery order, so a profiled sim stays seed-reproducible.
        self.latency_ranges = latency_ranges or {}
        # per-node queue of fresh connections (what TcpNetwork.connections is).
        self.node_connections: List[asyncio.Queue] = [
            asyncio.Queue() for _ in range(num_authorities)
        ]
        self._links: Dict[Tuple[int, int], tuple] = {}  # (ca, cb, pump_a, pump_b)
        self._severed: Set[Tuple[int, int]] = set()
        self._down: Set[int] = set()
        # Chaos seam (chaos.py): when set, every src->dst batch is routed
        # through ``filter_batch(src, dst, batch) -> [(extra_delay_s,
        # messages), ...]`` which may drop, duplicate, or delay individual
        # messages.  None = faithful delivery (one group, zero extra delay).
        self.fault_injector = None

    async def connect_all(self) -> None:
        for a in range(self.n):
            for b in range(a + 1, self.n):
                await self._connect_pair(a, b)

    async def _connect_pair(self, a: int, b: int) -> None:
        ca = Connection(b)  # a's handle, peer=b
        cb = Connection(a)
        pump_a = spawn_logged(self._pump(a, b, ca, cb), log, name=f"pump {a}->{b}")
        pump_b = spawn_logged(self._pump(b, a, cb, ca), log, name=f"pump {b}->{a}")
        self._links[(a, b)] = (ca, cb, pump_a, pump_b)
        await self.node_connections[a].put(ca)
        await self.node_connections[b].put(cb)

    def _latency(self, src: int = -1, dst: int = -1) -> float:
        loop = asyncio.get_event_loop()
        rng = getattr(loop, "rng", None)
        lo, hi = self.latency_ranges.get((src, dst), self.LATENCY_RANGE)
        if rng is None:
            import random

            # Reached only on a loop without a seeded .rng — i.e. a real
            # event loop, which is nondeterministic anyway; DeterministicLoop
            # always carries one.
            return random.uniform(lo, hi)  # lint: ignore[sim-taint]
        return rng.uniform(lo, hi)

    async def _pump(self, src: int, dst: int, c_src: Connection, c_dst: Connection):
        """Move messages src->dst with latency.

        Messages already queued together ride ONE timer with one latency
        draw (a burst sent back-to-back arrives back-to-back — the same
        in-order, latency-delayed semantics), which cuts the simulator's
        scheduler events per message several-fold: at 50 authorities the
        per-message timer/task churn, not the consensus logic, dominated
        the wall clock."""
        loop = asyncio.get_event_loop()
        while not c_src.is_closed():
            batch = [await c_src.sender.get()]
            while True:
                try:
                    batch.append(c_src.sender.get_nowait())
                except asyncio.QueueEmpty:
                    break

            injector = self.fault_injector
            groups = (
                [(0.0, batch)]
                if injector is None
                else injector.filter_batch(src, dst, batch)
            )
            if not groups:
                continue
            base_latency = self._latency(src, dst)
            for extra_delay, messages in groups:
                if not messages:
                    continue

                def deliver(ms=messages):
                    if not c_dst.is_closed():
                        for m in ms:
                            try:
                                c_dst.receiver.put_nowait(m)
                            except asyncio.QueueFull:
                                break

                loop.call_later(base_latency + extra_delay, deliver)

    # -- fault injection --

    def _sever(self, a: int, b: int) -> None:
        key = (min(a, b), max(a, b))
        link = self._links.pop(key, None)
        if link is None:
            return
        ca, cb, pump_a, pump_b = link
        ca.close()
        cb.close()
        pump_a.cancel()
        pump_b.cancel()
        self._severed.add(key)

    def partition(self, group_a: List[int], group_b: List[int]) -> None:
        """Cut all links between the two groups.  Like a real partition over
        TCP, the connections BREAK (peers see closure) — healing re-establishes
        them, which re-runs the subscribe/catch-up path (net_sync.rs:753-780)."""
        for a in group_a:
            for b in group_b:
                self._sever(a, b)

    def isolate(self, node: int) -> None:
        self.partition([node], [i for i in range(self.n) if i != node])

    def crash(self, node: int) -> None:
        """Take a node off the network abruptly: every link breaks (peers
        observe closure mid-protocol) and queued-but-unaccepted fresh
        connections are discarded, so a restarted node's accept loop only
        ever sees post-restart connections."""
        self._down.add(node)
        for peer in range(self.n):
            if peer != node:
                self._sever(node, peer)
        queue = self.node_connections[node]
        while True:
            try:
                queue.get_nowait()
            except asyncio.QueueEmpty:
                break

    async def restart(self, node: int) -> None:
        """Bring a crashed node back: re-establish links to every live peer
        (both ends receive fresh Connection objects, re-running the
        subscribe/catch-up path exactly like a healed partition)."""
        self._down.discard(node)
        for key in sorted(k for k in self._severed if node in k):
            a, b = key
            other = b if a == node else a
            if other in self._down:
                continue
            self._severed.discard(key)
            await self._connect_pair(a, b)

    async def heal(self) -> None:
        """Reconnect every severed pair (the reconnect-forever workers' job in
        the real transport, network.rs:218-242).  Pairs touching a crashed
        node stay severed until that node restarts."""
        severed, self._severed = self._severed, set()
        for a, b in sorted(severed):
            if a in self._down or b in self._down:
                self._severed.add((a, b))
                continue
            await self._connect_pair(a, b)

    def close(self) -> None:
        for ca, cb, pump_a, pump_b in self._links.values():
            ca.close()
            cb.close()
            pump_a.cancel()
            pump_b.cancel()
