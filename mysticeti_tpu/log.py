"""Append-only text log of processed transaction locators.

Capability parity with ``mysticeti-core/src/log.rs``: a ``TransactionLog`` opened
for write that records each certified/committed locator on its own line
(log.rs:10-33).  The reference offloads writes to a blocking tokio pool; here a
buffered writer + explicit flush keeps the consensus owner task non-blocking in
practice (page-cache writes), and the bench harness reads the file back for the
safety cross-checks.
"""
from __future__ import annotations

import io
from typing import Iterable, List

from .types import TransactionLocator


class TransactionLog:
    """File-backed sink usable as a TransactionAggregator handler hook."""

    __slots__ = ("_file", "_last_block", "_last_prefix")

    def __init__(self, path: str) -> None:
        self._file = open(path, "a", buffering=1 << 16)
        self._last_block = None
        self._last_prefix = ""

    @classmethod
    def start(cls, path: str) -> "TransactionLog":
        return cls(path)

    def log(self, locator: TransactionLocator) -> None:
        # Certified locators arrive in per-block runs; hex-encoding the digest
        # once per block (not per transaction) halves this hook's cost at load.
        blk = locator.block
        if blk is not self._last_block:
            self._last_block = blk
            self._last_prefix = f"{blk.authority},{blk.round},{blk.digest.hex()},"
        self._file.write(f"{self._last_prefix}{locator.offset}\n")

    def log_all(self, locators: Iterable[TransactionLocator]) -> None:
        for loc in locators:
            self.log(loc)

    def log_range(self, block, start: int, end: int) -> None:
        """Bulk form: one line per offset, identical format to ``log`` —
        certification arrives in contiguous runs and the per-line method
        call + f-string was measurable at fleet saturation."""
        if start >= end:
            return
        prefix = f"{block.authority},{block.round},{block.digest.hex()},"
        self._last_block = block
        self._last_prefix = prefix
        # map(str, range) keeps the per-offset work in C: a per-line
        # f-string re-rendered the constant prefix 1.4M times per
        # measurement window at saturation.
        self._file.write(
            prefix
            + ("\n" + prefix).join(map(str, range(start, end)))
            + "\n"
        )

    def flush(self) -> None:
        self._file.flush()

    def close(self) -> None:
        self._file.close()

    @staticmethod
    def read_locators(path: str) -> List[TransactionLocator]:
        from .types import BlockReference

        out = []
        with open(path) as f:
            for line in f:
                if not line.strip():
                    continue
                a, r, d, off = line.strip().split(",")
                out.append(
                    TransactionLocator(
                        BlockReference(int(a), int(r), bytes.fromhex(d)), int(off)
                    )
                )
        return out
