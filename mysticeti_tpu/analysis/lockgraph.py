"""Lock-order analysis and guarded-field inference.

Two rules over the package's lock landscape:

``lock-order``
    Build the lock *acquisition graph*: an edge ``A -> B`` whenever a
    ``with self.B:`` is entered while ``self.A`` is already held — either
    by direct syntactic nesting or one call level deep (``self.m()``
    invoked under ``A`` where ``m`` acquires ``B``).  Edges merge across
    the whole package; any cycle is a deadlock-capable ordering and
    fails the gate.  Lock identities are class-qualified
    (``ClassName._lock``) so same-named locks on unrelated classes never
    alias.

``guard-inference``
    Infer which fields a class *intends* to guard: a field written under
    the same ``self.<lock>`` at two or more sites is treated as guarded
    by that lock, and any stray write outside it (construction excluded)
    is reported.  This demotes the hand-maintained ``GUARDED_FIELDS``
    registry in checker.py from the *source of truth* to *confirmed
    annotations*: registry entries keep their stricter any-write
    enforcement (rule ``lock-discipline``), every other field gets the
    inferred discipline automatically, and a registry entry that the
    code no longer exhibits (no guarded write of that field anywhere in
    the package) is flagged as a stale annotation so the registry cannot
    drift from the code it describes.

Both analyses are intentionally intra-class: a lock attribute lives on
``self``, so every acquisition that can nest with it is a method (or a
one-level ``self.`` call) of the same class.  Deliberate exceptions take
``# lint: ignore[lock-order]`` / ``# lint: ignore[guard-inference]``
with a justification, like every rule in this package.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

RULE_LOCK_ORDER = "lock-order"
RULE_GUARD_INFERENCE = "guard-inference"

# ``# lint: holds[_lock]`` on (or directly above) a ``def``: every caller
# holds ``self._lock`` for the duration of the call — the method's writes
# are censused as guarded by it.  The annotation is a *contract*, the
# same demotion as GUARDED_FIELDS: stated in one place, checked
# everywhere the census runs.
_HOLDS_RE = re.compile(r"#\s*lint:\s*holds\[([A-Za-z0-9_,\s]+)\]")

# A field is considered intentionally guarded once this many distinct
# write sites hold the same lock.  One site is ambient (the write may be
# inside the lock for unrelated reasons); two is a pattern.
MIN_GUARDED_SITES = 2

_CONSTRUCTOR_METHODS = frozenset({"__init__", "__post_init__", "__new__"})

_LOCK_CONSTRUCTORS = frozenset(
    {
        "threading.Lock",
        "threading.RLock",
        "threading.Condition",
        "asyncio.Lock",
        "asyncio.Condition",
    }
)


@dataclass(frozen=True)
class LockEdge:
    """``held`` was held when ``acquired`` was taken (class-qualified)."""

    held: str
    acquired: str
    path: str
    line: int


@dataclass(frozen=True)
class GuardFinding:
    line: int
    col: int
    message: str


@dataclass
class FieldWrites:
    """Per (class, field) write census."""

    # lock attr -> number of write sites holding it
    guarded: Dict[str, int] = field(default_factory=dict)
    # (line, col, held locks at the site)
    sites: List[Tuple[int, int, frozenset]] = field(default_factory=list)
    # locks observed held at *any* access of the field (incl. reads and
    # mutating method calls like ``self._ring.append(...)``) — used to
    # confirm GUARDED_FIELDS annotations, not to report strays
    touched: Set[str] = field(default_factory=set)


@dataclass
class ModuleLocks:
    """Everything analyze_paths needs from one module."""

    edges: List[LockEdge] = field(default_factory=list)
    # (class name, field) -> census
    writes: Dict[Tuple[str, str], FieldWrites] = field(default_factory=dict)


def _class_lock_attrs(cls: ast.ClassDef, aliases: Dict[str, str]) -> Set[str]:
    from .checker import _dotted  # local import: avoid cycle at module load

    locks: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        if _dotted(node.value.func, aliases) not in _LOCK_CONSTRUCTORS:
            continue
        for target in node.targets:
            if (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                locks.add(target.attr)
    return locks


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _write_target_attr(node: ast.AST) -> Optional[str]:
    """Resolve a store target to its base ``self.<attr>``.

    ``self._f = v`` and ``self._f[k] = v`` / ``self._f[k][j] += v`` all
    mutate what ``self._f`` guards.
    """
    while isinstance(node, ast.Subscript):
        node = node.value
    return _self_attr(node)


class _MethodWalk:
    """One method: acquisitions, self-calls under locks, field writes."""

    def __init__(
        self, locks: Set[str], assumed_held: Tuple[str, ...] = ()
    ) -> None:
        self.locks = locks
        self.held: List[str] = list(assumed_held)
        # (held-before tuple, acquired, line)
        self.acquisitions: List[Tuple[Tuple[str, ...], str, int]] = []
        # (held tuple, callee name, line)
        self.calls_under: List[Tuple[Tuple[str, ...], str, int]] = []
        # (field, line, col, held frozenset)
        self.writes: List[Tuple[str, int, int, frozenset]] = []
        # (field, held frozenset) for any access while a lock is held
        self.touches: List[Tuple[str, frozenset]] = []
        self.acquired_anywhere: Set[str] = set()

    def walk(self, stmts: Iterable[ast.stmt]) -> None:
        for stmt in stmts:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.ClassDef):
            return  # nested classes analyzed on their own
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # A closure does NOT run under the locks held at its
            # definition site — walk it with a fresh stack, but keep its
            # own acquisitions/writes in this method's census (the
            # dispatch-EMA update lives in exactly such a callback).
            sub = _MethodWalk(self.locks)
            sub.walk(stmt.body)
            self.acquisitions.extend(sub.acquisitions)
            self.calls_under.extend(sub.calls_under)
            self.writes.extend(sub.writes)
            self.touches.extend(sub.touches)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in stmt.items:
                self._scan_expr(item.context_expr)
                attr = _self_attr(item.context_expr)
                if attr is not None and attr in self.locks:
                    self.acquisitions.append((tuple(self.held), attr, stmt.lineno))
                    self.acquired_anywhere.add(attr)
                    self.held.append(attr)
                    pushed += 1
            for sub in stmt.body:
                self._stmt(sub)
            for _ in range(pushed):
                self.held.pop()
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = getattr(stmt, "value", None)
            if value is not None:
                self._scan_expr(value)
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            )
            for target in targets:
                attr = _write_target_attr(target)
                if attr is not None and attr not in self.locks:
                    self.writes.append(
                        (attr, target.lineno, target.col_offset, frozenset(self.held))
                    )
                    if self.held:
                        self.touches.append((attr, frozenset(self.held)))
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                self._stmt(child)
            else:
                self._scan_expr(child)

    def _scan_expr(self, expr: ast.AST) -> None:
        if not self.held:
            return
        held = frozenset(self.held)
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                attr = _self_attr(node.func)
                if attr is not None:
                    self.calls_under.append((tuple(self.held), attr, node.lineno))
            attr = _self_attr(node)
            if attr is not None and attr not in self.locks:
                self.touches.append((attr, held))


def holds_annotations(source: str) -> Dict[int, Tuple[str, ...]]:
    """line -> lock attrs named by a ``# lint: holds[...]`` comment."""
    from .checker import comment_lines

    out: Dict[int, Tuple[str, ...]] = {}
    for i, line in comment_lines(source).items():
        m = _HOLDS_RE.search(line)
        if m:
            out[i] = tuple(
                part.strip() for part in m.group(1).split(",") if part.strip()
            )
    return out


def collect_module_locks(
    tree: ast.AST, aliases: Dict[str, str], path: str, source: str = ""
) -> ModuleLocks:
    """Lock acquisition edges + field-write census for one module."""
    out = ModuleLocks()
    holds = holds_annotations(source) if source else {}
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = _class_lock_attrs(cls, aliases)
        method_walks: Dict[str, _MethodWalk] = {}
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            assumed = holds.get(fn.lineno) or holds.get(fn.lineno - 1) or ()
            walk = _MethodWalk(locks, assumed_held=assumed)
            walk.walk(fn.body)
            method_walks[fn.name] = walk
            if fn.name not in _CONSTRUCTOR_METHODS:
                for attr, line, col, held in walk.writes:
                    census = out.writes.setdefault((cls.name, attr), FieldWrites())
                    census.sites.append((line, col, held))
                    for lock in held:
                        census.guarded[lock] = census.guarded.get(lock, 0) + 1
                for attr, held in walk.touches:
                    census = out.writes.setdefault((cls.name, attr), FieldWrites())
                    census.touched.update(held)
        qual = lambda lock: f"{cls.name}.{lock}"  # noqa: E731
        for walk in method_walks.values():
            for held_before, acquired, line in walk.acquisitions:
                for held in held_before:
                    out.edges.append(
                        LockEdge(qual(held), qual(acquired), path, line)
                    )
            # One call level deep: self.m() under A, where m acquires B.
            for held_tuple, callee, line in walk.calls_under:
                target = method_walks.get(callee)
                if target is None:
                    continue
                for acquired in sorted(target.acquired_anywhere):
                    for held in held_tuple:
                        if held != acquired:
                            out.edges.append(
                                LockEdge(qual(held), qual(acquired), path, line)
                            )
    return out


def check_guard_inference(
    module: ModuleLocks, annotated: Dict[str, str]
) -> List[GuardFinding]:
    """Stray unguarded writes to inferred-guarded fields (one module).

    ``annotated`` is the GUARDED_FIELDS registry: those fields already
    carry the stricter lock-discipline enforcement, so inference skips
    them here (the repo-level stale-annotation check covers the reverse
    direction).
    """
    findings: List[GuardFinding] = []
    for (cls_name, attr), census in sorted(module.writes.items()):
        if attr in annotated or not census.guarded:
            continue
        lock, guarded_sites = max(
            census.guarded.items(), key=lambda kv: (kv[1], kv[0])
        )
        if guarded_sites < MIN_GUARDED_SITES:
            continue
        for line, col, held in census.sites:
            if lock in held:
                continue
            others = ", ".join(sorted(held)) or "no lock"
            findings.append(
                GuardFinding(
                    line=line,
                    col=col,
                    message=(
                        f"self.{attr} ({cls_name}) is written under "
                        f"self.{lock} at {guarded_sites} site(s) but here "
                        f"under {others} — a concurrent holder of "
                        f"self.{lock} races this write; guard it, or add "
                        "the field to GUARDED_FIELDS with a justification "
                        "if the discipline is intentional"
                    ),
                )
            )
    findings.sort(key=lambda f: (f.line, f.col))
    return findings


def find_lock_cycles(edges: Iterable[LockEdge]) -> List[List[LockEdge]]:
    """Cycles in the merged acquisition graph (each as its edge list)."""
    graph: Dict[str, Dict[str, LockEdge]] = {}
    for edge in edges:
        graph.setdefault(edge.held, {}).setdefault(edge.acquired, edge)

    cycles: List[List[LockEdge]] = []
    seen_cycles: Set[Tuple[str, ...]] = set()
    visiting: List[str] = []
    done: Set[str] = set()

    def dfs(node: str) -> None:
        if node in done:
            return
        if node in visiting:
            i = visiting.index(node)
            members = visiting[i:]
            key = tuple(sorted(members))
            if key not in seen_cycles:
                seen_cycles.add(key)
                cycle_edges = [
                    graph[members[j]][members[(j + 1) % len(members)]]
                    for j in range(len(members))
                ]
                cycles.append(cycle_edges)
            return
        visiting.append(node)
        for nxt in sorted(graph.get(node, ())):
            dfs(nxt)
        visiting.pop()
        done.add(node)

    for node in sorted(graph):
        dfs(node)
    return cycles


def lock_order_messages(cycles: List[List[LockEdge]]) -> List[Tuple[str, int, str]]:
    """(path, line, message) per cycle, anchored at its first edge."""
    out: List[Tuple[str, int, str]] = []
    for cycle_edges in cycles:
        ring = " -> ".join(e.held for e in cycle_edges)
        ring += f" -> {cycle_edges[0].held}"
        sites = "; ".join(
            f"{e.held} then {e.acquired} at {e.path}:{e.line}" for e in cycle_edges
        )
        anchor = cycle_edges[0]
        out.append(
            (
                anchor.path,
                anchor.line,
                (
                    f"lock acquisition cycle {ring} — two threads entering "
                    "the ring from different edges deadlock; acquire in one "
                    f"global order ({sites})"
                ),
            )
        )
    return out


def stale_annotations(
    modules: Iterable[ModuleLocks], annotated: Dict[str, str]
) -> List[Tuple[str, str, str]]:
    """GUARDED_FIELDS entries with no guarded write anywhere: (field, lock, msg)."""
    observed: Set[Tuple[str, str]] = set()
    written: Set[str] = set()
    for module in modules:
        for (_cls, attr), census in module.writes.items():
            if census.sites:
                written.add(attr)
            for lock in census.guarded:
                observed.add((attr, lock))
            for lock in census.touched:
                observed.add((attr, lock))
    out: List[Tuple[str, str, str]] = []
    for attr, lock in sorted(annotated.items()):
        if (attr, lock) in observed:
            continue
        reason = (
            "is never written under it outside construction"
            if attr in written
            else "is never written at all outside construction"
        )
        out.append(
            (
                attr,
                lock,
                (
                    f"GUARDED_FIELDS annotates self.{attr} with self.{lock} "
                    f"but the field {reason} — the annotation is stale; "
                    "update or remove it so the registry keeps matching the "
                    "code it describes"
                ),
            )
        )
    return out
